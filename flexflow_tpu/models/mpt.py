"""MPT model family (reference ``inference/models/mpt.cc`` and
``python/flexflow/serve/models/mpt.py``): ALiBi attention bias (no
positional embeddings), bias-free LayerNorm, un-biased MHA + GELU FFN,
tied LM head. Runs on the generic decoder (:mod:`.transformer`); the
ALiBi path adds a per-line position buffer to the KV cache so serving
bias is computed against true key positions (see
``transformer.needs_pos_cache``)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=50368,
        hidden_size=4096,
        intermediate_size=4 * 4096,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=32,
        max_position_embeddings=2048,
        norm_type="layernorm",
        norm_bias=False,
        norm_eps=1e-5,
        positions="alibi",
        activation="gelu",
        glu=False,
        parallel_block=False,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=True,
    )
    d.update(kw)
    return DecoderConfig(**d)


def mpt_7b(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["d_model"],
        intermediate_size=hf.get("expansion_ratio", 4) * hf["d_model"],
        num_hidden_layers=hf["n_layers"],
        num_attention_heads=hf["n_heads"],
        num_key_value_heads=hf["n_heads"],
        max_position_embeddings=hf.get("max_seq_len", 2048),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(sd: Dict[str, Any], cfg: DecoderConfig) -> Dict[str, Any]:
    """HF ``MptForCausalLM`` state dict → framework pytree. The fused
    ``Wqkv`` (3D, D) splits into equal Q/K/V thirds."""
    dt = cfg.dtype
    pre = "transformer."
    L = cfg.num_hidden_layers
    D = cfg.hidden_size

    wq, wk, wv = [], [], []
    for i in range(L):
        w = linear_w(sd, f"{pre}blocks.{i}.attn.Wqkv.weight")  # (D, 3D)
        wq.append(w[:, :D])
        wk.append(w[:, D : 2 * D])
        wv.append(w[:, 2 * D :])

    def vec(fmt):
        return stack([to_np(sd[pre + fmt.format(i)]) for i in range(L)], dt)

    layers = {
        "attn_norm_scale": vec("blocks.{}.norm_1.weight"),
        "wq": stack(wq, dt),
        "wk": stack(wk, dt),
        "wv": stack(wv, dt),
        "wo": stack(
            [linear_w(sd, f"{pre}blocks.{i}.attn.out_proj.weight") for i in range(L)], dt
        ),
        "mlp_norm_scale": vec("blocks.{}.norm_2.weight"),
        "w_up": stack(
            [linear_w(sd, f"{pre}blocks.{i}.ffn.up_proj.weight") for i in range(L)], dt
        ),
        "w_down": stack(
            [linear_w(sd, f"{pre}blocks.{i}.ffn.down_proj.weight") for i in range(L)], dt
        ),
    }
    return {
        "embed": jnp.asarray(to_np(sd[pre + "wte.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "norm_f.weight"]), dt),
    }
