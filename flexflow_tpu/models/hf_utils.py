"""HuggingFace weight conversion utilities.

The reference converts HF checkpoints into per-layer binary files and
loads them partition-aware at startup (reference ``python/flexflow/serve/
serve.py:167-227`` download/convert, ``inference/file_loader.cc:651-819``
shard-aware load). The TPU-native pipeline is simpler: read the HF
state dict (safetensors / torch .bin from a *local* directory — this
environment has no network egress), map names into the framework's
stacked-layer pytree, and `jax.device_put` with the model's
NamedShardings — XLA lays out the shards, no manual head slicing.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def to_np(x) -> np.ndarray:
    """torch.Tensor | np.ndarray → np.ndarray (f32 for float types)."""
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().to("cpu")
        try:
            import torch

            if x.dtype in (torch.bfloat16, torch.float16):
                x = x.float()
        except ImportError:
            pass
        x = x.numpy()
    return np.asarray(x)


def linear_w(sd: Dict[str, Any], name: str) -> np.ndarray:
    """HF Linear stores (out, in); the framework right-multiplies, so
    transpose to (in, out)."""
    return to_np(sd[name]).T


def stack(arrs: List[np.ndarray], dtype) -> jnp.ndarray:
    return jnp.asarray(np.stack(arrs, axis=0), dtype=dtype)


def layer_stackers(sd: Dict[str, Any], pre: str, num_layers: int, dtype):
    """(mats, vecs) helpers shared by the family converters: stack a
    per-layer HF tensor name pattern into one (L, ...) array — ``mats``
    transposes Linear weights to (in, out), ``vecs`` takes them raw."""

    def mats(fmt):
        return stack(
            [linear_w(sd, pre + fmt.format(i)) for i in range(num_layers)],
            dtype,
        )

    def vecs(fmt):
        return stack(
            [to_np(sd[pre + fmt.format(i)]) for i in range(num_layers)],
            dtype,
        )

    return mats, vecs


def load_state_dict(model_dir: str) -> Dict[str, np.ndarray]:
    """Load all weights from a local HF checkpoint directory
    (*.safetensors preferred, falling back to pytorch_model*.bin)."""
    sd: Dict[str, np.ndarray] = {}
    st_files = sorted(
        f for f in os.listdir(model_dir) if f.endswith(".safetensors")
    )
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(os.path.join(model_dir, f), framework="np") as h:
                for k in h.keys():
                    sd[k] = h.get_tensor(k)
        return sd
    bin_files = sorted(
        f
        for f in os.listdir(model_dir)
        if f.startswith("pytorch_model") and f.endswith(".bin")
    )
    if not bin_files:
        raise FileNotFoundError(f"no safetensors/bin weights in {model_dir}")
    import torch

    for f in bin_files:
        part = torch.load(
            os.path.join(model_dir, f), map_location="cpu", weights_only=True
        )
        sd.update(part)
    return sd


def load_hf_config(model_dir: str) -> Dict[str, Any]:
    with open(os.path.join(model_dir, "config.json")) as f:
        return json.load(f)


def device_put_sharded(params, mesh, pspecs, memory_kind=None):
    """Place a host pytree onto the mesh with the model's shardings —
    the analog of the reference's partition-aware weight copy.
    ``memory_kind="pinned_host"`` keeps params in host memory on TPU
    (the CPU-offload path; XLA streams them per step)."""
    from jax.sharding import NamedSharding, PartitionSpec

    kw = {} if memory_kind is None else {"memory_kind": memory_kind}
    shardings = jax.tree.map(
        lambda p: NamedSharding(mesh, p, **kw),
        pspecs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
    return jax.tree.map(jax.device_put, params, shardings)
