"""Falcon model family (reference ``inference/models/falcon.cc`` and
``python/flexflow/serve/models/falcon.py``): RoPE + MQA/GQA, *parallel*
attention+MLP blocks (one shared input LayerNorm on 7B, separate
ln_attn/ln_mlp on the 40B "new decoder architecture"), un-biased GELU
FFN. Runs on the generic decoder (:mod:`.transformer`)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=65024,
        hidden_size=4544,
        intermediate_size=4 * 4544,
        num_hidden_layers=32,
        num_attention_heads=71,
        num_key_value_heads=1,  # falcon-7b is MQA
        max_position_embeddings=2048,
        norm_type="layernorm",
        norm_bias=True,
        norm_eps=1e-5,
        positions="rope",
        activation="gelu",
        glu=False,
        parallel_block=True,
        parallel_two_norms=False,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
    )
    d.update(kw)
    return DecoderConfig(**d)


def falcon_7b(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    new_arch = hf.get("new_decoder_architecture", False)
    heads = hf.get("num_attention_heads", hf.get("n_head"))
    if new_arch:
        kv = hf.get("num_kv_heads", hf.get("n_head_kv", heads))
    elif hf.get("multi_query", True):
        kv = 1
    else:
        kv = heads
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf.get("ffn_hidden_size", 4 * hf["hidden_size"]),
        num_hidden_layers=hf.get("num_hidden_layers", hf.get("n_layer")),
        num_attention_heads=heads,
        num_key_value_heads=kv,
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        parallel_two_norms=new_arch,
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    d.update(kw)
    return config(**d)


def _split_fused_qkv(w: np.ndarray, cfg: DecoderConfig, new_arch: bool):
    """HF Falcon fuses QKV into one matmul. Old (7B, MQA) layout stacks
    all H query heads then 1 K and 1 V head; new (40B) layout interleaves
    per KV group: [G query heads, k, v] × KV. ``w`` is already (in, out)."""
    D = cfg.hidden_size
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    if new_arch:
        g = w.reshape(D, KV, H // KV + 2, dk)
        wq = g[:, :, :-2, :].reshape(D, H * dk)
        wk = g[:, :, -2, :].reshape(D, KV * dk)
        wv = g[:, :, -1, :].reshape(D, KV * dk)
    else:
        g = w.reshape(D, H + 2 * KV, dk)
        wq = g[:, :H, :].reshape(D, H * dk)
        wk = g[:, H : H + KV, :].reshape(D, KV * dk)
        wv = g[:, H + KV :, :].reshape(D, KV * dk)
    return wq, wk, wv


def convert_hf_state_dict(sd: Dict[str, Any], cfg: DecoderConfig) -> Dict[str, Any]:
    """HF ``FalconForCausalLM`` state dict → framework pytree."""
    dt = cfg.dtype
    pre = "transformer."
    L = cfg.num_hidden_layers
    new_arch = cfg.parallel_two_norms

    wq, wk, wv = [], [], []
    for i in range(L):
        q, k, v = _split_fused_qkv(
            linear_w(sd, f"{pre}h.{i}.self_attention.query_key_value.weight"),
            cfg,
            new_arch,
        )
        wq.append(q), wk.append(k), wv.append(v)

    def vec(fmt):
        return stack([to_np(sd[pre + fmt.format(i)]) for i in range(L)], dt)

    if new_arch:
        norm = {
            "attn_norm_scale": vec("h.{}.ln_attn.weight"),
            "attn_norm_bias": vec("h.{}.ln_attn.bias"),
            "mlp_norm_scale": vec("h.{}.ln_mlp.weight"),
            "mlp_norm_bias": vec("h.{}.ln_mlp.bias"),
        }
    else:
        norm = {
            "attn_norm_scale": vec("h.{}.input_layernorm.weight"),
            "attn_norm_bias": vec("h.{}.input_layernorm.bias"),
        }

    layers = {
        **norm,
        "wq": stack(wq, dt),
        "wk": stack(wk, dt),
        "wv": stack(wv, dt),
        "wo": stack(
            [linear_w(sd, f"{pre}h.{i}.self_attention.dense.weight") for i in range(L)], dt
        ),
        "w_up": stack(
            [linear_w(sd, f"{pre}h.{i}.mlp.dense_h_to_4h.weight") for i in range(L)], dt
        ),
        "w_down": stack(
            [linear_w(sd, f"{pre}h.{i}.mlp.dense_4h_to_h.weight") for i in range(L)], dt
        ),
    }
    params = {
        "embed": jnp.asarray(to_np(sd[pre + "word_embeddings.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "ln_f.weight"]), dt),
        "final_norm_bias": jnp.asarray(to_np(sd[pre + "ln_f.bias"]), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(linear_w(sd, "lm_head.weight"), dt)
    return params
