"""Generic decoder-only transformer — one engine for the whole model zoo.

The reference builds each serving architecture as a separate C++ graph
builder (reference ``inference/models/{opt,falcon,mpt,starcoder}.cc`` and
Python twins ``python/flexflow/serve/models/*.py``), each wiring the same
operator set with per-family choices (norm type, positional scheme,
MQA/GQA widths, FFN activation, parallel vs sequential block). The
TPU-native design factors that variation into one configurable decoder:
a single `lax.scan`-over-stacked-layers program whose config selects

  * normalisation: LayerNorm (± bias) or RMSNorm,
  * positions: RoPE, learned absolute embeddings, or ALiBi bias,
  * attention widths: MHA / GQA / MQA via ``num_key_value_heads``,
  * FFN: relu/gelu/gelu_tanh/silu, optionally gated (GLU),
  * block topology: sequential (x + attn; x + ffn) or parallel
    (x + attn + ffn, Falcon-style, with one or two input norms),
  * biases and tied embeddings.

Each family module (opt.py, falcon.py, mpt.py, starcoder.py) is then just
a config mapping + HF weight converter. LLaMA keeps its tuned standalone
implementation (models/llama.py) as the flagship.

Sharding follows the same Megatron scheme as llama.py: QKV/up
column-parallel and O/down row-parallel on the ``model`` mesh axis, layer
stack sharded on ``pipe``, KV cache slots on ``data``.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
)


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    num_key_value_heads: int = 12        # 1 = MQA (Falcon-7B, Starcoder)
    max_position_embeddings: int = 2048
    norm_type: str = "layernorm"         # "layernorm" | "rmsnorm"
    norm_bias: bool = True
    norm_eps: float = 1e-5
    positions: str = "rope"              # "rope" | "learned" | "alibi"
    learned_pos_offset: int = 0          # OPT stores positions at idx+2
    rope_theta: float = 10000.0
    activation: str = "gelu"             # "relu"|"gelu"|"gelu_tanh"|"silu"
    glu: bool = False                    # gated FFN (SwiGLU-style)
    parallel_block: bool = False         # Falcon: x + attn(h) + mlp(h)
    parallel_two_norms: bool = False     # Falcon-40B: ln_attn + ln_mlp
    qkv_bias: bool = False
    out_bias: bool = False
    mlp_bias: bool = False
    tie_word_embeddings: bool = True
    # Mixture-of-experts FFN (Mixtral-style, HF MixtralSparseMoeBlock):
    # 0 = dense FFN; E > 0 replaces the FFN with E experts and a linear
    # router taking the top-k per token (softmax over the selected k).
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    # Qwen2-MoE extensions (HF Qwen2MoeSparseMoeBlock): experts may use
    # their own FFN width; an always-on shared expert (its own glu FFN)
    # joins the routed sum scaled by sigmoid(h @ shared_expert_gate);
    # and norm_topk=False keeps the softmax-over-ALL-experts weights of
    # the selected k WITHOUT renormalizing (qwen2_moe's default),
    # versus the Mixtral renormalize-over-selected behavior.
    moe_intermediate_size: int = 0          # 0 = intermediate_size
    moe_shared_expert_intermediate_size: int = 0  # 0 = no shared expert
    moe_norm_topk: bool = True
    # Sliding-window attention (Mistral-style): w > 0 lets a query at
    # position q attend only keys in (q-w, q]. 0 = full causal. The
    # serving KV cache keeps its full-length layout (lines beyond the
    # window are masked, not evicted) — correctness first; a rolling
    # cache is a memory optimization the reference also lacks.
    sliding_window: int = 0
    # Gemma-style knobs: a head_dim decoupled from hidden/heads (0 =
    # derived — kept as an OVERRIDE field, not resolved at construction,
    # so dataclasses.replace(cfg, num_attention_heads=...) re-derives
    # instead of carrying a stale value), RMSNorm scaling by (1 + w)
    # instead of w, and sqrt(D) input-embedding scaling.
    head_dim_override: int = 0
    norm_plus_one: bool = False
    embed_scale: bool = False
    # Phi-style knobs: partial rotary embeddings (only the first
    # rotary_pct of each head rotates) and an LM-head bias.
    rotary_pct: float = 1.0
    lm_head_bias: bool = False
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.num_local_experts and self.mlp_bias:
            # the MoE FFN has no bias path — allocating dead b_up/b_down
            # params would silently diverge from the configured arch
            raise ValueError(
                "mlp_bias is not supported with num_local_experts > 0"
            )
        if self.lm_head_bias and self.tie_word_embeddings:
            # a tied head has no separate lm_head tensor to bias — the
            # configured bias would silently vanish
            raise ValueError(
                "lm_head_bias requires tie_word_embeddings=False"
            )
        rot = int(self.head_dim * self.rotary_pct)
        if self.positions == "rope" and rot % 2:
            # an odd rotary width would silently rotate one dim fewer
            # than HF's partial-rope implementations
            raise ValueError(
                f"rotary_pct={self.rotary_pct} gives an odd rotary "
                f"width {rot} over head_dim={self.head_dim}; pick a "
                "fraction with an even rotated width"
            )

    @property
    def head_dim(self) -> int:
        return (
            self.head_dim_override
            or self.hidden_size // self.num_attention_heads
        )


def _activation(cfg: DecoderConfig, x):
    if cfg.activation == "relu":
        return jax.nn.relu(x)
    if cfg.activation == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if cfg.activation == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    if cfg.activation == "silu":
        return jax.nn.silu(x)
    raise ValueError(cfg.activation)


def _norm(cfg: DecoderConfig, x, scale, bias):
    xf = x.astype(jnp.float32)
    if cfg.norm_type == "rmsnorm":
        r = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + cfg.norm_eps)
        if cfg.norm_plus_one:  # Gemma: weight is an offset from 1
            scale = 1.0 + scale.astype(jnp.float32)
            return ((xf * r) * scale).astype(x.dtype)
        return ((xf * r).astype(x.dtype)) * scale
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = ((xf - mu) * lax.rsqrt(var + cfg.norm_eps)).astype(x.dtype) * scale
    if bias is not None:
        y = y + bias
    return y


def _dense_w(w, dtype):
    """Resolve a possibly-quantized ({"q","scale"}) weight to dense."""
    if isinstance(w, dict):  # int8/int4 weight-only quantization
        from ..quantization import dequantize

        return dequantize(w, dtype)
    return w


def _mm(x, w):
    w = _dense_w(w, x.dtype)
    return jnp.matmul(x, w, preferred_element_type=jnp.float32).astype(x.dtype)


# ---------------------------------------------------------------------------
# Positions


def rope_freqs(cfg: DecoderConfig, positions: jnp.ndarray):
    # partial rotary (Phi-style): only the first rotary_pct of each
    # head rotates; cos/sin carry that width and apply_rope passes the
    # rest of the head through untouched
    rot = int(cfg.head_dim * cfg.rotary_pct)
    half = rot // 2
    inv_freq = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    angles = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin):
    rot = cos.shape[-1]
    xr, x_pass = x[..., :rot], x[..., rot:]
    half = rot // 2
    x1, x2 = xr[..., :half], xr[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out = xr * cos[..., None, :] + rotated * sin[..., None, :]
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass.astype(out.dtype)], axis=-1)
    return out.astype(x.dtype)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Standard ALiBi head slopes (power-of-two geometric sequence, with
    the interpolation rule for non-power-of-two head counts)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        s = pow2_slopes(num_heads)
    else:
        n = 2 ** math.floor(math.log2(num_heads))
        s = pow2_slopes(n)
        extra = pow2_slopes(2 * n)[0::2][: num_heads - n]
        s = s + extra
    return jnp.asarray(s, jnp.float32)


# ---------------------------------------------------------------------------
# Parameters

def init_params(key, cfg: DecoderConfig) -> Dict[str, Any]:
    L, D, F = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    dt = cfg.dtype
    ks = jax.random.split(key, 10)
    std = 0.02

    def w(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    ones = lambda shape: jnp.ones(shape, dt)
    zeros = lambda shape: jnp.zeros(shape, dt)

    layers: Dict[str, Any] = {
        "attn_norm_scale": ones((L, D)),
        "wq": w(ks[0], (L, D, H * dk)),
        "wk": w(ks[1], (L, D, KV * dk)),
        "wv": w(ks[2], (L, D, KV * dk)),
        "wo": w(ks[3], (L, H * dk, D), std / math.sqrt(2 * L)),
    }
    E = cfg.num_local_experts
    if E:
        # expert-stacked FFN + router (HF Mixtral block_sparse_moe):
        # expert dim shards over the ``expert`` mesh axis
        Fe = cfg.moe_intermediate_size or F
        layers["w_router"] = w(jax.random.fold_in(ks[4], 1), (L, D, E))
        layers["w_up"] = w(ks[4], (L, E, D, Fe))
        layers["w_down"] = w(ks[5], (L, E, Fe, D), std / math.sqrt(2 * L))
        Fs = cfg.moe_shared_expert_intermediate_size
        if Fs:
            # always-on shared expert (Qwen2-MoE), sigmoid-gated; the
            # gate stays un-prefixed so quantization never touches it
            kk = jax.random.fold_in(ks[5], 7)
            layers["w_shared_up"] = w(jax.random.fold_in(kk, 0), (L, D, Fs))
            layers["w_shared_gate"] = w(jax.random.fold_in(kk, 1), (L, D, Fs))
            layers["w_shared_down"] = w(
                jax.random.fold_in(kk, 2), (L, Fs, D), std / math.sqrt(2 * L)
            )
            layers["shared_expert_gate"] = w(
                jax.random.fold_in(kk, 3), (L, D, 1)
            )
    else:
        layers["w_up"] = w(ks[4], (L, D, F))
        layers["w_down"] = w(ks[5], (L, F, D), std / math.sqrt(2 * L))
    if cfg.norm_bias:
        layers["attn_norm_bias"] = zeros((L, D))
    # Sequential blocks and Falcon-40B-style parallel blocks have a second
    # norm; Falcon-7B-style parallel blocks share one input norm.
    if (not cfg.parallel_block) or cfg.parallel_two_norms:
        layers["mlp_norm_scale"] = ones((L, D))
        if cfg.norm_bias:
            layers["mlp_norm_bias"] = zeros((L, D))
    if cfg.glu:
        layers["w_gate"] = w(
            ks[6],
            (L, E, D, cfg.moe_intermediate_size or F) if E else (L, D, F),
        )
    if cfg.qkv_bias:
        layers["bq"] = zeros((L, H * dk))
        layers["bk"] = zeros((L, KV * dk))
        layers["bv"] = zeros((L, KV * dk))
    if cfg.out_bias:
        layers["bo"] = zeros((L, D))
    if cfg.mlp_bias:
        layers["b_up"] = zeros((L, F))
        layers["b_down"] = zeros((L, D))
        if cfg.glu:
            layers["b_gate"] = zeros((L, F))

    params: Dict[str, Any] = {
        "embed": w(ks[7], (cfg.vocab_size, D)),
        "layers": layers,
        "final_norm_scale": ones((D,)),
    }
    if cfg.norm_bias:
        params["final_norm_bias"] = zeros((D,))
    if cfg.positions == "learned":
        params["pos_embed"] = w(
            ks[8], (cfg.max_position_embeddings + cfg.learned_pos_offset, D)
        )
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(ks[9], (D, cfg.vocab_size))
        if cfg.lm_head_bias:
            params["lm_head_bias"] = zeros((cfg.vocab_size,))
    return params


def param_pspecs(cfg: DecoderConfig, *, pipeline: bool = False) -> Dict[str, Any]:
    """Megatron TP shardings on ``model``; stacked layer dim on ``pipe``
    (the analog of the reference's hardcoded inference-TP rewrite,
    reference ``src/runtime/model.cc:3239-3312``)."""
    pp = PIPE_AXIS if pipeline else None
    col = lambda: P(pp, None, MODEL_AXIS)     # D×(sharded out)
    row = lambda: P(pp, MODEL_AXIS, None)     # (sharded in)×D
    vec_col = lambda: P(pp, MODEL_AXIS)       # bias of a col-parallel matmul
    vec_rep = lambda: P(pp, None)             # replicated per-layer vector

    layers = {
        "attn_norm_scale": vec_rep(),
        "wq": col(), "wk": col(), "wv": col(), "wo": row(),
        "w_up": col(), "w_down": row(),
    }
    if cfg.num_local_experts:
        # experts shard over the expert axis AND Megatron-TP inside each
        # expert (HF Mixtral weights are per-expert dense matmuls)
        layers["w_router"] = P(pp, None, None)
        layers["w_up"] = P(pp, EXPERT_AXIS, None, MODEL_AXIS)
        layers["w_down"] = P(pp, EXPERT_AXIS, MODEL_AXIS, None)
        if cfg.moe_shared_expert_intermediate_size:
            # the shared expert is dense per token: plain Megatron TP
            layers["w_shared_up"] = col()
            layers["w_shared_gate"] = col()
            layers["w_shared_down"] = row()
            layers["shared_expert_gate"] = P(pp, None, None)
    opt_specs = {
        "attn_norm_bias": vec_rep(),
        "mlp_norm_scale": vec_rep(),
        "mlp_norm_bias": vec_rep(),
        "w_gate": (
            P(pp, EXPERT_AXIS, None, MODEL_AXIS)
            if cfg.num_local_experts else col()
        ),
        "bq": vec_col(), "bk": vec_col(), "bv": vec_col(),
        "bo": vec_rep(),
        "b_up": vec_col(), "b_gate": vec_col(), "b_down": vec_rep(),
    }
    probe = init_shapes(cfg)
    for name, spec in opt_specs.items():
        if name in probe["layers"]:
            layers[name] = spec
    specs: Dict[str, Any] = {
        "embed": P(None, None),
        "layers": layers,
        "final_norm_scale": P(None),
    }
    if "final_norm_bias" in probe:
        specs["final_norm_bias"] = P(None)
    if "pos_embed" in probe:
        specs["pos_embed"] = P(None, None)
    if "lm_head" in probe:
        specs["lm_head"] = P(None, MODEL_AXIS)
    if "lm_head_bias" in probe:
        specs["lm_head_bias"] = P(MODEL_AXIS)
    return specs


@functools.lru_cache(maxsize=32)
def _shapes_cache(cfg: DecoderConfig):
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


def init_shapes(cfg: DecoderConfig):
    return _shapes_cache(cfg)


# ---------------------------------------------------------------------------
# Attention + block (shared by train and serve paths)


def _gqa_attend(cfg: DecoderConfig, q, k, v, bias, mask):
    """q (B,S,H,dk) vs k/v (B,T,KV,dk) grouped without materialising the
    head repeat. ``bias`` (B,H,S,T) f32 or None; ``mask`` (B,S,T) bool."""
    B, S, H, dk = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, S, KV, G, dk)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(cfg.head_dim)
    if bias is not None:
        scores = scores + bias.reshape(B, KV, G, *bias.shape[-2:])
    if mask is not None:
        m = mask if mask.ndim == 3 else mask[None]
        scores = jnp.where(m[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(B, S, H * dk)


def _project_qkv(cfg: DecoderConfig, p, h):
    B, S, _ = h.shape
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    q = _mm(h, p["wq"])
    k = _mm(h, p["wk"])
    v = _mm(h, p["wv"])
    if cfg.qkv_bias:
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    return (
        q.reshape(B, S, H, dk),
        k.reshape(B, S, KV, dk),
        v.reshape(B, S, KV, dk),
    )


def _moe_ffn(cfg: DecoderConfig, p, h):
    """Mixtral-style sparse-MoE FFN (HF ``MixtralSparseMoeBlock``):
    linear router → top-k per token → softmax over the SELECTED k →
    weighted sum of expert outputs.

    TPU shape: experts are computed as one batched einsum over the
    expert dim rather than gather/scatter per expert — at decode (a few
    tokens per step) the all-expert compute is cheap and keeps the MXU
    busy with one big contraction; the expert dim shards over the
    ``expert`` mesh axis so each device computes only its expert range
    and GSPMD inserts the combine reduction (the serving-time analog of
    ops/moe.py's ExpertsOp range sharding). For E=8,K=2 this spends E/K
    = 4x the FLOPs of perfect dispatch at prefill — acceptable until
    a capacity-dispatch Pallas path is warranted."""
    E, K = cfg.num_local_experts, cfg.num_experts_per_tok
    router = jnp.matmul(
        h.astype(jnp.float32), _dense_w(p["w_router"], jnp.float32),
        preferred_element_type=jnp.float32,
    )  # (B,S,E)
    topv, topi = lax.top_k(router, K)
    if cfg.moe_norm_topk:
        # renormalize over the selected k (Mixtral; equals softmax over
        # the selected logits)
        gate = jax.nn.softmax(topv, axis=-1)  # (B,S,K)
    else:
        # softmax over ALL experts, keep the selected weights verbatim
        # (Qwen2-MoE norm_topk_prob=False default)
        gate = jnp.take_along_axis(
            jax.nn.softmax(router, axis=-1), topi, axis=-1
        )
    combine = jnp.einsum(
        "bsk,bske->bse", gate, jax.nn.one_hot(topi, E, dtype=jnp.float32)
    )  # (B,S,E)
    w_up = _dense_w(p["w_up"], h.dtype)
    w_down = _dense_w(p["w_down"], h.dtype)
    up = jnp.einsum(
        "bsd,edf->bsef", h, w_up, preferred_element_type=jnp.float32
    ).astype(h.dtype)
    if cfg.glu:
        gate_p = jnp.einsum(
            "bsd,edf->bsef", h, _dense_w(p["w_gate"], h.dtype),
            preferred_element_type=jnp.float32,
        ).astype(h.dtype)
        act = _activation(cfg, gate_p) * up
    else:
        act = _activation(cfg, up)
    # single contraction: folding the combine weights in avoids ever
    # materializing the E-times-wider (B,S,E,D) f32 intermediate
    out = jnp.einsum(
        "bsef,efd,bse->bsd", act, w_down, combine,
        preferred_element_type=jnp.float32,
    ).astype(h.dtype)
    if cfg.moe_shared_expert_intermediate_size:
        # always-on shared expert, scaled by a sigmoid token gate
        # (HF Qwen2MoeSparseMoeBlock shared_expert + shared_expert_gate)
        s_up = _mm(h, p["w_shared_up"])
        s_act = _activation(cfg, _mm(h, p["w_shared_gate"])) * s_up
        s_out = _mm(s_act, p["w_shared_down"])
        s_gate = jax.nn.sigmoid(
            jnp.matmul(
                h.astype(jnp.float32),
                _dense_w(p["shared_expert_gate"], jnp.float32),
                preferred_element_type=jnp.float32,
            )
        ).astype(h.dtype)  # (B,S,1)
        out = out + s_gate * s_out
    return out


def _ffn(cfg: DecoderConfig, p, h):
    if cfg.num_local_experts:
        return _moe_ffn(cfg, p, h)
    up = _mm(h, p["w_up"])
    if cfg.mlp_bias:
        up = up + p["b_up"]
    if cfg.glu:
        gate = _mm(h, p["w_gate"])
        if cfg.mlp_bias:
            gate = gate + p["b_gate"]
        act = _activation(cfg, gate) * up
    else:
        act = _activation(cfg, up)
    out = _mm(act, p["w_down"])
    if cfg.mlp_bias:
        out = out + p["b_down"]
    return out


def block(
    cfg: DecoderConfig,
    p: Dict[str, jnp.ndarray],
    x: jnp.ndarray,              # (B, S, D)
    rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]],
    bias: Optional[jnp.ndarray],  # additive attention bias (ALiBi)
    mask: Optional[jnp.ndarray],
):
    """One decoder block, full-sequence (training) attention."""
    h = _norm(cfg, x, p["attn_norm_scale"], p.get("attn_norm_bias"))
    q, k, v = _project_qkv(cfg, p, h)
    if rope is not None:
        cos, sin = rope
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    attn = _gqa_attend(cfg, q, k, v, bias, mask)
    attn = _mm(attn, p["wo"])
    if cfg.out_bias:
        attn = attn + p["bo"]

    if cfg.parallel_block:
        if cfg.parallel_two_norms:
            h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
        else:
            h2 = h
        return x + attn + _ffn(cfg, p, h2), None
    x = x + attn
    h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
    return x + _ffn(cfg, p, h2), None


def _train_bias(cfg: DecoderConfig, positions):
    """ALiBi additive bias for full-sequence attention: (B,H,S,S)."""
    if cfg.positions != "alibi":
        return None
    slopes = alibi_slopes(cfg.num_attention_heads)
    qp = positions.astype(jnp.float32)
    dist = qp[:, None, :, None] - qp[:, None, None, :]  # (B,1,S,S) q - k
    return -slopes[None, :, None, None] * dist


def _embed_in(cfg: DecoderConfig, params, tokens, positions):
    x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
    if cfg.embed_scale:  # Gemma scales inputs by sqrt(hidden)
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    if cfg.positions == "learned":
        # mode="clip": padding slots carry the scratch-row position, which
        # exceeds the table; JAX's default out-of-bounds fill is NaN, which
        # would poison attention through the scratch cache line.
        x = x + jnp.take(
            params["pos_embed"],
            positions.astype(jnp.int32) + cfg.learned_pos_offset,
            axis=0,
            mode="clip",
        )
    return x


def _lm_logits(cfg: DecoderConfig, params, x):
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    logits = jnp.matmul(x, head, preferred_element_type=jnp.float32)
    if "lm_head_bias" in params:
        logits = logits + params["lm_head_bias"].astype(jnp.float32)
    return logits


def forward(
    params: Dict[str, Any],
    tokens: jnp.ndarray,
    cfg: DecoderConfig,
    *,
    positions: Optional[jnp.ndarray] = None,
    remat: bool = False,
    shard_activations: bool = False,
) -> jnp.ndarray:
    """Training/eval forward → logits (B, S, V)."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = _embed_in(cfg, params, tokens, positions)
    rope = rope_freqs(cfg, positions) if cfg.positions == "rope" else None
    bias = _train_bias(cfg, positions)
    mask = jnp.tril(jnp.ones((S, S), bool))
    if cfg.sliding_window:
        idx = jnp.arange(S)
        mask &= idx[None, :] > idx[:, None] - cfg.sliding_window

    def constrain(t):
        if shard_activations:
            return lax.with_sharding_constraint(t, P(DATA_AXIS, SEQ_AXIS, None))
        return t

    x = constrain(x)
    blk = functools.partial(block, cfg)
    if remat:
        blk = jax.checkpoint(blk)

    def scan_body(carry, p_l):
        y, _ = blk(p_l, carry, rope, bias, mask)
        return constrain(y), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    x = _norm(cfg, x, params["final_norm_scale"], params.get("final_norm_bias"))
    return _lm_logits(cfg, params, x)


# ---------------------------------------------------------------------------
# Serving path — the same engine protocol as models/llama.py: request-slot
# paged KV cache with a scratch row, one compiled program per static
# (chunk, all_logits, mask-mode) signature (reference's three attention
# operators inc/spec/tree_inc_multihead_self_attention collapse into one).


def needs_pos_cache(cfg: DecoderConfig) -> bool:
    """ALiBi biases and sliding-window masks depend on key *sequence*
    positions at attention time (RoPE bakes position into cached K
    instead), so the cache carries a per-line position buffer. For the
    window this makes tree-verify masking EXACT: an in-flight tree key's
    cache line (prefix + node index) is not its sequence position
    (prefix + depth), so a line-index window would under-mask."""
    return cfg.positions == "alibi" or cfg.sliding_window > 0


def init_kv_cache(cfg: DecoderConfig, num_slots: int, max_len: int, dtype=None):
    L, KV, dk = cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim
    dt = dtype or cfg.dtype
    shape = (L, num_slots, max_len + 1, KV, dk)
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if needs_pos_cache(cfg):
        cache["pos"] = jnp.zeros((num_slots, max_len + 1), jnp.int32)
    return cache


def kv_cache_pspecs(cfg: DecoderConfig = None, *, pipeline: bool = False):
    # MQA (KV=1) caches replicate across TP: a size-1 head dim cannot
    # split over the model axis (the memory cost is the standard MQA
    # serving trade; queries still shard by head). With ``pipeline`` the
    # layer-major leading dim shards over ``pipe``.
    kv_axis = None if (cfg is not None and cfg.num_key_value_heads == 1) else MODEL_AXIS
    pp = PIPE_AXIS if pipeline else None
    specs = {
        "k": P(pp, DATA_AXIS, None, kv_axis, None),
        "v": P(pp, DATA_AXIS, None, kv_axis, None),
    }
    if cfg is not None and needs_pos_cache(cfg):
        specs["pos"] = P(DATA_AXIS, None)
    return specs


def _serve_attend(cfg: DecoderConfig, q, k_cache, v_cache, bias, mask):
    """q (R,C,H,dk) against cache (R,S1,KV,dk)."""
    R, C, H, dk = q.shape
    KV = k_cache.shape[2]
    G = H // KV
    qg = q.reshape(R, C, KV, G, dk)
    scores = jnp.einsum(
        "rckgd,rskd->rkgcs", qg, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(cfg.head_dim)
    if bias is not None:  # (R,H,C,S1)
        scores = scores + bias.reshape(R, KV, G, *bias.shape[-2:])
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("rkgcs,rskd->rckgd", probs, v_cache)
    return out.reshape(R, C, H * dk)


def serve_block(cfg, p, x, rope, bias, mask, k_cache, v_cache, cache_positions):
    R, C, D = x.shape
    h = _norm(cfg, x, p["attn_norm_scale"], p.get("attn_norm_bias"))
    q, k, v = _project_qkv(cfg, p, h)
    if rope is not None:
        cos, sin = rope
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    bidx = jnp.arange(R)[:, None]
    k_cache = k_cache.at[bidx, cache_positions].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[bidx, cache_positions].set(v.astype(v_cache.dtype))
    attn = _serve_attend(cfg, q, k_cache, v_cache, bias, mask)
    attn = _mm(attn, p["wo"])
    if cfg.out_bias:
        attn = attn + p["bo"]
    if cfg.parallel_block:
        if cfg.parallel_two_norms:
            h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
        else:
            h2 = h
        return x + attn + _ffn(cfg, p, h2), k_cache, v_cache
    x = x + attn
    h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
    return x + _ffn(cfg, p, h2), k_cache, v_cache


def serve_step(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,      # (R, C)
    positions: jnp.ndarray,   # (R, C) sequence positions
    logits_idx: jnp.ndarray,  # (R,)
    mask: Optional[jnp.ndarray],   # (R, C, S1) bool or None => causal
    cache_positions: Optional[jnp.ndarray] = None,
    *,
    cfg: DecoderConfig,
    all_logits: bool = False,
    num_layers: Optional[int] = None,
    mesh=None,
):
    """One serving step over R request slots × C tokens; same contract as
    ``models.llama.serve_step`` (see engine protocol in serve/engine.py),
    including the stage-sharded pipeline path when ``mesh`` has pipe>1.
    ``num_layers`` is the layer-sliced early-exit draft step (see
    models/llama.serve_step): only the first ``num_layers`` blocks run
    and commit K/V; the deeper layers' cache buffers pass through for
    the verify pass to own (the position buffer, written once per step
    rather than per layer, updates in full either way)."""
    R, C = tokens.shape
    S1 = cache["k"].shape[2]
    if cache_positions is None:
        cache_positions = positions
    x = _embed_in(cfg, params, tokens, positions)
    rope = rope_freqs(cfg, positions) if cfg.positions == "rope" else None
    if mask is None:
        from ..serve.kernels import causal_serve_mask

        mask = causal_serve_mask(positions, S1)

    bias = None
    pos_cache = None
    if needs_pos_cache(cfg):
        bidx = jnp.arange(R)[:, None]
        pos_cache = cache["pos"].at[bidx, cache_positions].set(
            positions.astype(jnp.int32)
        )
        if cfg.positions == "alibi":
            slopes = alibi_slopes(cfg.num_attention_heads)
            dist = (
                positions.astype(jnp.float32)[:, None, :, None]
                - pos_cache.astype(jnp.float32)[:, None, None, :]
            )  # (R,1,C,S1)
            bias = -slopes[None, :, None, None] * dist
    if cfg.sliding_window:
        # window by TRUE key sequence positions from the pos cache —
        # exact for every path, including tree-verify lines whose cache
        # line (prefix + node index) differs from their sequence
        # position (prefix + depth). Unwritten lines hold position 0,
        # but the causal/tree mask already excludes them.
        mask = mask & (
            pos_cache[:, None, :]
            > positions[:, :, None] - cfg.sliding_window
        )

    def scan_body(h, xs):
        p_l, kc, vc = xs
        h, kc, vc = serve_block(
            cfg, p_l, h, rope, bias, mask, kc, vc, cache_positions
        )
        return h, (kc, vc)

    if mesh is not None and mesh.shape[PIPE_AXIS] > 1:
        if num_layers is not None:
            raise NotImplementedError(
                "early-exit drafting (num_layers) is not composed with "
                "pipeline parallelism — the sliced stack would idle the "
                "deeper stages"
            )

        from ..parallel.pipeline import make_pipelined_serve

        # Row-sharded args go through explicit specs (closures would
        # replicate over the manual data axis — see make_pipelined_serve).
        row = {"mask": mask, "cpos": cache_positions}
        if rope is not None:
            row["cos"], row["sin"] = rope
        if bias is not None:
            row["bias"] = bias

        def stage_fn(stage_layers, caches, h, row):
            rope_l = (row["cos"], row["sin"]) if "cos" in row else None
            kc, vc = caches

            def body(hh, xs):
                p_l, kcl, vcl = xs
                hh, kcl, vcl = serve_block(
                    cfg, p_l, hh, rope_l, row.get("bias"), row["mask"],
                    kcl, vcl, row["cpos"],
                )
                return hh, (kcl, vcl)

            h, (kc, vc) = lax.scan(body, h, (stage_layers, kc, vc))
            return h, (kc, vc)

        piped = make_pipelined_serve(
            mesh,
            stage_fn,
            params_spec=jax.tree.map(lambda _: P(PIPE_AXIS), params["layers"]),
            cache_spec=(
                P(PIPE_AXIS, DATA_AXIS),
                P(PIPE_AXIS, DATA_AXIS),
            ),
            row_specs={k: P(DATA_AXIS) for k in row},
        )
        x, (k_new, v_new) = piped(
            params["layers"], (cache["k"], cache["v"]), x, row
        )
    elif num_layers is not None and num_layers < cfg.num_hidden_layers:
        n = num_layers
        x, (k_upd, v_upd) = lax.scan(
            scan_body, x,
            (jax.tree.map(lambda a: a[:n], params["layers"]),
             cache["k"][:n], cache["v"][:n]),
        )
        k_new = jnp.concatenate([k_upd, cache["k"][n:]], axis=0)
        v_new = jnp.concatenate([v_upd, cache["v"][n:]], axis=0)
    else:
        x, (k_new, v_new) = lax.scan(
            scan_body, x, (params["layers"], cache["k"], cache["v"])
        )
    x = _norm(cfg, x, params["final_norm_scale"], params.get("final_norm_bias"))
    if not all_logits:
        x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)
        logits = _lm_logits(cfg, params, x)[:, 0]
    else:
        logits = _lm_logits(cfg, params, x)
    new_cache = {"k": k_new, "v": v_new}
    if needs_pos_cache(cfg):
        new_cache["pos"] = pos_cache
    return logits, new_cache


def commit_kv(cache, src, dst):
    """Move accepted speculative cache lines into committed positions (see
    ``models.llama.commit_kv``; reference ``request_manager.cu`` token
    commit). Handles the extra (R, S1) position buffer for ALiBi caches."""
    R = src.shape[0]
    bidx = jnp.arange(R)[:, None]
    out = {}
    for name, buf in cache.items():
        if name == "pos":  # (R, S1)
            out[name] = buf.at[bidx, dst].set(buf[bidx, src])
        else:  # (L, R, S1, KV, dk)
            out[name] = buf.at[:, bidx, dst].set(buf[:, bidx, src])
    return out


def reorder_slots(
    cache: Dict[str, jnp.ndarray], src: jnp.ndarray  # (R,) int32
) -> Dict[str, jnp.ndarray]:
    """Gather cache slots (see models.llama.reorder_slots); the ALiBi
    position buffer's slot dim leads instead of following the layer dim."""
    return {
        name: (buf[src] if name == "pos" else buf[:, src])
        for name, buf in cache.items()
    }


# ---------------------------------------------------------------------------
# Paged serving path (Ragged Paged Attention layout — see the twin
# implementation in models/llama.py for the design rationale): the pool
# replaces the per-slot line dim with (pages+1, page_size); page tables
# resolve logical cache lines to physical pages. The extra per-line
# position buffer (ALiBi/sliding-window families) pages the same way.

#: decode-step fusions the generic decoder's serving step supports
#: (ServingConfig.fused_decode; the engine validates requests against
#: this). "rope_kv_write": serve_step_paged folds RoPE (or, for
#: learned-position families, just the quantizing KV page write) into
#: the ragged paged Pallas kernel; ALiBi batches keep the unfused
#: path at run time because the additive bias already excludes the
#: Pallas kernel. The "sampling" epilogue fusion is model-agnostic —
#: it lives in the engine's step program — so it is not listed here.
FUSED_DECODE = ("rope_kv_write", "whole_step")


def init_paged_kv_cache(
    cfg: DecoderConfig, num_pages: int, page_size: int, dtype=None,
    kv_quant: Optional[str] = None, extra_rows: int = 0,
):
    """Pool (L, num_pages+1, page_size, KV, dk); pool row ``num_pages``
    is the shared scratch page. ALiBi/sliding-window configs also page
    the per-line position buffer. With ``kv_quant`` the pools store
    quantized codes — int8, or packed int4 nibbles (two codes per byte
    along dk, trailing dim ``head_dim // 2``) — plus per-page-per-KV-
    head f32 ``k_scale``/``v_scale`` rows (serve/kv_quant.py; the
    position buffer stays int32 — it is exact metadata, not tensor
    payload). ``extra_rows`` appends never-referenced pad rows after
    the scratch row (context-parallel row-shard alignment — see
    models/llama.py init_paged_kv_cache)."""
    L, KV, dk = cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim
    dt = dtype or cfg.dtype
    spec = None
    if kv_quant is not None:
        from ..serve.kv_quant import resolve_spec

        spec = resolve_spec(kv_quant)
        dt = spec.dtype
        if dk % spec.pack:
            raise ValueError(
                f"kv_quant={kv_quant!r} packs {spec.pack} codes per "
                f"element along head_dim, which needs head_dim "
                f"({dk}) divisible by {spec.pack}"
            )
        dk = dk // spec.pack
    rows = num_pages + 1 + int(extra_rows)
    shape = (L, rows, page_size, KV, dk)
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if spec is not None:
        sshape = (L, rows, KV)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    if needs_pos_cache(cfg):
        cache["pos"] = jnp.zeros((rows, page_size), jnp.int32)
    return cache


def paged_kv_cache_pspecs(cfg: DecoderConfig = None, *, pipeline: bool = False,
                          kv_quant: Optional[str] = None,
                          kv_shard: Optional[str] = None):
    """Pages shard over DP, KV heads over TP (MQA replicates, as in the
    dense layout); quantized scale rows shard like their pools (pages
    on data, KV heads on model). ``kv_shard="context"`` shards pool
    rows (and the position buffer's) over the SEQ axis instead — the
    sequence-sharded layout of context-parallel serving (see
    models/llama.py paged_kv_cache_pspecs)."""
    kv_axis = (
        None if (cfg is not None and cfg.num_key_value_heads == 1)
        else MODEL_AXIS
    )
    page_axis = SEQ_AXIS if kv_shard == "context" else DATA_AXIS
    pp = PIPE_AXIS if pipeline else None
    specs = {
        "k": P(pp, page_axis, None, kv_axis, None),
        "v": P(pp, page_axis, None, kv_axis, None),
    }
    if kv_quant is not None:
        specs["k_scale"] = P(pp, page_axis, kv_axis)
        specs["v_scale"] = P(pp, page_axis, kv_axis)
    if cfg is not None and needs_pos_cache(cfg):
        specs["pos"] = P(page_axis, None)
    return specs


def _page_lookup(page_table, cache_positions, page_size):
    logical = cache_positions // page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    return phys, cache_positions % page_size


def serve_block_paged(cfg, p, x, rope, bias, mask, k_pool, v_pool,
                      phys, off, page_table, kernels: str = "xla",
                      k_scale=None, v_scale=None, qmax=None,
                      *, fused_rope: bool = False, logical=None,
                      cp_mesh=None):
    """Paged twin of :func:`serve_block`: scatter new K/V at the
    table-resolved (page, offset); attend over the virtual cache read
    through the table (``jnp.take`` gather, or the fused ragged paged
    kernel when ``kernels='pallas'`` and no additive bias is in play).
    With ``qmax`` the pool is quantized (serve/kv_quant.py): the commit
    quantizes in-step and reads dequantize at the page scales (fused
    in-kernel on the Pallas path). Returns
    ``(x, k_pool, v_pool, k_scale, v_scale)``.

    ``fused_rope`` (megakernel decode step): on the Pallas path RoPE —
    or, for non-RoPE position schemes, just the quantizing KV commit —
    moves inside the ragged paged kernel
    (serve/kernels.fused_rope_paged_attention). ALiBi batches keep the
    unfused path (the additive bias already excludes the Pallas
    kernel); on kernels="xla" the flag is a no-op — the unfused XLA
    step is the CPU-parity fallback. On a sequence-sharded mesh
    (``cp_mesh``) the fused prologue joins the RING body instead
    (PR-11's exclusion, lifted — serve/kernels.
    ring_ragged_paged_attention's ``fused`` mode)."""
    from ..serve import kernels as _pk

    R, C, D = x.shape
    if cp_mesh is None and not (kernels == "pallas" and bias is None):
        # the unfused XLA path — the CPU-parity reference every fusion
        # (and the whole-step megakernel) anchors on; ONE shared body
        return _block_paged_xla(
            cfg, p, x, rope, bias, mask, k_pool, v_pool, phys, off,
            page_table, k_scale, v_scale, qmax,
        )
    h = _norm(cfg, x, p["attn_norm_scale"], p.get("attn_norm_bias"))
    q, k, v = _project_qkv(cfg, p, h)
    if (fused_rope and kernels == "pallas" and bias is None
            and cp_mesh is not None):
        # ring fused prologue: RoPE + the resident-line commit move
        # inside the per-shard shard_map body (full-precision pools;
        # quantized raises loudly in the kernel and is excluded at
        # ServingConfig validation)
        cos, sin = rope if rope is not None else (None, None)
        attn, k_pool, v_pool = _pk.ring_ragged_paged_attention(
            q, k_pool, v_pool, page_table, mask, cp_mesh,
            fused=dict(k_new=k, v_new=v, cos=cos, sin=sin,
                       phys=phys, off=off),
        )
        attn = attn.reshape(R, C, -1)
        attn = _mm(attn, p["wo"])
        if cfg.out_bias:
            attn = attn + p["bo"]
        if cfg.parallel_block:
            if cfg.parallel_two_norms:
                h2 = _norm(cfg, x, p["mlp_norm_scale"],
                           p.get("mlp_norm_bias"))
            else:
                h2 = h
            return (x + attn + _ffn(cfg, p, h2), k_pool, v_pool,
                    k_scale, v_scale)
        x = x + attn
        h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
        return x + _ffn(cfg, p, h2), k_pool, v_pool, k_scale, v_scale
    if fused_rope and kernels == "pallas" and bias is None:
        cos, sin = rope if rope is not None else (None, None)
        attn, k_pool, v_pool, k_scale, v_scale = (
            _pk.fused_rope_paged_attention(
                q, k, v, cos, sin, k_pool, v_pool, page_table,
                logical, off, mask,
                k_scale=k_scale, v_scale=v_scale, qmax=qmax,
            )
        )
        attn = attn.reshape(R, C, -1)
        attn = _mm(attn, p["wo"])
        if cfg.out_bias:
            attn = attn + p["bo"]
        if cfg.parallel_block:
            if cfg.parallel_two_norms:
                h2 = _norm(cfg, x, p["mlp_norm_scale"],
                           p.get("mlp_norm_bias"))
            else:
                h2 = h
            return (x + attn + _ffn(cfg, p, h2), k_pool, v_pool,
                    k_scale, v_scale)
        x = x + attn
        h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
        return x + _ffn(cfg, p, h2), k_pool, v_pool, k_scale, v_scale
    if rope is not None:
        cos, sin = rope
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    if qmax is not None:
        from ..serve.kv_quant import quant_line_write

        k_pool, k_scale = quant_line_write(k_pool, k_scale, phys, off, k, qmax)
        v_pool, v_scale = quant_line_write(v_pool, v_scale, phys, off, v, qmax)
    else:
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
    if cp_mesh is not None:
        if bias is not None:
            # ALiBi's additive bias needs per-key-position terms the
            # ring program does not carry yet (same exclusion as the
            # Pallas kernel); sliding-window masks are fine — they are
            # mask refinements, already folded in before this call.
            raise NotImplementedError(
                "ring context parallelism is not composed with ALiBi "
                "position bias — serve this family with "
                "kv_shard='context' on a seq-degree-1 mesh (the table-"
                "gather layout), or use a RoPE/learned-position family"
            )
        attn = _pk.ring_ragged_paged_attention(
            q, k_pool, v_pool, page_table, mask, cp_mesh,
            k_scale=k_scale, v_scale=v_scale,
        )
        attn = attn.reshape(R, C, -1)
    else:  # kernels == "pallas", bias None (the xla path returned above)
        attn = _pk.ragged_paged_attention(
            q, k_pool, v_pool, page_table, mask,
            k_scale=k_scale, v_scale=v_scale,
        )
        attn = attn.reshape(R, C, -1)
    attn = _mm(attn, p["wo"])
    if cfg.out_bias:
        attn = attn + p["bo"]
    if cfg.parallel_block:
        if cfg.parallel_two_norms:
            h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
        else:
            h2 = h
        return x + attn + _ffn(cfg, p, h2), k_pool, v_pool, k_scale, v_scale
    x = x + attn
    h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
    return x + _ffn(cfg, p, h2), k_pool, v_pool, k_scale, v_scale


def _mm_reduced(x, w, reduce_fn):
    """``_mm`` with a tensor-parallel partial-sum chokepoint (see
    models/llama.py ``_mm_reduced``): the reduction applies to the f32
    matmul output BEFORE the model-dtype cast — where GSPMD inserts its
    all-reduce — so the collective-explicit whole-step walk stays
    bitwise the GSPMD-scheduled step. None = literally ``_mm``."""
    if reduce_fn is None:
        return _mm(x, w)
    out = jnp.matmul(
        x, _dense_w(w, x.dtype), preferred_element_type=jnp.float32
    )
    return reduce_fn(out).astype(x.dtype)


def _project_qkv_local(cfg: DecoderConfig, p, h):
    """:func:`_project_qkv` with head counts derived from the WEIGHT
    shapes instead of cfg — op-for-op identical on the single-shard
    path, and what lets the same body serve TP-local head shards."""
    B, S, _ = h.shape
    dk = cfg.head_dim
    q = _mm(h, p["wq"])
    k = _mm(h, p["wk"])
    v = _mm(h, p["wv"])
    if cfg.qkv_bias:
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    return (
        q.reshape(B, S, -1, dk),
        k.reshape(B, S, -1, dk),
        v.reshape(B, S, -1, dk),
    )


def _attend_paged_xla(cfg: DecoderConfig, q, k_virt, v_virt, bias, mask):
    """:func:`_serve_attend` with KV heads derived from the operands
    (see :func:`_project_qkv_local` for why)."""
    R, C, H, dk = q.shape
    KV = k_virt.shape[2]
    G = H // KV
    qg = q.reshape(R, C, KV, G, dk)
    scores = jnp.einsum(
        "rckgd,rskd->rkgcs", qg, k_virt, preferred_element_type=jnp.float32
    ) / math.sqrt(cfg.head_dim)
    if bias is not None:
        scores = scores + bias.reshape(R, KV, G, *bias.shape[-2:])
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("rkgcs,rskd->rckgd", probs, v_virt)
    return out.reshape(R, C, H * dk)


def _ffn_reduced(cfg: DecoderConfig, p, h, reduce_fn):
    """:func:`_ffn` with the row-parallel down-projection routed
    through ``reduce_fn`` (None = literally ``_ffn``; MoE FFNs never
    reach here with a reduce_fn — the whole-step layout hook excludes
    them)."""
    if reduce_fn is None:
        return _ffn(cfg, p, h)
    up = _mm(h, p["w_up"])
    if cfg.mlp_bias:
        up = up + p["b_up"]
    if cfg.glu:
        gate = _mm(h, p["w_gate"])
        if cfg.mlp_bias:
            gate = gate + p["b_gate"]
        act = _activation(cfg, gate) * up
    else:
        act = _activation(cfg, up)
    out = _mm_reduced(act, p["w_down"], reduce_fn)
    if cfg.mlp_bias:
        out = out + p["b_down"]
    return out


def _block_paged_xla(cfg: DecoderConfig, p, x, rope, bias, mask,
                     k_pool, v_pool, phys, off, page_table,
                     k_scale=None, v_scale=None, qmax=None,
                     reduce_fn=None):
    """One block of the UNFUSED XLA paged step on values — the shared
    body of :func:`serve_block_paged`'s XLA path AND the whole-step
    decode megakernel / TP walk (:func:`serve_step_whole`); one
    definition is what makes whole-step decode bitwise the unfused XLA
    step (see the llama twin for the full rationale)."""
    from ..serve import kernels as _pk

    R, C, D = x.shape
    h = _norm(cfg, x, p["attn_norm_scale"], p.get("attn_norm_bias"))
    q, k, v = _project_qkv_local(cfg, p, h)
    if rope is not None:
        cos, sin = rope
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    if qmax is not None:
        from ..serve.kv_quant import quant_line_write

        k_pool, k_scale = quant_line_write(k_pool, k_scale, phys, off, k,
                                           qmax)
        v_pool, v_scale = quant_line_write(v_pool, v_scale, phys, off, v,
                                           qmax)
    else:
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
    if qmax is not None:
        k_virt = _pk.dequant_pages(k_pool, k_scale, page_table, q.dtype)
        v_virt = _pk.dequant_pages(v_pool, v_scale, page_table, q.dtype)
    else:
        k_virt = _pk.gather_pages(k_pool, page_table)
        v_virt = _pk.gather_pages(v_pool, page_table)
    attn = _attend_paged_xla(cfg, q, k_virt, v_virt, bias, mask)
    attn = _mm_reduced(attn, p["wo"], reduce_fn)
    if cfg.out_bias:
        attn = attn + p["bo"]
    if cfg.parallel_block:
        if cfg.parallel_two_norms:
            h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
        else:
            h2 = h
        return (x + attn + _ffn_reduced(cfg, p, h2, reduce_fn),
                k_pool, v_pool, k_scale, v_scale)
    x = x + attn
    h2 = _norm(cfg, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"))
    return (x + _ffn_reduced(cfg, p, h2, reduce_fn),
            k_pool, v_pool, k_scale, v_scale)


def _paged_serve_context(cfg, cache, positions, cache_positions, mask,
                         page_table, cache_len):
    """Shared prologue of the paged step/debug paths: page lookup, the
    causal-or-padded mask over the virtual cache, and the paged position
    buffer + ALiBi bias/sliding-window refinement."""
    from ..serve.kernels import gather_pages, paged_serve_mask

    ps = cache["k"].shape[2]
    phys, off = _page_lookup(page_table, cache_positions, ps)
    mask = paged_serve_mask(mask, positions, page_table.shape[1], ps, cache_len)

    bias = None
    pos_pool = None
    if needs_pos_cache(cfg):
        pos_pool = cache["pos"].at[phys, off].set(positions.astype(jnp.int32))
        pos_virt = gather_pages(pos_pool, page_table)  # (R, S_virt)
        if cfg.positions == "alibi":
            slopes = alibi_slopes(cfg.num_attention_heads)
            dist = (
                positions.astype(jnp.float32)[:, None, :, None]
                - pos_virt.astype(jnp.float32)[:, None, None, :]
            )
            bias = -slopes[None, :, None, None] * dist
        if cfg.sliding_window:
            mask = mask & (
                pos_virt[:, None, :]
                > positions[:, :, None] - cfg.sliding_window
            )
    return phys, off, mask, bias, pos_pool


def serve_step_paged(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,      # (R, C)
    positions: jnp.ndarray,   # (R, C)
    logits_idx: jnp.ndarray,  # (R,)
    mask: Optional[jnp.ndarray],   # (R, C, cache_len+1) bool or None
    cache_positions: Optional[jnp.ndarray],
    page_table: jnp.ndarray,  # (R, NP) int32
    *,
    cfg: DecoderConfig,
    cache_len: int,
    all_logits: bool = False,
    kernels: str = "xla",
    kv_quant: Optional[str] = None,
    fused_rope: bool = False,
    num_layers: Optional[int] = None,
    mesh=None,
    cp_mesh=None,
):
    """Paged twin of :func:`serve_step` — same contract plus the page
    table (see models/llama.py serve_step_paged; ``kv_quant`` selects
    the quantized pool layout, ``fused_rope`` the megakernel decode
    step's in-kernel RoPE + KV-write prologue on the Pallas path,
    ``num_layers`` the layer-sliced early-exit draft step, ``cp_mesh``
    the ring context-parallel attention over a sequence-sharded pool —
    ALiBi-bias families reject it, see serve_block_paged)."""
    if mesh is not None and mesh.shape.get(PIPE_AXIS, 1) > 1:
        raise NotImplementedError(
            "paged KV serving is not composed with pipeline parallelism "
            "yet — use kv_layout='dense' with pipe>1"
        )
    if cache_positions is None:
        cache_positions = positions
    x = _embed_in(cfg, params, tokens, positions)
    rope = rope_freqs(cfg, positions) if cfg.positions == "rope" else None
    phys, off, mask, bias, pos_pool = _paged_serve_context(
        cfg, cache, positions, cache_positions, mask, page_table, cache_len
    )
    logical = cache_positions // cache["k"].shape[2]

    n = cfg.num_hidden_layers
    if num_layers is not None:
        n = min(num_layers, n)
    sliced = n < cfg.num_hidden_layers
    layers = (
        jax.tree.map(lambda a: a[:n], params["layers"])
        if sliced else params["layers"]
    )

    if kv_quant is not None:
        from ..serve.kv_quant import resolve_spec

        qmax = resolve_spec(kv_quant).qmax

        def scan_body_q(h, xs):
            p_l, kc, vc, ks, vs = xs
            h, kc, vc, ks, vs = serve_block_paged(
                cfg, p_l, h, rope, bias, mask, kc, vc, phys, off,
                page_table, kernels, ks, vs, qmax,
                fused_rope=fused_rope, logical=logical, cp_mesh=cp_mesh,
            )
            return h, (kc, vc, ks, vs)

        x, (k_new, v_new, ks_new, vs_new) = lax.scan(
            scan_body_q, x,
            (layers, cache["k"][:n], cache["v"][:n],
             cache["k_scale"][:n], cache["v_scale"][:n]),
        )
        if sliced:
            k_new = jnp.concatenate([k_new, cache["k"][n:]], axis=0)
            v_new = jnp.concatenate([v_new, cache["v"][n:]], axis=0)
            ks_new = jnp.concatenate([ks_new, cache["k_scale"][n:]], axis=0)
            vs_new = jnp.concatenate([vs_new, cache["v_scale"][n:]], axis=0)
        new_cache = {"k": k_new, "v": v_new,
                     "k_scale": ks_new, "v_scale": vs_new}
    else:
        def scan_body(h, xs):
            p_l, kc, vc = xs
            h, kc, vc, _, _ = serve_block_paged(
                cfg, p_l, h, rope, bias, mask, kc, vc, phys, off,
                page_table, kernels,
                fused_rope=fused_rope, logical=logical, cp_mesh=cp_mesh,
            )
            return h, (kc, vc)

        x, (k_new, v_new) = lax.scan(
            scan_body, x, (layers, cache["k"][:n], cache["v"][:n])
        )
        if sliced:
            k_new = jnp.concatenate([k_new, cache["k"][n:]], axis=0)
            v_new = jnp.concatenate([v_new, cache["v"][n:]], axis=0)
        new_cache = {"k": k_new, "v": v_new}
    x = _norm(cfg, x, params["final_norm_scale"], params.get("final_norm_bias"))
    if not all_logits:
        x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)
        logits = _lm_logits(cfg, params, x)[:, 0]
    else:
        logits = _lm_logits(cfg, params, x)
    if needs_pos_cache(cfg):
        new_cache["pos"] = pos_pool
    return logits, new_cache


# ---------------------------------------------------------------------------
# Whole-step decode megakernel (see models/llama.py's twin and
# serve/kernels.whole_step_decode for the program design). The generic
# decoder supports the walk for the configs whose block math the
# streamed kernel body can run; the layout hook gates the rest with a
# construction-time error naming the fix.


def whole_step_weight_layout(
    params: Dict[str, Any], cfg: DecoderConfig
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Weight layout for blocked HBM→VMEM streaming (see the llama
    twin): ``(layer_arrays, head_arrays)``. Raises ValueError for
    configs the walk cannot serve: MoE FFNs (the routed expert einsums
    have no streamable per-layer block mapping yet), ALiBi /
    sliding-window families (attention needs the paged position buffer,
    which is not layer-streamed), and weight-only quantized params."""
    if cfg.num_local_experts:
        raise ValueError(
            "whole_step is not composed with mixture-of-experts FFNs — "
            "the routed expert contraction has no streamed per-layer "
            "weight block yet; drop the whole_step fusion for this "
            "family"
        )
    if needs_pos_cache(cfg):
        raise ValueError(
            "whole_step is not composed with ALiBi / sliding-window "
            "families — their attention reads the per-line position "
            "buffer, which the layer walk does not stream; drop the "
            "whole_step fusion for this family"
        )
    L = cfg.num_hidden_layers
    layer_arrays = {}
    for name, a in params["layers"].items():
        if isinstance(a, dict):
            raise ValueError(
                "whole_step is not composed with weight-only "
                f"quantization (layer tensor {name!r} is a quantized "
                "{'q','scale'} pair) — serve full-precision params or "
                "drop the whole_step fusion"
            )
        if a.shape[0] != L:
            raise ValueError(
                f"layer tensor {name!r} leading dim {a.shape[0]} != "
                f"num_hidden_layers {L}"
            )
        layer_arrays[name] = a
    head_arrays = {"final_norm_scale": params["final_norm_scale"]}
    if "final_norm_bias" in params:
        head_arrays["final_norm_bias"] = params["final_norm_bias"]
    if cfg.tie_word_embeddings:
        head_arrays["embed"] = params["embed"]
    else:
        if isinstance(params["lm_head"], dict):
            raise ValueError(
                "whole_step is not composed with a weight-only "
                "quantized lm_head"
            )
        head_arrays["lm_head"] = params["lm_head"]
        if "lm_head_bias" in params:
            head_arrays["lm_head_bias"] = params["lm_head_bias"]
    return layer_arrays, head_arrays


def _whole_head_fn(cfg: DecoderConfig, head, x, logits_idx):
    """Epilogue on values — op-for-op :func:`serve_step_paged`'s tail
    (final norm → logits row select → :func:`_lm_logits`)."""
    x = _norm(cfg, x, head["final_norm_scale"],
              head.get("final_norm_bias"))
    x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)
    hm = head["embed"].T if cfg.tie_word_embeddings else head["lm_head"]
    logits = jnp.matmul(x, hm, preferred_element_type=jnp.float32)
    if "lm_head_bias" in head:
        logits = logits + head["lm_head_bias"].astype(jnp.float32)
    return logits[:, 0]


def _whole_head_all_fn(cfg: DecoderConfig, head, x, logits_idx):
    """ALL-positions epilogue twin — op-for-op
    :func:`serve_step_paged`'s ``all_logits=True`` tail (final norm →
    LM head over every chunk column). The spec draft/verify fold's
    head (see the llama twin)."""
    del logits_idx
    x = _norm(cfg, x, head["final_norm_scale"],
              head.get("final_norm_bias"))
    hm = head["embed"].T if cfg.tie_word_embeddings else head["lm_head"]
    logits = jnp.matmul(x, hm, preferred_element_type=jnp.float32)
    if "lm_head_bias" in head:
        logits = logits + head["lm_head_bias"].astype(jnp.float32)
    return logits


def whole_step_tile_roles(
    cfg: DecoderConfig,
) -> Dict[str, Tuple[str, Optional[str]]]:
    """Sub-block streaming roles for the generic decoder
    (serve/kernels._whole_step_decode_tiled): the canonical
    column-tiled projection roles mapped to this family's weight and
    bias names — biases ride per cfg flag, "gate" only for GLU MLPs."""
    roles = {
        "q": ("wq", "bq" if cfg.qkv_bias else None),
        "k": ("wk", "bk" if cfg.qkv_bias else None),
        "v": ("wv", "bv" if cfg.qkv_bias else None),
        "o": ("wo", "bo" if cfg.out_bias else None),
        "up": ("w_up", "b_up" if cfg.mlp_bias else None),
        "down": ("w_down", "b_down" if cfg.mlp_bias else None),
    }
    if cfg.glu:
        roles["gate"] = ("w_gate", "b_gate" if cfg.mlp_bias else None)
    return roles


def _whole_tile_plan(cfg: DecoderConfig, qmax):
    """Closure bundle for the sub-block streaming walk — the SAME ops
    :func:`_block_paged_xla` runs, split at the projection boundaries
    (see the llama twin). ``mid_fn`` carries the parallel-block norm
    routing: parallel blocks feed the MLP the pre-attention norm (or
    their second norm), sequential blocks norm the post-attention
    residual."""
    from ..serve import kernels as _pk

    def pre_fn(p, x):
        return _norm(cfg, x, p["attn_norm_scale"],
                     p.get("attn_norm_bias"))

    def attend_fn(p, q, k, v, cs, sn, mask, kb, vb, ks, vs, ph, of, pt):
        dk = cfg.head_dim
        R, C, _ = q.shape
        q = q.reshape(R, C, -1, dk)
        k = k.reshape(R, C, -1, dk)
        v = v.reshape(R, C, -1, dk)
        if cs is not None:
            q, k = apply_rope(q, cs, sn), apply_rope(k, cs, sn)
        if qmax is not None:
            from ..serve.kv_quant import quant_line_write

            kb, ks = quant_line_write(kb, ks, ph, of, k, qmax)
            vb, vs = quant_line_write(vb, vs, ph, of, v, qmax)
        else:
            kb = kb.at[ph, of].set(k.astype(kb.dtype))
            vb = vb.at[ph, of].set(v.astype(vb.dtype))
        if qmax is not None:
            k_virt = _pk.dequant_pages(kb, ks, pt, q.dtype)
            v_virt = _pk.dequant_pages(vb, vs, pt, q.dtype)
        else:
            k_virt = _pk.gather_pages(kb, pt)
            v_virt = _pk.gather_pages(vb, pt)
        attn = _attend_paged_xla(cfg, q, k_virt, v_virt, None, mask)
        return attn, kb, vb, ks, vs

    def mid_fn(p, x, h, x2):
        if cfg.parallel_block:
            if cfg.parallel_two_norms:
                return _norm(cfg, x, p["mlp_norm_scale"],
                             p.get("mlp_norm_bias"))
            return h
        return _norm(cfg, x2, p["mlp_norm_scale"],
                     p.get("mlp_norm_bias"))

    def act_fn(g, u):
        if g is not None:
            return _activation(cfg, g) * u
        return _activation(cfg, u)

    return {
        "roles": whole_step_tile_roles(cfg),
        "mm_fn": _mm,
        "pre_fn": pre_fn,
        "attend_fn": attend_fn,
        "mid_fn": mid_fn,
        "act_fn": act_fn,
    }


def serve_step_whole(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,      # (R, C) int32 — C=1 decode, C>1 mixed
    positions: jnp.ndarray,   # (R, C) int32
    logits_idx: jnp.ndarray,  # (R,) int32
    page_table: jnp.ndarray,  # (R, NP) int32
    *,
    cfg: DecoderConfig,
    cache_len: int,
    kv_quant: Optional[str] = None,
    tp_mesh=None,
    collective: str = "exact",
    tiles: int = 1,
    mask: Optional[jnp.ndarray] = None,       # (R, C, cache_len+1) bool
    cache_positions: Optional[jnp.ndarray] = None,  # (R, C) cache lines
    all_logits: bool = False,
    num_layers: Optional[int] = None,
):
    """The WHOLE serving step as one program — the generic-decoder twin
    of models/llama.serve_step_whole (same contract: returns
    ``(logits, greedy_tokens, new_cache)``, bitwise the unfused
    kernels="xla" step on the same backend under the "exact"
    collective). ``C == 1`` is the decode step, ``C > 1`` the
    whole-step mixed step; ``tiles > 1`` streams each projection
    weight in output-column sub-tiles (the engine's VMEM gate picks
    the count — see the llama twin). The SPECULATION FOLD kwargs
    (explicit tree ``mask``, slack-line ``cache_positions``,
    ``all_logits``, early-exit ``num_layers``) turn one SpecInfer
    round's draft and verify passes into two dispatches of this one
    persistent program — see the llama twin; not composed with
    ``tiles > 1`` or the TP walk."""
    from ..serve.kernels import paged_serve_mask

    R, C = tokens.shape
    ps = cache["k"].shape[2]
    spec_fold = all_logits or num_layers is not None
    if spec_fold and tiles > 1:
        raise ValueError(
            "the whole-step speculation fold (all_logits/num_layers) is "
            "not composed with sub-block streaming (tiles > 1) — the "
            "tiled walk's epilogue emits the single decode logits row"
        )
    if cache_positions is None:
        cache_positions = positions
    x = _embed_in(cfg, params, tokens, positions)
    rope = rope_freqs(cfg, positions) if cfg.positions == "rope" else None
    mask = paged_serve_mask(
        mask, positions, page_table.shape[1], ps, cache_len
    )
    phys, off = _page_lookup(page_table, cache_positions, ps)
    qmax = None
    if kv_quant is not None:
        from ..serve.kv_quant import resolve_spec

        qmax = resolve_spec(kv_quant).qmax
    from ..core.mesh import MODEL_AXIS

    if tp_mesh is not None and tp_mesh.shape.get(MODEL_AXIS, 1) > 1:
        if tiles > 1:
            raise ValueError(
                "whole-step sub-block streaming (tiles > 1) is not "
                "composed with the TP walk — the collective-explicit "
                "path is per-layer XLA, not one kernel"
            )
        if spec_fold:
            raise ValueError(
                "the whole-step speculation fold (all_logits/num_layers) "
                "is not composed with the TP walk — the engine routes "
                "TP spec rounds through the unfused paged step"
            )
        return _serve_step_whole_tp(
            params, cache, x, rope, mask, phys, off, page_table,
            logits_idx, cfg=cfg, qmax=qmax, mesh=tp_mesh,
            collective=collective,
        )
    layer_arrays, head_arrays = whole_step_weight_layout(params, cfg)
    from ..serve import kernels as _pk

    cos, sin = rope if rope is not None else (None, None)

    n = cfg.num_hidden_layers
    if num_layers is not None:
        n = min(num_layers, n)
    sliced = n < cfg.num_hidden_layers
    walk_cache = cache
    if sliced:
        # early-exit draft fold: walk only the first n layers; deeper
        # pool rows are handed back untouched below (serve_step_paged's
        # num_layers contract)
        layer_arrays = {k: a[:n] for k, a in layer_arrays.items()}
        walk_cache = {k: a[:n] for k, a in cache.items()}

    def block_fn(p_l, xv, cs, sn, mk, kb, vb, ks, vs, ph, of, pt):
        rp = (cs, sn) if cs is not None else None
        return _block_paged_xla(
            cfg, p_l, xv, rp, None, mk, kb, vb, ph, of, pt, ks, vs, qmax
        )

    if all_logits:
        def head_fn(head, xv, li):
            return _whole_head_all_fn(cfg, head, xv, li)
    else:
        def head_fn(head, xv, li):
            return _whole_head_fn(cfg, head, xv, li)

    plan = _whole_tile_plan(cfg, qmax) if tiles > 1 else None
    logits, toks, new_cache = _pk.whole_step_decode(
        layer_arrays, head_arrays, x, cos, sin, walk_cache, page_table,
        phys, off, mask, logits_idx.astype(jnp.int32),
        block_fn=block_fn, head_fn=head_fn, tiles=tiles, tile_plan=plan,
    )
    if sliced:
        new_cache = {
            k: jnp.concatenate([new_cache[k], cache[k][n:]], axis=0)
            for k in new_cache
        }
    return logits, toks, new_cache


def _serve_step_whole_tp(params, cache, x, rope, mask, phys, off,
                         page_table, logits_idx, *, cfg, qmax, mesh,
                         collective):
    """The TP whole-step walk (see the llama twin): manual ``model``-
    axis shard_map, per-layer :func:`_block_paged_xla` with explicit
    :func:`..serve.collectives.tp_allreduce` row-parallel reductions."""
    from ..core.mesh import MODEL_AXIS, shard_map_unchecked
    from ..serve.collectives import tp_allreduce

    whole_step_weight_layout(params, cfg)  # capability gate, fail fast
    quant = qmax is not None
    tie = cfg.tie_word_embeddings
    has_rope = rope is not None

    def _model_only(spec):
        return P(*[MODEL_AXIS if s == MODEL_AXIS else None for s in spec])

    pspecs = param_pspecs(cfg)
    layer_specs = jax.tree.map(
        _model_only, pspecs["layers"], is_leaf=lambda s: isinstance(s, P)
    )
    cache_specs = {
        name: _model_only(spec)
        for name, spec in paged_kv_cache_pspecs(
            cfg, kv_quant="int8" if quant else None
        ).items()
    }
    cache_names = sorted(cache)
    head_names = ["final_norm_scale"]
    if "final_norm_bias" in params:
        head_names.append("final_norm_bias")
    if tie:
        head_names.append("embed")
    else:
        head_names.append("lm_head")
        if "lm_head_bias" in params:
            head_names.append("lm_head_bias")
    head_specs = [
        _model_only(pspecs[n]) if n in ("lm_head", "lm_head_bias")
        else P(*([None] * params[n].ndim))
        for n in head_names
    ]

    def body(layers, x_, mask_, phys_, off_, pt_, li_, *rest):
        nh = len(head_names)
        heads = dict(zip(head_names, rest[:nh]))
        i = nh
        if has_rope:
            rp = (rest[i], rest[i + 1])
            i += 2
        else:
            rp = None
        cc = dict(zip(cache_names, rest[i:]))

        def red(t):
            return tp_allreduce(t, MODEL_AXIS, collective)

        def scan_body(h, xs):
            if quant:
                p_l, kc, vc, ks, vs = xs
            else:
                p_l, kc, vc = xs
                ks = vs = None
            h, kc, vc, ks, vs = _block_paged_xla(
                cfg, p_l, h, rp, None, mask_, kc, vc, phys_, off_,
                pt_, ks, vs, qmax, reduce_fn=red,
            )
            return h, (kc, vc, ks, vs) if quant else (kc, vc)

        xs = (layers, cc["k"], cc["v"])
        if quant:
            xs = xs + (cc["k_scale"], cc["v_scale"])
        h, new = lax.scan(scan_body, x_, xs)
        h = _norm(cfg, h, heads["final_norm_scale"],
                  heads.get("final_norm_bias"))
        h = jnp.take_along_axis(h, li_[:, None, None], axis=1)
        if tie:
            logits = jnp.matmul(
                h, heads["embed"].T, preferred_element_type=jnp.float32
            )[:, 0]
        else:
            part = jnp.matmul(
                h, heads["lm_head"], preferred_element_type=jnp.float32
            )
            if "lm_head_bias" in heads:
                part = part + heads["lm_head_bias"].astype(jnp.float32)
            part = part[:, 0]  # (R, V/n)
            logits = jax.lax.all_gather(
                part, MODEL_AXIS, axis=1, tiled=True
            )
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out_cc = {"k": new[0], "v": new[1]}
        if quant:
            out_cc["k_scale"], out_cc["v_scale"] = new[2], new[3]
        return (logits, toks) + tuple(out_cc[nm] for nm in cache_names)

    rep3 = P(None, None, None)
    in_specs = [layer_specs, rep3, rep3, P(None, None), P(None, None),
                P(None, None), P(None)] + head_specs
    operands = [
        params["layers"], x, mask, phys.astype(jnp.int32),
        off.astype(jnp.int32), page_table.astype(jnp.int32),
        logits_idx.astype(jnp.int32),
    ] + [params[n] for n in head_names]
    if has_rope:
        in_specs += [rep3, rep3]
        operands += [rope[0], rope[1]]
    in_specs += [cache_specs[nm] for nm in cache_names]
    operands += [cache[nm] for nm in cache_names]
    out_specs = tuple(
        [P(None, None), P(None)] + [cache_specs[nm] for nm in cache_names]
    )
    fn = shard_map_unchecked(
        body, mesh, tuple(in_specs), out_specs, manual_axes={MODEL_AXIS},
    )
    outs = jax.jit(fn)(*operands)
    logits, toks = outs[0], outs[1]
    new_cache = dict(zip(cache_names, outs[2:]))
    return logits, toks, new_cache


def copy_page_kv(cache, src, dst):
    """Copy one physical page's lines to another page (prefix-cache
    copy-on-write; see models.llama.copy_page_kv) — the position pool
    pages like K/V but without the layer dim. Dtype-agnostic: quantized
    pools' int8 codes and their (L, P+1, KV) scale rows copy through
    the same pool-row scatter, so a COW'd page dequantizes identically
    to its original."""
    out = {}
    for name, buf in cache.items():
        if name == "pos":  # (P+1, ps)
            out[name] = buf.at[dst].set(buf[src])
        else:              # (L, P+1, ps|KV, ...)
            out[name] = buf.at[:, dst].set(buf[:, src])
    return out


def gather_page_kv(cache, page):
    """Slice one physical page out of every cache buffer (hierarchical-
    KV spill read; see models.llama.gather_page_kv) — the position pool
    pages like K/V but without the layer dim."""
    out = {}
    for name, buf in cache.items():
        if name == "pos":  # (P+1, ps)
            out[name] = buf[page]
        else:              # (L, P+1, ps|KV, ...)
            out[name] = buf[:, page]
    return out


def scatter_page_kv(cache, page, values):
    """Write a spilled page's content back into pool row ``page``
    (hierarchical-KV re-admit; see models.llama.scatter_page_kv)."""
    out = {}
    for name, buf in cache.items():
        if name == "pos":
            out[name] = buf.at[page].set(values[name])
        else:
            out[name] = buf.at[:, page].set(values[name])
    return out


def commit_kv_paged(cache, page_table, src, dst, *, kv_quant=None):
    """:func:`commit_kv` through the page table (see
    models.llama.commit_kv_paged); the position pool pages like K/V but
    without the layer dim. Quantized pools dequant-then-requant the
    moved lines so destination page scales stay exact (the position
    buffer still moves verbatim — it is exact int32 metadata)."""
    ps = cache["k"].shape[2]
    s_phys, s_off = _page_lookup(page_table, src, ps)
    d_phys, d_off = _page_lookup(page_table, dst, ps)
    if kv_quant is not None:
        from ..serve.kv_quant import quant_commit_lines, resolve_spec

        qmax = resolve_spec(kv_quant).qmax
        out = dict(cache)
        for name in ("k", "v"):
            out[name], out[name + "_scale"] = quant_commit_lines(
                cache[name], cache[name + "_scale"],
                s_phys, s_off, d_phys, d_off, qmax,
            )
        if "pos" in cache:
            out["pos"] = cache["pos"].at[d_phys, d_off].set(
                cache["pos"][s_phys, s_off]
            )
        return out
    out = {}
    for name, buf in cache.items():
        if name == "pos":  # (P+1, ps)
            out[name] = buf.at[d_phys, d_off].set(buf[s_phys, s_off])
        else:              # (L, P+1, ps, KV, dk)
            out[name] = buf.at[:, d_phys, d_off].set(buf[:, s_phys, s_off])
    return out


def reorder_slots_paged(cache, page_table, src):
    """Page-content copy between slots' own pages (see
    models.llama.reorder_slots_paged)."""
    src_pages = page_table[src].reshape(-1)
    dst_pages = page_table.reshape(-1)
    out = {}
    for name, buf in cache.items():
        if name == "pos":
            out[name] = buf.at[dst_pages].set(buf[src_pages])
        else:
            out[name] = buf.at[:, dst_pages].set(buf[:, src_pages])
    return out


def serve_debug_activations(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    cache_positions: Optional[jnp.ndarray] = None,
    *,
    cfg: DecoderConfig,
    kernels: str = "xla",
    page_table: Optional[jnp.ndarray] = None,
    cache_len: Optional[int] = None,
    kv_quant: Optional[str] = None,
):
    """Per-layer hidden-state capture for ``inference_debugging`` on the
    generic decoder — previously the hook only existed for LLaMA, making
    the switch a silent no-op for every other family (ADVICE.md round
    5). Eager Python loop so each layer's output survives as its own
    array; cache writes are computed and DISCARDED (the engine's
    donating step does the real commit). ``kernels`` is accepted for
    signature parity with the engine's call and ignored — the triage
    path is deliberately the plain XLA one."""
    del kernels  # triage runs the reference XLA math
    if cache_positions is None:
        cache_positions = positions
    x = _embed_in(cfg, params, tokens, positions)
    rope = rope_freqs(cfg, positions) if cfg.positions == "rope" else None
    acts = []
    if page_table is not None:  # paged layout
        phys, off, mask, bias, _ = _paged_serve_context(
            cfg, cache, positions, cache_positions, mask, page_table,
            cache_len,
        )
        qmax = None
        if kv_quant is not None:
            from ..serve.kv_quant import resolve_spec

            qmax = resolve_spec(kv_quant).qmax
        for l in range(cfg.num_hidden_layers):
            p_l = jax.tree.map(lambda a: a[l], params["layers"])
            x, *_ = serve_block_paged(
                cfg, p_l, x, rope, bias, mask,
                cache["k"][l], cache["v"][l], phys, off, page_table,
                "xla",
                cache["k_scale"][l] if qmax is not None else None,
                cache["v_scale"][l] if qmax is not None else None,
                qmax,
            )
            acts.append(x)
        return acts
    R = tokens.shape[0]
    S1 = cache["k"].shape[2]
    if mask is None:
        from ..serve.kernels import causal_serve_mask

        mask = causal_serve_mask(positions, S1)
    bias = None
    if needs_pos_cache(cfg):
        bidx = jnp.arange(R)[:, None]
        pos_cache = cache["pos"].at[bidx, cache_positions].set(
            positions.astype(jnp.int32)
        )
        if cfg.positions == "alibi":
            slopes = alibi_slopes(cfg.num_attention_heads)
            dist = (
                positions.astype(jnp.float32)[:, None, :, None]
                - pos_cache.astype(jnp.float32)[:, None, None, :]
            )
            bias = -slopes[None, :, None, None] * dist
        if cfg.sliding_window:
            mask = mask & (
                pos_cache[:, None, :]
                > positions[:, :, None] - cfg.sliding_window
            )
    for l in range(cfg.num_hidden_layers):
        p_l = jax.tree.map(lambda a: a[l], params["layers"])
        x, _, _ = serve_block(
            cfg, p_l, x, rope, bias, mask,
            cache["k"][l], cache["v"][l], cache_positions,
        )
        acts.append(x)
    return acts


def num_params(cfg: DecoderConfig) -> int:
    shapes = init_shapes(cfg)
    return sum(
        int(math.prod(s.shape)) for s in jax.tree.leaves(shapes)
    )
