"""Qwen2 model family — beyond the reference zoo (reference ships
llama/opt/falcon/mpt/starcoder, ``python/flexflow/serve/models``; Qwen2
is the same decoder recipe the zoo's generic engine already speaks:
RMSNorm + RoPE + GQA + SwiGLU, plus Q/K/V *biases* — the one knob that
distinguishes it from LLaMA). Runs on the generic decoder
(:mod:`.transformer`)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import layer_stackers, linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=151936,
        hidden_size=3584,
        intermediate_size=18944,
        num_hidden_layers=28,
        num_attention_heads=28,
        num_key_value_heads=4,
        max_position_embeddings=32768,
        norm_type="rmsnorm",
        norm_bias=False,
        norm_eps=1e-6,
        positions="rope",
        rope_theta=1000000.0,
        activation="silu",
        glu=True,
        parallel_block=False,
        qkv_bias=True,      # Qwen2's signature deviation from LLaMA
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
    )
    d.update(kw)
    return DecoderConfig(**d)


def qwen2_7b(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    if hf.get("model_type", "qwen2") != "qwen2":
        # qwen2_moe has its own family (models/qwen2_moe.py) and the
        # detect_family fallback matches longest-key-first, so only
        # genuinely unsupported variants (qwen2_vl etc.) land here —
        # their weights don't fit the dense decoder; fail with the
        # real reason
        raise NotImplementedError(
            f"model_type {hf['model_type']!r} is not dense Qwen2 "
            "(use the qwen2_moe family for MoE; VL is unsupported)"
        )
    if hf.get("use_sliding_window"):
        # the generic decoder runs full causal attention — silently
        # loading a sliding-window checkpoint would diverge from HF
        # beyond the window instead of erroring here
        raise NotImplementedError(
            "Qwen2 sliding-window attention (use_sliding_window=true) is "
            "not supported; load a full-attention checkpoint"
        )
    d = dict(
        vocab_size=hf.get("vocab_size", 151936),
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get(
            "num_key_value_heads", hf["num_attention_heads"]
        ),
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rope_theta=hf.get("rope_theta", 1000000.0),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(sd: Dict[str, Any], cfg: DecoderConfig) -> Dict[str, Any]:
    """HF ``Qwen2ForCausalLM`` state dict → framework pytree (stacked
    layer dim; HF linear weights transposed to (in, out) by linear_w)."""
    dt = cfg.dtype
    L = cfg.num_hidden_layers
    pre = "model."

    mats, vecs = layer_stackers(sd, pre, L, dt)

    layers = {
        "attn_norm_scale": vecs("layers.{}.input_layernorm.weight"),
        "mlp_norm_scale": vecs("layers.{}.post_attention_layernorm.weight"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "bq": vecs("layers.{}.self_attn.q_proj.bias"),
        "bk": vecs("layers.{}.self_attn.k_proj.bias"),
        "bv": vecs("layers.{}.self_attn.v_proj.bias"),
        "wo": mats("layers.{}.self_attn.o_proj.weight"),
        "w_gate": mats("layers.{}.mlp.gate_proj.weight"),
        "w_up": mats("layers.{}.mlp.up_proj.weight"),
        "w_down": mats("layers.{}.mlp.down_proj.weight"),
    }
    params = {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "norm.weight"]), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(linear_w(sd, "lm_head.weight"), dt)
    return params
