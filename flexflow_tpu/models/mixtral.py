"""Mixtral model family — sparse-MoE serving BEYOND the reference zoo
(the reference serves dense decoders only, ``inference/models/*.cc``;
its MoE support is the training-side expert ops). Runs on the generic
decoder (:mod:`.transformer`) with ``num_local_experts`` > 0: a linear
router takes the top-k experts per token (softmax over the selected k,
HF ``MixtralSparseMoeBlock`` semantics), expert weights shard over the
``expert`` mesh axis with Megatron TP inside each expert.

Architecture = LLaMA attention (RoPE, GQA, RMSNorm, no biases) + the
MoE FFN; weight conversion from HF ``MixtralForCausalLM``.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import layer_stackers, linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        max_position_embeddings=4096,
        norm_type="rmsnorm",
        norm_bias=False,
        norm_eps=1e-5,
        positions="rope",
        rope_theta=1e6,
        activation="silu",
        glu=True,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        num_local_experts=8,
        num_experts_per_tok=2,
    )
    d.update(kw)
    return DecoderConfig(**d)


def mixtral_8x7b(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        num_local_experts=4,
        num_experts_per_tok=2,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get(
            "num_key_value_heads", hf["num_attention_heads"]
        ),
        max_position_embeddings=hf["max_position_embeddings"],
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 1e6),
        num_local_experts=hf.get("num_local_experts", 8),
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        # early mixtral-8x7b configs ship sliding_window=4096; the
        # generic decoder enforces it (null/absent = full causal)
        sliding_window=hf.get("sliding_window") or 0,
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(
    sd: Dict[str, Any], cfg: DecoderConfig
) -> Dict[str, Any]:
    """HF ``MixtralForCausalLM`` state dict → framework pytree. HF per-
    expert names w1 (gate), w2 (down), w3 (up) map onto the generic
    decoder's glu layout: w_gate ← w1, w_down ← w2, w_up ← w3, each
    stacked (L, E, in, out)."""
    dt = cfg.dtype
    L, E = cfg.num_hidden_layers, cfg.num_local_experts
    pre = "model."

    mats, vecs = layer_stackers(sd, pre, L, dt)

    def experts(which):
        return stack(
            [
                np.stack(
                    [
                        linear_w(
                            sd,
                            pre + f"layers.{i}.block_sparse_moe."
                                  f"experts.{e}.{which}.weight",
                        )
                        for e in range(E)
                    ],
                    axis=0,
                )
                for i in range(L)
            ],
            dt,
        )

    layers = {
        "attn_norm_scale": vecs("layers.{}.input_layernorm.weight"),
        "mlp_norm_scale": vecs("layers.{}.post_attention_layernorm.weight"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "wo": mats("layers.{}.self_attn.o_proj.weight"),
        "w_router": mats("layers.{}.block_sparse_moe.gate.weight"),
        "w_gate": experts("w1"),
        "w_up": experts("w3"),
        "w_down": experts("w2"),
    }
    out: Dict[str, Any] = {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "norm.weight"]), dt),
    }
    if not cfg.tie_word_embeddings:
        out["lm_head"] = jnp.asarray(to_np(sd["lm_head.weight"]).T, dt)
    return out
