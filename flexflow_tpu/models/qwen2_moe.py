"""Qwen2-MoE model family (HF ``Qwen2MoeForCausalLM``, e.g.
Qwen1.5-MoE-A2.7B) — beyond the reference zoo. Runs on the generic
decoder's MoE path plus its Qwen2-MoE extensions: routed experts with
their own FFN width, softmax-over-all top-k WITHOUT renormalization
(``norm_topk_prob=False`` default), and an always-on sigmoid-gated
shared expert. Attention is Qwen2-style (RoPE, GQA, RMSNorm, QKV
biases)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import layer_stackers, linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=151936,
        hidden_size=2048,
        intermediate_size=5632,
        num_hidden_layers=24,
        num_attention_heads=16,
        num_key_value_heads=16,
        max_position_embeddings=8192,
        norm_type="rmsnorm",
        norm_bias=False,
        norm_eps=1e-6,
        positions="rope",
        rope_theta=1e6,
        activation="silu",
        glu=True,
        qkv_bias=True,
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        num_local_experts=60,
        num_experts_per_tok=4,
        moe_intermediate_size=1408,
        moe_shared_expert_intermediate_size=5632,
        moe_norm_topk=False,
    )
    d.update(kw)
    return DecoderConfig(**d)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        num_local_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=96,
        moe_shared_expert_intermediate_size=112,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    if hf.get("decoder_sparse_step", 1) != 1 or hf.get("mlp_only_layers"):
        # non-uniform layer mixtures (every-Nth-layer MoE / forced-dense
        # layers) would need per-layer FFN shapes in the scan
        raise NotImplementedError(
            "Qwen2-MoE with decoder_sparse_step != 1 or mlp_only_layers "
            "is not supported (non-uniform layer stacks)"
        )
    if hf.get("use_sliding_window"):
        raise NotImplementedError(
            "Qwen2-MoE sliding-window attention is not supported"
        )
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get(
            "num_key_value_heads", hf["num_attention_heads"]
        ),
        max_position_embeddings=hf["max_position_embeddings"],
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rope_theta=hf.get("rope_theta", 1e6),
        num_local_experts=hf.get("num_experts", 60),
        num_experts_per_tok=hf.get("num_experts_per_tok", 4),
        moe_intermediate_size=hf.get("moe_intermediate_size", 1408),
        moe_shared_expert_intermediate_size=hf.get(
            "shared_expert_intermediate_size", 5632
        ),
        moe_norm_topk=hf.get("norm_topk_prob", False),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(
    sd: Dict[str, Any], cfg: DecoderConfig
) -> Dict[str, Any]:
    """HF ``Qwen2MoeForCausalLM`` state dict → framework pytree."""
    dt = cfg.dtype
    L, E = cfg.num_hidden_layers, cfg.num_local_experts
    pre = "model."
    mats, vecs = layer_stackers(sd, pre, L, dt)

    def experts(which):
        return stack(
            [
                np.stack(
                    [
                        linear_w(
                            sd,
                            pre + f"layers.{i}.mlp.experts.{e}."
                                  f"{which}.weight",
                        )
                        for e in range(E)
                    ],
                    axis=0,
                )
                for i in range(L)
            ],
            dt,
        )

    layers = {
        "attn_norm_scale": vecs("layers.{}.input_layernorm.weight"),
        "mlp_norm_scale": vecs("layers.{}.post_attention_layernorm.weight"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "wo": mats("layers.{}.self_attn.o_proj.weight"),
        "bq": vecs("layers.{}.self_attn.q_proj.bias"),
        "bk": vecs("layers.{}.self_attn.k_proj.bias"),
        "bv": vecs("layers.{}.self_attn.v_proj.bias"),
        "w_router": mats("layers.{}.mlp.gate.weight"),
        "w_gate": experts("gate_proj"),
        "w_up": experts("up_proj"),
        "w_down": experts("down_proj"),
        "w_shared_up": mats("layers.{}.mlp.shared_expert.up_proj.weight"),
        "w_shared_gate": mats("layers.{}.mlp.shared_expert.gate_proj.weight"),
        "w_shared_down": mats("layers.{}.mlp.shared_expert.down_proj.weight"),
        "shared_expert_gate": mats("layers.{}.mlp.shared_expert_gate.weight"),
    }
    out: Dict[str, Any] = {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "norm.weight"]), dt),
    }
    if not cfg.tie_word_embeddings:
        out["lm_head"] = jnp.asarray(to_np(sd["lm_head.weight"]).T, dt)
    return out
