"""GPT-2 model family (HF ``GPT2LMHeadModel``) — beyond the reference
zoo. Runs on the generic decoder: learned absolute positions, pre-LN
blocks with biases everywhere, gelu_tanh FFN, MHA, tied embeddings.
The converter splits HF's fused ``c_attn`` QKV projection and keeps
Conv1D's (in, out) orientation (HF GPT-2 Conv1D stores weights
UN-transposed, unlike nn.Linear — no ``linear_w`` flip here)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=50257,
        hidden_size=768,
        intermediate_size=3072,
        num_hidden_layers=12,
        num_attention_heads=12,
        num_key_value_heads=12,
        max_position_embeddings=1024,
        norm_type="layernorm",
        norm_bias=True,
        norm_eps=1e-5,
        positions="learned",
        learned_pos_offset=0,
        activation="gelu_tanh",
        glu=False,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        tie_word_embeddings=True,
    )
    d.update(kw)
    return DecoderConfig(**d)


def gpt2_small(**kw) -> DecoderConfig:
    return config(**kw)


def gpt2_xl(**kw) -> DecoderConfig:
    d = dict(
        hidden_size=1600,
        intermediate_size=6400,
        num_hidden_layers=48,
        num_attention_heads=25,
        num_key_value_heads=25,
    )
    d.update(kw)
    return config(**d)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


_HF_ACTS = {
    "gelu_new": "gelu_tanh",
    "gelu_pytorch_tanh": "gelu_tanh",
    "gelu_fast": "gelu_tanh",
    "gelu": "gelu",
    "relu": "relu",
    "silu": "silu",
}


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    mt = hf.get("model_type", "gpt2")
    if mt != "gpt2":
        raise NotImplementedError(
            f"model_type {mt!r} is not GPT-2"
        )
    # attention variants this engine does not implement must fail
    # loudly, not generate silently-wrong tokens
    for knob in ("scale_attn_by_inverse_layer_idx", "reorder_and_upcast_attn"):
        if hf.get(knob):
            raise NotImplementedError(f"GPT-2 {knob}=True is not supported")
    if not hf.get("scale_attn_weights", True):
        raise NotImplementedError(
            "GPT-2 scale_attn_weights=False is not supported"
        )
    act = hf.get("activation_function", "gelu_new")
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["n_embd"],
        intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
        num_hidden_layers=hf["n_layer"],
        num_attention_heads=hf["n_head"],
        num_key_value_heads=hf["n_head"],
        max_position_embeddings=hf["n_positions"],
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        activation=_HF_ACTS.get(act, act),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(
    sd: Dict[str, Any], cfg: DecoderConfig
) -> Dict[str, Any]:
    """HF ``GPT2LMHeadModel`` state dict → framework pytree."""
    from .hf_utils import layer_stackers

    dt = cfg.dtype
    D = cfg.hidden_size
    L = cfg.num_hidden_layers
    pre = "transformer." if "transformer.wte.weight" in sd else ""
    _, vecs = layer_stackers(sd, pre, L, dt)
    # Conv1D already stores (in, out) — the raw vecs stacker is exactly
    # right for matmul kernels too (no linear_w transpose)
    conv1d = vecs

    # one pass per layer: slice q|k|v out of the fused c_attn
    # (D, 3D) weight / (3D,) bias without re-converting it three times
    parts: Dict[str, list] = {k: [] for k in ("wq", "wk", "wv",
                                              "bq", "bk", "bv")}
    for i in range(L):
        w = to_np(sd[pre + f"h.{i}.attn.c_attn.weight"])
        b = to_np(sd[pre + f"h.{i}.attn.c_attn.bias"])
        for s, name in enumerate("qkv"):
            parts[f"w{name}"].append(w[:, s * D:(s + 1) * D])
            parts[f"b{name}"].append(b[s * D:(s + 1) * D])
    wq, wk, wv = (stack(parts[n], dt) for n in ("wq", "wk", "wv"))
    bq, bk, bv = (stack(parts[n], dt) for n in ("bq", "bk", "bv"))
    layers = {
        "attn_norm_scale": vecs("h.{}.ln_1.weight"),
        "attn_norm_bias": vecs("h.{}.ln_1.bias"),
        "mlp_norm_scale": vecs("h.{}.ln_2.weight"),
        "mlp_norm_bias": vecs("h.{}.ln_2.bias"),
        "wq": wq, "wk": wk, "wv": wv,
        "bq": bq, "bk": bk, "bv": bv,
        "wo": conv1d("h.{}.attn.c_proj.weight"),
        "bo": vecs("h.{}.attn.c_proj.bias"),
        "w_up": conv1d("h.{}.mlp.c_fc.weight"),
        "b_up": vecs("h.{}.mlp.c_fc.bias"),
        "w_down": conv1d("h.{}.mlp.c_proj.weight"),
        "b_down": vecs("h.{}.mlp.c_proj.bias"),
    }
    return {
        "embed": jnp.asarray(to_np(sd[pre + "wte.weight"]), dt),
        "pos_embed": jnp.asarray(to_np(sd[pre + "wpe.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "ln_f.weight"]), dt),
        "final_norm_bias": jnp.asarray(to_np(sd[pre + "ln_f.bias"]), dt),
    }
