"""LLaMA model family — the flagship architecture.

TPU-native equivalent of the reference's LLaMA builder (reference
``inference/models/llama.cc:23-280`` and ``python/flexflow/serve/models/
llama.py``): embedding → N × [rms_norm → attention(QKV+RoPE+GQA) →
residual_rms_norm → SwiGLU FFN] → rms_norm → lm_head → decode head.

Design differences from the reference, chosen for TPU:
  * **Stacked layers + ``lax.scan``**: all N layers' weights live in one
    pytree with a leading layer dim. One compiled block serves every
    layer (fast compile), the layer dim shards over the ``pipe`` axis for
    pipeline parallelism, and ``jax.checkpoint`` remats per block.
  * **bf16 compute / f32 accumulate** on the MXU via
    ``preferred_element_type``.
  * Training (full causal, :func:`block`) and serving (KV-cache
    prefill/decode/verify, :func:`serve_block`) share the projection and
    FFN math; serving batch layout comes from flexflow_tpu/serve.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS


@dataclasses.dataclass(frozen=True)
class LLaMAConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    dtype: Any = jnp.bfloat16
    tie_word_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def llama_7b(cls, **kw):
        return cls(**kw)

    @classmethod
    def llama_160m(cls, **kw):
        """The reference's standard SSM speculator (JackFram/llama-160m)."""
        d = dict(
            hidden_size=768,
            intermediate_size=3072,
            num_hidden_layers=12,
            num_attention_heads=12,
            num_key_value_heads=12,
        )
        d.update(kw)
        return cls(**d)

    @classmethod
    def tiny(cls, **kw):
        d = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=128,
        )
        d.update(kw)
        return cls(**d)

    @classmethod
    def from_hf(cls, hf: Dict[str, Any], **kw) -> "LLaMAConfig":
        d = dict(
            vocab_size=hf.get("vocab_size", 32000),
            hidden_size=hf.get("hidden_size", 4096),
            intermediate_size=hf.get("intermediate_size", 11008),
            num_hidden_layers=hf.get("num_hidden_layers", 32),
            num_attention_heads=hf.get("num_attention_heads", 32),
            num_key_value_heads=hf.get(
                "num_key_value_heads", hf.get("num_attention_heads", 32)
            ),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            rope_theta=hf.get("rope_theta", 10000.0),
            max_position_embeddings=hf.get("max_position_embeddings", 2048),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
        )
        d.update(kw)
        return cls(**d)


# ---------------------------------------------------------------------------
# RoPE (HF rotate-half convention; reference supports native + HF variants,
# inc_multihead_self_attention.cu:487)


def rope_freqs(cfg: LLaMAConfig, positions: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """positions (...,) int32 → cos/sin (..., head_dim)."""
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., half)
    angles = jnp.concatenate([angles, angles], axis=-1)  # (..., head_dim)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: (..., heads, head_dim); cos/sin broadcast over the head axis."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos[..., None, :] + rotated * sin[..., None, :]).astype(x.dtype)


# ---------------------------------------------------------------------------
# Parameters


def init_params(key, cfg: LLaMAConfig) -> Dict[str, Any]:
    L, D, F = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    dt = cfg.dtype
    ks = jax.random.split(key, 8)

    def norm_init(std, k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(dt)

    std = 0.02
    params = {
        "embed": norm_init(std, ks[0], (cfg.vocab_size, D)),
        "layers": {
            "attn_norm": jnp.ones((L, D), dt),
            "wq": norm_init(std, ks[1], (L, D, H * dk)),
            "wk": norm_init(std, ks[2], (L, D, KV * dk)),
            "wv": norm_init(std, ks[3], (L, D, KV * dk)),
            "wo": norm_init(std / math.sqrt(2 * L), ks[4], (L, H * dk, D)),
            "ffn_norm": jnp.ones((L, D), dt),
            "w1": norm_init(std, ks[5], (L, D, F)),
            "w2": norm_init(std / math.sqrt(2 * L), ks[6], (L, F, D)),
            "w3": norm_init(std, ks[7], (L, D, F)),
        },
        "final_norm": jnp.ones((D,), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm_init(std, jax.random.fold_in(key, 99), (D, cfg.vocab_size))
    return params


def param_pspecs(cfg: LLaMAConfig, *, pipeline: bool = False) -> Dict[str, Any]:
    """Megatron TP shardings (reference's hardcoded TP rewrite,
    model.cc:3239-3312): QKV/up column-parallel, O/down row-parallel on
    the ``model`` axis. With ``pipeline`` the stacked layer dim shards
    over ``pipe``."""
    pp = PIPE_AXIS if pipeline else None
    specs = {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(pp, None),
            "wq": P(pp, None, MODEL_AXIS),
            "wk": P(pp, None, MODEL_AXIS),
            "wv": P(pp, None, MODEL_AXIS),
            "wo": P(pp, MODEL_AXIS, None),
            "ffn_norm": P(pp, None),
            "w1": P(pp, None, MODEL_AXIS),
            "w2": P(pp, MODEL_AXIS, None),
            "w3": P(pp, None, MODEL_AXIS),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, MODEL_AXIS)
    return specs


# ---------------------------------------------------------------------------
# Forward


def _rms(x, gamma, eps):
    xf = x.astype(jnp.float32)
    r = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return ((xf * r).astype(x.dtype)) * gamma


def _mm(x, w):
    if isinstance(w, dict):  # int8/int4 weight-only quantization
        from ..quantization import dequantize

        w = dequantize(w, x.dtype)
    return jnp.matmul(x, w, preferred_element_type=jnp.float32).astype(x.dtype)


def attention(
    cfg: LLaMAConfig,
    q: jnp.ndarray,  # (B, S, H, dk) — rope applied
    k: jnp.ndarray,  # (B, T, KV, dk)
    v: jnp.ndarray,  # (B, T, KV, dk)
    mask: Optional[jnp.ndarray],  # (B, S, T) or (S, T) bool, True = attend
) -> jnp.ndarray:
    H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
    if KV != H:  # GQA: repeat KV heads
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum(
        "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(cfg.head_dim)
    if mask is not None:
        m = mask if mask.ndim == 3 else mask[None]
        scores = jnp.where(m[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def block(
    cfg: LLaMAConfig,
    p: Dict[str, jnp.ndarray],  # one layer's params (no L dim)
    x: jnp.ndarray,  # (B, S, D)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    attn_fn=None,  # override for sequence-parallel attention
):
    """One transformer block, training path (full local-sequence
    attention). The serving path with KV cache is :func:`serve_block`.
    Returns (x_out, None) — the None slot keeps the scan-body signature
    stable across train/serve variants."""
    B, S, D = x.shape
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim

    h = _rms(x, p["attn_norm"], cfg.rms_norm_eps)
    q = _mm(h, p["wq"]).reshape(B, S, H, dk)
    k = _mm(h, p["wk"]).reshape(B, S, KV, dk)
    v = _mm(h, p["wv"]).reshape(B, S, KV, dk)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = (attn_fn or attention)(cfg, q, k, v, mask)

    x = x + _mm(attn.reshape(B, S, H * dk), p["wo"])
    h2 = _rms(x, p["ffn_norm"], cfg.rms_norm_eps)
    ffn = _mm(jax.nn.silu(_mm(h2, p["w1"])) * _mm(h2, p["w3"]), p["w2"])
    return x + ffn, None


def causal_mask(S: int) -> jnp.ndarray:
    return jnp.tril(jnp.ones((S, S), bool))


def make_flash_attention(block_q: int = 128, block_k: int = 128):
    """Causal flash-attention attn_fn (Pallas kernel with custom VJP,
    ops/flash_attention.py): scores stream through VMEM instead of
    materialising the (B, H, S, S) tensor the XLA path writes to HBM."""
    from ..ops.flash_attention import flash_attention

    def attn_fn(cfg, q, k, v, mask):
        # mask is None by construction (forward() skips building it when
        # an attn_fn is supplied); causality is computed in-kernel
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        if KV != H:
            rep = H // KV
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return flash_attention(
            q, k, v, causal=True, block_q=block_q, block_k=block_k
        )

    return attn_fn


def make_sp_attention(mesh, impl: str = "ring"):
    """Build a sequence-parallel attention override for :func:`block`
    (ring ppermute or Ulysses all-to-all over the ``seq`` axis — the
    long-context capability the reference lacks, SURVEY.md §7 step 7)."""
    from ..parallel.sequence import ring_attention, ulysses_attention

    fn = ring_attention if impl == "ring" else ulysses_attention

    def attn_fn(cfg, q, k, v, mask):
        # K/V stay compact (GQA/MQA); the SP primitives expand per block
        # so ring ppermute traffic is KV-sized, not H-sized.
        return fn(
            q, k, v, mesh, causal=True,
            shard_heads=mesh.shape[MODEL_AXIS] > 1,
        )

    return attn_fn


def _remat_policy(name):
    """See :func:`flexflow_tpu.core.remat.resolve_remat_policy` (shared
    across model families and the fused graph-IR ops)."""
    from ..core.remat import resolve_remat_policy

    return resolve_remat_policy(name)


def forward(
    params: Dict[str, Any],
    tokens: jnp.ndarray,  # (B, S) int32
    cfg: LLaMAConfig,
    *,
    positions: Optional[jnp.ndarray] = None,
    remat: bool = False,
    remat_policy: Optional[str] = None,
    shard_activations: bool = False,
    attn_fn=None,
) -> jnp.ndarray:
    """Training/eval forward: full causal attention, returns logits
    (B, S, V). ``attn_fn`` overrides the attention computation (see
    :func:`make_sp_attention` for ring/Ulysses sequence parallelism)."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
    cos, sin = rope_freqs(cfg, positions)
    # SP attention derives causality from global positions — never
    # materialise the S×S mask on the long-context path.
    mask = None if attn_fn is not None else causal_mask(S)

    def constrain(t):
        if shard_activations:
            return lax.with_sharding_constraint(
                t, P(DATA_AXIS, SEQ_AXIS, None)
            )
        return t

    x = constrain(x)

    blk = functools.partial(block, cfg, attn_fn=attn_fn)
    if remat:
        blk = jax.checkpoint(blk, policy=_remat_policy(remat_policy))

    def scan_body(carry, p_l):
        y, _ = blk(p_l, carry, cos, sin, mask)
        return constrain(y), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    x = _rms(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.matmul(x, head, preferred_element_type=jnp.float32)


def next_token_loss(params, tokens, cfg, **kw) -> jnp.ndarray:
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1]."""
    logits = forward(params, tokens[:, :-1], cfg, **kw)
    targets = tokens[:, 1:].astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(
    cfg: LLaMAConfig,
    mesh,
    optimizer,
    *,
    num_microbatches: int = 1,
    remat: bool = True,
    remat_policy: Optional[str] = None,  # None (full) | "dots"
    shard_activations: bool = True,
    attention: str = "xla",  # "xla" | "flash" (Pallas, ops/flash_attention)
):
    """Build (init_fn, step_fn) jitted over ``mesh`` with the full
    dp/tp/pp/sp sharding stack.

    * dp: batch dim sharded on ``data`` (GSPMD all-reduces grads).
    * tp: Megatron weight shardings from :func:`param_pspecs` (GSPMD
      inserts the QKV/FFN all-reduces over ICI).
    * sp: activation sequence dim constrained to the ``seq`` axis.
    * pp (when mesh has pipe>1): GPipe microbatching via
      ``parallel.pipeline`` — the stacked layer dim is sharded over
      ``pipe`` and only that axis runs manually under shard_map.
    """
    from jax.sharding import NamedSharding

    pipeline = mesh.shape[PIPE_AXIS] > 1
    pspecs = param_pspecs(cfg, pipeline=pipeline)
    shardings = jax.tree.map(
        lambda p: NamedSharding(mesh, p), pspecs, is_leaf=lambda x: isinstance(x, P)
    )

    def init_fn(key):
        params = jax.jit(
            functools.partial(init_params, cfg=cfg), out_shardings=shardings
        )(key)
        opt_state = optimizer.init(params)
        return params, opt_state

    if not pipeline:
        sp = mesh.shape[SEQ_AXIS] > 1
        if sp:
            if attention == "flash":
                # explicit kernel choices must not be silently ignored
                from ..logging_utils import get_logger

                get_logger("model").warning(
                    "attention='flash' requested but the mesh has seq=%d: "
                    "sequence parallelism uses ring attention instead "
                    "(flash+SP composition is not implemented)",
                    mesh.shape[SEQ_AXIS],
                )
            attn_fn = make_sp_attention(mesh, "ring")
        elif attention == "flash":
            attn_fn = make_flash_attention()
        else:
            attn_fn = None

        def loss_fn(params, tokens):
            return next_token_loss(
                params,
                tokens,
                cfg,
                remat=remat,
                remat_policy=remat_policy,
                shard_activations=shard_activations and sp,
                attn_fn=attn_fn,
            )

    else:
        assert mesh.shape[SEQ_AXIS] == 1, (
            "sequence parallelism is not composed with the pipeline path "
            "yet: pipe>1 with seq>1 would fall back to dense attention "
            "over the gathered sequence (O(S^2) memory)"
        )
        from ..parallel.pipeline import make_pipelined_apply

        flash = attention == "flash"
        blk = functools.partial(
            block, cfg, attn_fn=make_flash_attention() if flash else None
        )
        if remat:
            blk = jax.checkpoint(blk, policy=_remat_policy(remat_policy))

        def loss_fn(params, tokens):
            B, S = tokens.shape
            Sm = S - 1
            inp, targets = tokens[:, :-1], tokens[:, 1:].astype(jnp.int32)
            x = jnp.take(params["embed"], inp.astype(jnp.int32), axis=0)
            if shard_activations and mesh.shape[SEQ_AXIS] > 1:
                x = lax.with_sharding_constraint(x, P(DATA_AXIS, SEQ_AXIS, None))
            cos, sin = rope_freqs(cfg, jnp.arange(Sm, dtype=jnp.int32))
            mask = None if flash else causal_mask(Sm)

            def block_stack(stage_layers, x_mb):
                def body(carry, p_l):
                    y, _ = blk(p_l, carry, cos, sin, mask)
                    return y, None

                y, _ = lax.scan(body, x_mb, stage_layers)
                return y

            mb = B // num_microbatches
            x_mb = x.reshape(num_microbatches, mb, Sm, cfg.hidden_size)
            piped = make_pipelined_apply(
                mesh,
                block_stack,
                num_microbatches=num_microbatches,
                params_spec=jax.tree.map(
                    lambda _: P(PIPE_AXIS), params["layers"]
                ),
            )
            y = piped(params["layers"], x_mb).reshape(B, Sm, cfg.hidden_size)
            y = _rms(y, params["final_norm"], cfg.rms_norm_eps)
            head = (
                params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
            )
            logits = jnp.matmul(y, head, preferred_element_type=jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            return nll.mean()

    def step_fn(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        params, opt_state = optimizer.update(grads, opt_state, params)
        return params, opt_state, loss

    data_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    step = jax.jit(step_fn, donate_argnums=(0, 1))
    return init_fn, step, data_sharding


# ---------------------------------------------------------------------------
# Serving path (KV cache). One step function serves prefill (chunk C>1),
# incremental decode (C=1), and SpecInfer tree-verify (explicit mask) —
# the TPU-native counterpart of the reference's three attention operators
# (inc/spec/tree_inc_multihead_self_attention, SURVEY.md §2.1): instead of
# three CUDA kernels there is one compiled XLA program per static
# (C, all_logits, mask-mode) signature, all sharing the same KV buffers.


def init_kv_cache(
    cfg: LLaMAConfig, num_slots: int, max_len: int, dtype=None
) -> Dict[str, jnp.ndarray]:
    """KV cache pytree: (L, slots, max_len+1, KV, dk). The last position is
    a scratch row — padding tokens scatter there so real cache lines are
    never corrupted (replaces the reference's per-request contiguous cache
    with request-slot paging, inc_multihead_self_attention.cu:1338)."""
    L, KV, dk = cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim
    dt = dtype or cfg.dtype
    shape = (L, num_slots, max_len + 1, KV, dk)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def kv_cache_pspecs(
    cfg: Optional[LLaMAConfig] = None, *, pipeline: bool = False
) -> Dict[str, P]:
    """Cache shards over TP on the KV-head dim (same axis the attention
    heads shard on) and over DP on the slot dim; with ``pipeline`` the
    layer-major leading dim shards over ``pipe`` so each stage holds the
    cache for its own layers."""
    pp = PIPE_AXIS if pipeline else None
    return {
        "k": P(pp, DATA_AXIS, None, MODEL_AXIS, None),
        "v": P(pp, DATA_AXIS, None, MODEL_AXIS, None),
    }


def serve_attention(cfg: LLaMAConfig, q, k_cache, v_cache, mask):
    """Grouped-query attention of q (R, C, H, dk) against the full cache
    (R, S, KV, dk) without materialising the GQA head repeat: q is viewed
    as (R, C, KV, G, dk) and contracted per KV group."""
    R, C, H, dk = q.shape
    KV = cfg.num_key_value_heads
    G = H // KV
    qg = q.reshape(R, C, KV, G, dk)
    scores = jnp.einsum(
        "rckgd,rskd->rkgcs", qg, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(cfg.head_dim)
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("rkgcs,rskd->rckgd", probs, v_cache)
    return out.reshape(R, C, H * dk)


def serve_block(cfg: LLaMAConfig, p, x, cos, sin, mask, k_cache, v_cache,
                positions, kernels: str = "xla"):
    """One transformer block on a serving step: project, RoPE, scatter new
    K/V into the cache at ``positions`` (cache line indices — for tree
    tokens these differ from the RoPE positions baked into cos/sin),
    attend over the whole cache. ``kernels="pallas"`` routes attention
    through the fused flash-style TPU kernels (serve/kernels.py: decode
    for C==1, tree-verify otherwise — the reference's
    inc/tree_inc_multihead_self_attention CUDA kernels)."""
    R, C, D = x.shape
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    h = _rms(x, p["attn_norm"], cfg.rms_norm_eps)
    q = _mm(h, p["wq"]).reshape(R, C, H, dk)
    k = _mm(h, p["wk"]).reshape(R, C, KV, dk)
    v = _mm(h, p["wv"]).reshape(R, C, KV, dk)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    bidx = jnp.arange(R)[:, None]
    k_cache = k_cache.at[bidx, positions].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[bidx, positions].set(v.astype(v_cache.dtype))
    if kernels == "pallas":
        from ..serve import kernels as _pk

        if C == 1:
            seq_lens = mask[:, 0, :].sum(axis=-1).astype(jnp.int32)
            attn = _pk.decode_attention(q[:, 0], k_cache, v_cache, seq_lens)
            attn = attn.reshape(R, 1, H * dk)
        else:
            attn = _pk.verify_attention(q, k_cache, v_cache, mask)
            attn = attn.reshape(R, C, H * dk)
    else:
        attn = serve_attention(cfg, q, k_cache, v_cache, mask)
    x = x + _mm(attn, p["wo"])
    h2 = _rms(x, p["ffn_norm"], cfg.rms_norm_eps)
    ffn = _mm(jax.nn.silu(_mm(h2, p["w1"])) * _mm(h2, p["w3"]), p["w2"])
    return x + ffn, k_cache, v_cache


def serve_step(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,     # (R, C) int32; padding points at scratch pos
    positions: jnp.ndarray,  # (R, C) int32 RoPE/sequence positions
    logits_idx: jnp.ndarray, # (R,) int32 chunk index whose logits to return
    mask: Optional[jnp.ndarray],  # (R, C, S+1) bool, or None => causal
    cache_positions: Optional[jnp.ndarray] = None,  # (R, C) cache line idx
    *,
    cfg: LLaMAConfig,
    all_logits: bool = False,
    kernels: str = "xla",
    num_layers: Optional[int] = None,
    mesh=None,
):
    """One serving step over R request slots × C tokens each.

    ``cache_positions`` defaults to ``positions``; SpecInfer passes them
    separately because sibling tree tokens share a sequence position
    (prefix + depth) but need distinct cache lines (prefix + node index).

    With a ``mesh`` whose pipe axis is >1, the layer stack (and the
    layer-major KV cache) is stage-sharded and activations flow through
    the pipeline (reference inference_manager.cc:91-133 stage mapping).

    ``num_layers`` runs a LAYER-SLICED step: only the first
    ``num_layers`` blocks execute (their K/V commit into the cache; the
    deeper layers' cache buffers pass through untouched) before the
    full model's final norm + head read the truncated hidden state —
    the self-speculation "early-exit" draft (LayerSkip-style,
    SpecConfig.draft="early_exit"): the target's own shallow prefix
    drafts tokens the full-depth verify pass then re-checks. None
    (default) = the full stack.

    Returns (logits, new_cache): logits (R, V) at ``logits_idx`` or
    (R, C, V) when ``all_logits`` (tree verification needs every token's
    logits, reference tree_inc_multihead_self_attention.cu).
    """
    R, C = tokens.shape
    S1 = cache["k"].shape[2]  # max_len + 1 (scratch row)
    if cache_positions is None:
        cache_positions = positions
    x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
    cos, sin = rope_freqs(cfg, positions)
    if mask is None:
        # Causal-by-position (serve/kernels.causal_serve_mask): a token
        # attends every cache line at position <= its own. Only
        # positions already written satisfy this, so stale lines from an
        # evicted request are never read.
        from ..serve.kernels import causal_serve_mask

        mask = causal_serve_mask(positions, S1)

    def scan_body(h, xs):
        p_l, kc, vc = xs
        h, kc, vc = serve_block(
            cfg, p_l, h, cos, sin, mask, kc, vc, cache_positions, kernels
        )
        return h, (kc, vc)

    if mesh is not None and mesh.shape[PIPE_AXIS] > 1:
        if num_layers is not None:
            raise NotImplementedError(
                "early-exit drafting (num_layers) is not composed with "
                "pipeline parallelism — the sliced stack would idle the "
                "deeper stages"
            )

        from ..parallel.pipeline import make_pipelined_serve

        def stage_fn(stage_layers, caches, h, row):
            kc, vc = caches

            def body(hh, xs):
                p_l, kcl, vcl = xs
                hh, kcl, vcl = serve_block(
                    cfg, p_l, hh, row["cos"], row["sin"], row["mask"],
                    kcl, vcl, row["cpos"], kernels,
                )
                return hh, (kcl, vcl)

            h, (kc, vc) = lax.scan(body, h, (stage_layers, kc, vc))
            return h, (kc, vc)

        row = {"cos": cos, "sin": sin, "mask": mask, "cpos": cache_positions}
        piped = make_pipelined_serve(
            mesh,
            stage_fn,
            params_spec=jax.tree.map(lambda _: P(PIPE_AXIS), params["layers"]),
            cache_spec=(
                P(PIPE_AXIS, DATA_AXIS),
                P(PIPE_AXIS, DATA_AXIS),
            ),
            row_specs={k: P(DATA_AXIS) for k in row},
        )
        x, (k_new, v_new) = piped(
            params["layers"], (cache["k"], cache["v"]), x, row
        )
    elif num_layers is not None and num_layers < cfg.num_hidden_layers:
        n = num_layers
        x, (k_upd, v_upd) = lax.scan(
            scan_body, x,
            (jax.tree.map(lambda a: a[:n], params["layers"]),
             cache["k"][:n], cache["v"][:n]),
        )
        # deeper layers never run: their cache rows pass through intact
        # (the verify pass owns them)
        k_new = jnp.concatenate([k_upd, cache["k"][n:]], axis=0)
        v_new = jnp.concatenate([v_upd, cache["v"][n:]], axis=0)
    else:
        x, (k_new, v_new) = lax.scan(
            scan_body, x, (params["layers"], cache["k"], cache["v"])
        )
    x = _rms(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    if not all_logits:
        x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)  # (R,1,D)
        logits = jnp.matmul(x, head, preferred_element_type=jnp.float32)[:, 0]
    else:
        logits = jnp.matmul(x, head, preferred_element_type=jnp.float32)
    return logits, {"k": k_new, "v": v_new}


def serve_debug_activations(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    cache_positions: Optional[jnp.ndarray] = None,
    *,
    cfg: LLaMAConfig,
    kernels: str = "xla",
    page_table: Optional[jnp.ndarray] = None,
    cache_len: Optional[int] = None,
    kv_quant: Optional[str] = None,
):
    """Per-layer hidden-state capture for ``inference_debugging``
    (reference's per-op tensor dump mode, serve/__init__.py:48 —
    saving all inputs/outputs to file for serving triage). Runs the
    layer stack as an eager Python loop instead of ``lax.scan`` so every
    layer's output survives as its own array; cache writes are computed
    and DISCARDED (the caller's donating step does the real commit).
    Deliberately slow — a triage tool, not a serving path. With
    ``page_table`` the paged layout is read/written through the table
    (``kv_quant``: the quantized pool, dequantized per layer)."""
    if cache_positions is None:
        cache_positions = positions
    x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
    cos, sin = rope_freqs(cfg, positions)
    acts = []
    if page_table is not None:  # paged layout
        ps = cache["k"].shape[2]
        mask = _paged_mask(mask, positions, page_table, ps, cache_len)
        phys, off = _page_lookup(page_table, cache_positions, ps)
        qmax = None
        if kv_quant is not None:
            from ..serve.kv_quant import resolve_spec

            qmax = resolve_spec(kv_quant).qmax
        for l in range(cfg.num_hidden_layers):
            p_l = jax.tree.map(lambda a: a[l], params["layers"])
            x, *_ = serve_block_paged(
                cfg, p_l, x, cos, sin, mask,
                cache["k"][l], cache["v"][l], phys, off, page_table,
                kernels,
                cache["k_scale"][l] if qmax is not None else None,
                cache["v_scale"][l] if qmax is not None else None,
                qmax,
            )
            acts.append(x)
        return acts
    S1 = cache["k"].shape[2]
    if mask is None:
        from ..serve.kernels import causal_serve_mask

        mask = causal_serve_mask(positions, S1)
    for l in range(cfg.num_hidden_layers):
        p_l = jax.tree.map(lambda a: a[l], params["layers"])
        x, _, _ = serve_block(
            cfg, p_l, x, cos, sin, mask,
            cache["k"][l], cache["v"][l], cache_positions, kernels,
        )
        acts.append(x)
    return acts


# ---------------------------------------------------------------------------
# Paged serving path (Ragged Paged Attention layout, PAPERS.md arxiv
# 2604.15464): K/V live in a pool of fixed-size token pages shared by all
# request slots; each slot's page table maps logical cache lines
# (line // page_size) to physical pages. HBM is proportional to pages
# allocated — live tokens — instead of slots × max_len, which is what
# lets one chip serve the reference's 64 request slots. The XLA path
# gathers the virtual cache through the table with ``jnp.take`` and runs
# the exact dense serve_attention math (bit-for-bit parity with the
# dense layout); ``kernels="pallas"`` routes through the fused ragged
# paged kernel (serve/kernels.py) which DMAs pages directly.

#: decode-step fusions this family's serving step supports
#: (ServingConfig.fused_decode; the engine validates requests against
#: this). "rope_kv_write": serve_step_paged folds RoPE + the KV page
#: write into the ragged paged Pallas kernel (the megakernel decode
#: step). "whole_step": the FULL decode step runs as one persistent
#: layer-walking Pallas program (:func:`serve_step_whole`). The
#: "sampling" epilogue fusion is model-agnostic — it lives in the
#: engine's step program — so it is not listed here.
FUSED_DECODE = ("rope_kv_write", "whole_step")


def init_paged_kv_cache(
    cfg: LLaMAConfig, num_pages: int, page_size: int, dtype=None,
    kv_quant: Optional[str] = None, extra_rows: int = 0,
) -> Dict[str, jnp.ndarray]:
    """Paged pool: (L, num_pages+1, page_size, KV, dk). Pool row
    ``num_pages`` is the shared scratch page — unallocated page-table
    entries point there, so padding writes and gathers through
    unallocated entries never touch live pages (the paged analog of the
    dense layout's per-slot scratch row).

    With ``kv_quant`` (serve/kv_quant.py) the pools store quantized
    codes — int8, or packed int4 nibbles (two codes per byte along dk,
    so the trailing dim is ``head_dim // 2``) — and the cache gains
    ``k_scale``/``v_scale``: (L, num_pages+1, KV) f32
    per-page-per-KV-head amax scales, zero-initialised (a zero scale
    marks a page with no committed lines).

    ``extra_rows`` appends never-referenced pad rows AFTER the scratch
    row — context-parallel serving (ServingConfig.kv_shard="context")
    shards pool rows over the mesh ``seq`` axis and pads the row count
    to a multiple of the shard degree; no table entry ever points past
    the scratch row, so the pads are pure alignment."""
    L, KV, dk = cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim
    dt = dtype or cfg.dtype
    spec = None
    if kv_quant is not None:
        from ..serve.kv_quant import resolve_spec

        spec = resolve_spec(kv_quant)
        dt = spec.dtype
        if dk % spec.pack:
            raise ValueError(
                f"kv_quant={kv_quant!r} packs {spec.pack} codes per "
                f"element along head_dim, which needs head_dim "
                f"({dk}) divisible by {spec.pack}"
            )
        dk = dk // spec.pack
    rows = num_pages + 1 + int(extra_rows)
    shape = (L, rows, page_size, KV, dk)
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if spec is not None:
        sshape = (L, rows, KV)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return cache


def paged_kv_cache_pspecs(
    cfg: Optional[LLaMAConfig] = None, *, pipeline: bool = False,
    kv_quant: Optional[str] = None, kv_shard: Optional[str] = None,
) -> Dict[str, P]:
    """Pages shard over DP on the pool dim, KV heads over TP on the
    model axis (same head axis the attention shards on) — tensor-
    parallel serving keeps working; MQA (KV=1) replicates as in the
    dense layout. Quantized pools shard their per-page scale rows the
    same way (pages on data, KV heads on model). With
    ``kv_shard="context"`` pool rows shard over the SEQ axis instead —
    each sequence shard holds its own slice of one request's pages
    (ring ragged paged attention reads them locally;
    serve/kernels.ring_ragged_paged_attention)."""
    kv_axis = (
        None if (cfg is not None and cfg.num_key_value_heads == 1)
        else MODEL_AXIS
    )
    page_axis = SEQ_AXIS if kv_shard == "context" else DATA_AXIS
    pp = PIPE_AXIS if pipeline else None
    specs = {
        "k": P(pp, page_axis, None, kv_axis, None),
        "v": P(pp, page_axis, None, kv_axis, None),
    }
    if kv_quant is not None:
        specs["k_scale"] = P(pp, page_axis, kv_axis)
        specs["v_scale"] = P(pp, page_axis, kv_axis)
    return specs


def _page_lookup(page_table: jnp.ndarray, cache_positions: jnp.ndarray,
                 page_size: int):
    """(R, NP) table × (R, C) cache lines → physical page + in-page
    offset, each (R, C)."""
    logical = cache_positions // page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    return phys, cache_positions % page_size


def _mm_reduced(x, w, reduce_fn):
    """``_mm`` with a tensor-parallel partial-sum chokepoint: the
    reduction applies to the f32 matmul output BEFORE the model-dtype
    cast — exactly where GSPMD inserts its all-reduce for a
    row-parallel matmul, so the collective-explicit whole-step walk
    stays bitwise the GSPMD-scheduled step. ``None`` is literally
    :func:`_mm` (the single-shard path is untouched)."""
    if reduce_fn is None:
        return _mm(x, w)
    out = jnp.matmul(x, w, preferred_element_type=jnp.float32)
    return reduce_fn(out).astype(x.dtype)


def _attend_paged_xla(cfg: LLaMAConfig, q, k_virt, v_virt, mask):
    """:func:`serve_attention` with head counts derived from the
    OPERANDS instead of cfg — op-for-op identical on the single-shard
    path (where they agree), and what lets the same body serve the
    TP-local head shards of the whole-step walk."""
    R, C, H, dk = q.shape
    KV = k_virt.shape[2]
    G = H // KV
    qg = q.reshape(R, C, KV, G, dk)
    scores = jnp.einsum(
        "rckgd,rskd->rkgcs", qg, k_virt, preferred_element_type=jnp.float32
    ) / math.sqrt(cfg.head_dim)
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("rkgcs,rskd->rckgd", probs, v_virt)
    return out.reshape(R, C, H * dk)


def _block_paged_xla(cfg: LLaMAConfig, p, x, cos, sin, mask,
                     k_pool, v_pool, phys, off, page_table,
                     k_scale=None, v_scale=None, qmax=None,
                     reduce_fn=None):
    """One block of the UNFUSED XLA paged step, on values: project,
    RoPE, commit K/V at the table-resolved (page, offset) — quantizing
    at the page scales when ``qmax`` is set — gather the virtual cache
    through the table, attend, out-project, FFN. This is the ONE
    definition shared by :func:`serve_block_paged`'s ``kernels="xla"``
    path and the whole-step decode megakernel / TP walk
    (:func:`serve_step_whole`) — sharing the body is what makes
    whole-step decode BITWISE the unfused XLA step. ``reduce_fn`` is
    the row-parallel partial reduction of the collective-explicit TP
    walk (see :func:`_mm_reduced`); None on the single-shard path."""
    dk = cfg.head_dim
    R, C, D = x.shape
    h = _rms(x, p["attn_norm"], cfg.rms_norm_eps)
    q = _mm(h, p["wq"]).reshape(R, C, -1, dk)
    k = _mm(h, p["wk"]).reshape(R, C, -1, dk)
    v = _mm(h, p["wv"]).reshape(R, C, -1, dk)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if qmax is not None:
        from ..serve.kv_quant import quant_line_write

        k_pool, k_scale = quant_line_write(k_pool, k_scale, phys, off, k,
                                           qmax)
        v_pool, v_scale = quant_line_write(v_pool, v_scale, phys, off, v,
                                           qmax)
    else:
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
    from ..serve import kernels as _pk

    if qmax is not None:
        k_virt = _pk.dequant_pages(k_pool, k_scale, page_table, q.dtype)
        v_virt = _pk.dequant_pages(v_pool, v_scale, page_table, q.dtype)
    else:
        k_virt = _pk.gather_pages(k_pool, page_table)
        v_virt = _pk.gather_pages(v_pool, page_table)
    attn = _attend_paged_xla(cfg, q, k_virt, v_virt, mask)
    x = x + _mm_reduced(attn, p["wo"], reduce_fn)
    h2 = _rms(x, p["ffn_norm"], cfg.rms_norm_eps)
    ffn = _mm_reduced(
        jax.nn.silu(_mm(h2, p["w1"])) * _mm(h2, p["w3"]), p["w2"], reduce_fn
    )
    return x + ffn, k_pool, v_pool, k_scale, v_scale


def serve_block_paged(cfg: LLaMAConfig, p, x, cos, sin, mask,
                      k_pool, v_pool, phys, off, page_table,
                      kernels: str = "xla",
                      k_scale=None, v_scale=None, qmax=None,
                      *, fused_rope: bool = False, logical=None,
                      cp_mesh=None):
    """One block on a paged serving step: scatter new K/V at the
    table-resolved (physical page, offset), attend over the virtual
    cache read through the page table. With ``qmax`` (quantized pool,
    serve/kv_quant.py) the KV commit quantizes in the step itself —
    per-page amax scales, rescale-on-growth — and attention dequantizes
    at read time (in-kernel on the Pallas path), so full-precision K/V
    never round-trip HBM. Returns
    ``(x, k_pool, v_pool, k_scale, v_scale)`` (scales None when the
    pool is full-precision).

    ``fused_rope`` (the megakernel decode step,
    ``ServingConfig.fused_decode``): on the Pallas path the RoPE on
    Q/K and the (optionally quantizing) KV page write move INSIDE the
    ragged paged kernel (serve/kernels.fused_rope_paged_attention) —
    the fresh K/V lines never round-trip HBM between this block's
    projection and its attention read. Bitwise-identical to the
    unfused composition below; on kernels="xla" the flag is a no-op
    because the unfused XLA step IS the CPU-parity fallback. On a
    sequence-sharded mesh (``cp_mesh``) the fused prologue joins the
    RING body instead (PR-11's exclusion, lifted): each shard rotates
    Q/K and commits its resident lines inside the shard_map program —
    serve/kernels.ring_ragged_paged_attention's ``fused`` mode."""
    R, C, D = x.shape
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    from ..serve import kernels as _pk

    if cp_mesh is None and kernels != "pallas":
        # the unfused XLA path — the CPU-parity reference every fusion
        # (and the whole-step megakernel) anchors on; ONE shared body
        return _block_paged_xla(
            cfg, p, x, cos, sin, mask, k_pool, v_pool, phys, off,
            page_table, k_scale, v_scale, qmax,
        )
    h = _rms(x, p["attn_norm"], cfg.rms_norm_eps)
    q = _mm(h, p["wq"]).reshape(R, C, H, dk)
    k = _mm(h, p["wk"]).reshape(R, C, KV, dk)
    v = _mm(h, p["wv"]).reshape(R, C, KV, dk)

    if fused_rope and kernels == "pallas" and cp_mesh is None:
        attn, k_pool, v_pool, k_scale, v_scale = (
            _pk.fused_rope_paged_attention(
                q, k, v, cos, sin, k_pool, v_pool, page_table,
                logical, off, mask,
                k_scale=k_scale, v_scale=v_scale, qmax=qmax,
            )
        )
        attn = attn.reshape(R, C, H * dk)
        x = x + _mm(attn, p["wo"])
        h2 = _rms(x, p["ffn_norm"], cfg.rms_norm_eps)
        ffn = _mm(jax.nn.silu(_mm(h2, p["w1"])) * _mm(h2, p["w3"]), p["w2"])
        return x + ffn, k_pool, v_pool, k_scale, v_scale
    if fused_rope and kernels == "pallas" and cp_mesh is not None:
        # ring fused prologue: RoPE + the resident-line commit move
        # inside the per-shard shard_map body (full-precision pools;
        # the quantized combination raises loudly in the kernel and is
        # excluded at ServingConfig validation)
        attn, k_pool, v_pool = _pk.ring_ragged_paged_attention(
            q, k_pool, v_pool, page_table, mask, cp_mesh,
            fused=dict(k_new=k, v_new=v, cos=cos, sin=sin,
                       phys=phys, off=off),
        )
        attn = attn.reshape(R, C, H * dk)
        x = x + _mm(attn, p["wo"])
        h2 = _rms(x, p["ffn_norm"], cfg.rms_norm_eps)
        ffn = _mm(jax.nn.silu(_mm(h2, p["w1"])) * _mm(h2, p["w3"]), p["w2"])
        return x + ffn, k_pool, v_pool, k_scale, v_scale
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if qmax is not None:
        from ..serve.kv_quant import quant_line_write

        k_pool, k_scale = quant_line_write(k_pool, k_scale, phys, off, k, qmax)
        v_pool, v_scale = quant_line_write(v_pool, v_scale, phys, off, v, qmax)
    else:
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
    if cp_mesh is not None:
        # context-parallel attention over the sequence-sharded pool:
        # each seq shard attends its resident pages, partial softmax
        # stats rotate via ppermute (the chunked-prefill KV write above
        # already landed on the owning shard — GSPMD routes the
        # replicated-index scatter to the sharded rows)
        attn = _pk.ring_ragged_paged_attention(
            q, k_pool, v_pool, page_table, mask, cp_mesh,
            k_scale=k_scale, v_scale=v_scale,
        )
        attn = attn.reshape(R, C, H * dk)
    else:  # kernels == "pallas" (the xla path returned above)
        attn = _pk.ragged_paged_attention(
            q, k_pool, v_pool, page_table, mask,
            k_scale=k_scale, v_scale=v_scale,
        )
        attn = attn.reshape(R, C, H * dk)
    x = x + _mm(attn, p["wo"])
    h2 = _rms(x, p["ffn_norm"], cfg.rms_norm_eps)
    ffn = _mm(jax.nn.silu(_mm(h2, p["w1"])) * _mm(h2, p["w3"]), p["w2"])
    return x + ffn, k_pool, v_pool, k_scale, v_scale


def _paged_mask(mask, positions, page_table, page_size, cache_len):
    """Default causal-by-position mask over the virtual cache, or an
    explicit (R, C, cache_len+1) mask padded out to the page-aligned
    virtual length (serve/kernels.paged_serve_mask — shared with the
    generic decoder)."""
    from ..serve.kernels import paged_serve_mask

    return paged_serve_mask(
        mask, positions, page_table.shape[1], page_size, cache_len
    )


def serve_step_paged(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,      # (R, C)
    positions: jnp.ndarray,   # (R, C) RoPE/sequence positions
    logits_idx: jnp.ndarray,  # (R,)
    mask: Optional[jnp.ndarray],  # (R, C, cache_len+1) bool or None
    cache_positions: Optional[jnp.ndarray],  # (R, C) cache line idx
    page_table: jnp.ndarray,  # (R, NP) int32
    *,
    cfg: LLaMAConfig,
    cache_len: int,
    all_logits: bool = False,
    kernels: str = "xla",
    kv_quant: Optional[str] = None,
    fused_rope: bool = False,
    num_layers: Optional[int] = None,
    mesh=None,
    cp_mesh=None,
):
    """Paged twin of :func:`serve_step` — same contract plus the
    per-slot page table; prefill chunks, single-token decode and
    tree-verify all read/write K/V through the table. ``kv_quant``
    selects the quantized pool layout (serve/kv_quant.py): the KV
    commit quantizes in-step and attention dequantizes at read time.
    ``fused_rope`` (megakernel decode step) folds RoPE and the KV page
    write into the Pallas kernel per block — a no-op on the XLA path,
    which already is the fused variants' CPU-parity reference.
    ``num_layers`` is the layer-sliced early-exit draft step (see
    :func:`serve_step`): only the first ``num_layers`` blocks run and
    commit K/V; deeper pool rows (and their quant scale rows) pass
    through untouched for the verify pass to own. ``cp_mesh`` (context
    parallelism, ServingConfig.kv_shard="context" on a sequence-
    sharded mesh) routes every block's attention through ring ragged
    paged attention over the seq-sharded pool
    (serve/kernels.ring_ragged_paged_attention)."""
    if mesh is not None and mesh.shape.get(PIPE_AXIS, 1) > 1:
        raise NotImplementedError(
            "paged KV serving is not composed with pipeline parallelism "
            "yet — use kv_layout='dense' with pipe>1"
        )
    if cache_positions is None:
        cache_positions = positions
    ps = cache["k"].shape[2]
    x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
    cos, sin = rope_freqs(cfg, positions)
    mask = _paged_mask(mask, positions, page_table, ps, cache_len)
    phys, off = _page_lookup(page_table, cache_positions, ps)
    logical = cache_positions // ps

    n = cfg.num_hidden_layers
    if num_layers is not None:
        n = min(num_layers, n)
    sliced = n < cfg.num_hidden_layers
    layers = (
        jax.tree.map(lambda a: a[:n], params["layers"])
        if sliced else params["layers"]
    )

    if kv_quant is not None:
        from ..serve.kv_quant import resolve_spec

        qmax = resolve_spec(kv_quant).qmax

        def scan_body_q(h, xs):
            p_l, kc, vc, ks, vs = xs
            h, kc, vc, ks, vs = serve_block_paged(
                cfg, p_l, h, cos, sin, mask, kc, vc, phys, off,
                page_table, kernels, ks, vs, qmax,
                fused_rope=fused_rope, logical=logical, cp_mesh=cp_mesh,
            )
            return h, (kc, vc, ks, vs)

        x, (k_new, v_new, ks_new, vs_new) = lax.scan(
            scan_body_q, x,
            (layers, cache["k"][:n], cache["v"][:n],
             cache["k_scale"][:n], cache["v_scale"][:n]),
        )
        if sliced:
            k_new = jnp.concatenate([k_new, cache["k"][n:]], axis=0)
            v_new = jnp.concatenate([v_new, cache["v"][n:]], axis=0)
            ks_new = jnp.concatenate([ks_new, cache["k_scale"][n:]], axis=0)
            vs_new = jnp.concatenate([vs_new, cache["v_scale"][n:]], axis=0)
        new_cache = {"k": k_new, "v": v_new,
                     "k_scale": ks_new, "v_scale": vs_new}
    else:
        def scan_body(h, xs):
            p_l, kc, vc = xs
            h, kc, vc, _, _ = serve_block_paged(
                cfg, p_l, h, cos, sin, mask, kc, vc, phys, off,
                page_table, kernels,
                fused_rope=fused_rope, logical=logical, cp_mesh=cp_mesh,
            )
            return h, (kc, vc)

        x, (k_new, v_new) = lax.scan(
            scan_body, x, (layers, cache["k"][:n], cache["v"][:n])
        )
        if sliced:
            k_new = jnp.concatenate([k_new, cache["k"][n:]], axis=0)
            v_new = jnp.concatenate([v_new, cache["v"][n:]], axis=0)
        new_cache = {"k": k_new, "v": v_new}
    x = _rms(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    if not all_logits:
        x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)
        logits = jnp.matmul(x, head, preferred_element_type=jnp.float32)[:, 0]
    else:
        logits = jnp.matmul(x, head, preferred_element_type=jnp.float32)
    return logits, new_cache


# ---------------------------------------------------------------------------
# Whole-step decode megakernel (ServingConfig.fused_decode=("whole_step",);
# serve/kernels.whole_step_decode carries the program design). The model
# family's half of the contract: the weight layout for blocked streaming
# and the step entry point that binds this family's block/head math —
# the SAME ``_block_paged_xla`` body the unfused XLA step runs, which is
# the bitwise guarantee.


def whole_step_weight_layout(
    params: Dict[str, Any], cfg: LLaMAConfig
) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Weight layout for blocked HBM→VMEM streaming: returns
    ``(layer_arrays, head_arrays)`` — every per-layer tensor as one
    stacked ``(L, ...)`` array (already this family's storage layout;
    the hook VALIDATES and names the streams rather than copying) plus
    the resident epilogue params. Raises ValueError for layouts the
    walk cannot stream — weight-only quantized params ({"q","scale"}
    dicts have no single streamable block per layer yet) — so the
    engine fails at construction, not mid-serve."""
    L = cfg.num_hidden_layers
    layer_arrays = {}
    for name, a in params["layers"].items():
        if isinstance(a, dict):
            raise ValueError(
                "whole_step is not composed with weight-only "
                f"quantization (layer tensor {name!r} is a quantized "
                "{'q','scale'} pair) — serve full-precision params or "
                "drop the whole_step fusion"
            )
        if a.shape[0] != L:
            raise ValueError(
                f"layer tensor {name!r} leading dim {a.shape[0]} != "
                f"num_hidden_layers {L}"
            )
        layer_arrays[name] = a
    head_arrays = {"final_norm": params["final_norm"]}
    if cfg.tie_word_embeddings:
        head_arrays["embed"] = params["embed"]
    else:
        if isinstance(params["lm_head"], dict):
            raise ValueError(
                "whole_step is not composed with a weight-only "
                "quantized lm_head"
            )
        head_arrays["lm_head"] = params["lm_head"]
    return layer_arrays, head_arrays


def _whole_head_fn(cfg: LLaMAConfig, head, x, logits_idx):
    """Epilogue on values — op-for-op :func:`serve_step_paged`'s tail
    (final norm → logits row select → LM head)."""
    x = _rms(x, head["final_norm"], cfg.rms_norm_eps)
    hm = head["embed"].T if cfg.tie_word_embeddings else head["lm_head"]
    x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)
    return jnp.matmul(x, hm, preferred_element_type=jnp.float32)[:, 0]


def _whole_head_all_fn(cfg: LLaMAConfig, head, x, logits_idx):
    """ALL-positions epilogue twin — op-for-op
    :func:`serve_step_paged`'s ``all_logits=True`` tail (final norm →
    LM head over every chunk column, no row select). The spec
    draft/verify fold dispatches the whole-step walk with this head:
    the verifier needs logits at every tree node, the draft pass at
    every frontier column."""
    del logits_idx
    x = _rms(x, head["final_norm"], cfg.rms_norm_eps)
    hm = head["embed"].T if cfg.tie_word_embeddings else head["lm_head"]
    return jnp.matmul(x, hm, preferred_element_type=jnp.float32)


def whole_step_tile_roles(
    cfg: LLaMAConfig,
) -> Dict[str, Tuple[str, Optional[str]]]:
    """Sub-block streaming roles for this family
    (serve/kernels._whole_step_decode_tiled): which per-layer weight
    each canonical column-tiled role names, plus its bias (LLaMA
    projections are bias-free). w1 gates, w3 lifts, w2 closes — the
    SwiGLU naming of :func:`_block_paged_xla`."""
    return {
        "q": ("wq", None), "k": ("wk", None), "v": ("wv", None),
        "o": ("wo", None), "gate": ("w1", None), "up": ("w3", None),
        "down": ("w2", None),
    }


def _whole_tile_plan(cfg: LLaMAConfig, qmax):
    """Closure bundle for the sub-block streaming walk — the SAME ops
    :func:`_block_paged_xla` runs, split at the projection boundaries
    so the kernel can column-tile each matmul (the elementwise and
    residual pieces act slice-locally, so the tiled walk stays bitwise
    the unfused step)."""
    from ..serve import kernels as _pk

    def pre_fn(p, x):
        return _rms(x, p["attn_norm"], cfg.rms_norm_eps)

    def attend_fn(p, q, k, v, cs, sn, mask, kb, vb, ks, vs, ph, of, pt):
        dk = cfg.head_dim
        R, C, _ = q.shape
        q = q.reshape(R, C, -1, dk)
        k = k.reshape(R, C, -1, dk)
        v = v.reshape(R, C, -1, dk)
        q = apply_rope(q, cs, sn)
        k = apply_rope(k, cs, sn)
        if qmax is not None:
            from ..serve.kv_quant import quant_line_write

            kb, ks = quant_line_write(kb, ks, ph, of, k, qmax)
            vb, vs = quant_line_write(vb, vs, ph, of, v, qmax)
        else:
            kb = kb.at[ph, of].set(k.astype(kb.dtype))
            vb = vb.at[ph, of].set(v.astype(vb.dtype))
        if qmax is not None:
            k_virt = _pk.dequant_pages(kb, ks, pt, q.dtype)
            v_virt = _pk.dequant_pages(vb, vs, pt, q.dtype)
        else:
            k_virt = _pk.gather_pages(kb, pt)
            v_virt = _pk.gather_pages(vb, pt)
        attn = _attend_paged_xla(cfg, q, k_virt, v_virt, mask)
        return attn, kb, vb, ks, vs

    def mid_fn(p, x, h, x2):
        return _rms(x2, p["ffn_norm"], cfg.rms_norm_eps)

    def act_fn(g, u):
        return jax.nn.silu(g) * u

    return {
        "roles": whole_step_tile_roles(cfg),
        "mm_fn": _mm,
        "pre_fn": pre_fn,
        "attend_fn": attend_fn,
        "mid_fn": mid_fn,
        "act_fn": act_fn,
    }


def serve_step_whole(
    params: Dict[str, Any],
    cache: Dict[str, jnp.ndarray],
    tokens: jnp.ndarray,      # (R, C) int32 — C=1 decode, C>1 mixed
    positions: jnp.ndarray,   # (R, C) int32
    logits_idx: jnp.ndarray,  # (R,) int32 (zeros at C=1)
    page_table: jnp.ndarray,  # (R, NP) int32
    *,
    cfg: LLaMAConfig,
    cache_len: int,
    kv_quant: Optional[str] = None,
    tp_mesh=None,
    collective: str = "exact",
    tiles: int = 1,
    mask: Optional[jnp.ndarray] = None,       # (R, C, cache_len+1) bool
    cache_positions: Optional[jnp.ndarray] = None,  # (R, C) cache lines
    all_logits: bool = False,
    num_layers: Optional[int] = None,
):
    """The WHOLE serving step as one program (ROADMAP 5a/5b,
    MPK-style): embedding, all L layers (QKV → RoPE + KV page commit →
    ragged paged attention → out-proj → MLP), final norm, LM head and
    the greedy sampling epilogue. ``C == 1`` is the decode step;
    ``C > 1`` is the whole-step MIXED step — chunked prefill and decode
    rows walk the same persistent program, each row's head read at its
    own ``logits_idx``. Single-shard meshes run it as ONE persistent
    Pallas program whose grid walks the layers with double-buffered
    weight streaming (serve/kernels.whole_step_decode); ``tiles > 1``
    (the engine's VMEM gate, for layers whose working set exceeds the
    budget) streams each projection weight in output-column sub-tiles
    over an inner grid dimension instead of falling back. TP meshes
    run the collective-explicit walk — the same per-layer body under a
    manual ``model``-axis shard_map with ONE
    ``serve/collectives.tp_allreduce`` per row-parallel matmul
    (quantized EQuARX codes when ``collective="int8"``, literally
    ``lax.psum`` in "exact" mode), still one dispatched program.

    Returns ``(logits (R, V) f32, greedy_tokens (R,) int32,
    new_cache)``. Bitwise contract: logits, greedy tokens and
    non-scratch pool bytes are identical to
    :func:`serve_step_paged`(kernels="xla") on the same backend (exact
    collective mode; "int8" is a documented-tolerance trade) — at any
    tile count, because tiles split only matmul OUTPUT columns.

    The SPECULATION FOLD rides the same four optional kwargs
    :func:`serve_step_paged` grew for it: an explicit tree ``mask``,
    ``cache_positions`` for slack-line K/V placement, ``all_logits``
    (logits at every chunk column — the all-positions head twin
    :func:`_whole_head_all_fn`) and ``num_layers`` (the early-exit
    draft walks only the first k grid steps; deeper pool rows pass
    through untouched for the verify pass to own). With them the
    draft pass and the verify pass of one SpecInfer round become two
    dispatches of this ONE persistent program — same streamed weight
    blocks, bitwise the unfused spec round. Not composed with
    sub-block streaming (``tiles > 1``) or the TP walk."""
    R, C = tokens.shape
    ps = cache["k"].shape[2]
    spec_fold = all_logits or num_layers is not None
    if spec_fold and tiles > 1:
        raise ValueError(
            "the whole-step speculation fold (all_logits/num_layers) is "
            "not composed with sub-block streaming (tiles > 1) — the "
            "tiled walk's epilogue emits the single decode logits row"
        )
    if cache_positions is None:
        cache_positions = positions
    x = jnp.take(params["embed"], tokens.astype(jnp.int32), axis=0)
    cos, sin = rope_freqs(cfg, positions)
    mask = _paged_mask(mask, positions, page_table, ps, cache_len)
    phys, off = _page_lookup(page_table, cache_positions, ps)
    qmax = None
    if kv_quant is not None:
        from ..serve.kv_quant import resolve_spec

        qmax = resolve_spec(kv_quant).qmax
    from ..core.mesh import MODEL_AXIS

    if tp_mesh is not None and tp_mesh.shape.get(MODEL_AXIS, 1) > 1:
        if tiles > 1:
            raise ValueError(
                "whole-step sub-block streaming (tiles > 1) is not "
                "composed with the TP walk — the collective-explicit "
                "path is per-layer XLA, not one kernel"
            )
        if spec_fold:
            raise ValueError(
                "the whole-step speculation fold (all_logits/num_layers) "
                "is not composed with the TP walk — the engine routes "
                "TP spec rounds through the unfused paged step"
            )
        return _serve_step_whole_tp(
            params, cache, x, cos, sin, mask, phys, off, page_table,
            logits_idx, cfg=cfg, qmax=qmax, mesh=tp_mesh,
            collective=collective,
        )
    layer_arrays, head_arrays = whole_step_weight_layout(params, cfg)
    from ..serve import kernels as _pk

    n = cfg.num_hidden_layers
    if num_layers is not None:
        n = min(num_layers, n)
    sliced = n < cfg.num_hidden_layers
    walk_cache = cache
    if sliced:
        # early-exit draft fold: the grid walks only the first n layers
        # — slice the weight streams AND the pool rows (the walk derives
        # L from the pool), then hand the deeper rows back untouched
        # below, exactly serve_step_paged's num_layers contract
        layer_arrays = {k: a[:n] for k, a in layer_arrays.items()}
        walk_cache = {k: a[:n] for k, a in cache.items()}

    def block_fn(p_l, xv, cs, sn, mk, kb, vb, ks, vs, ph, of, pt):
        return _block_paged_xla(
            cfg, p_l, xv, cs, sn, mk, kb, vb, ph, of, pt, ks, vs, qmax
        )

    if all_logits:
        def head_fn(head, xv, li):
            return _whole_head_all_fn(cfg, head, xv, li)
    else:
        def head_fn(head, xv, li):
            return _whole_head_fn(cfg, head, xv, li)

    plan = _whole_tile_plan(cfg, qmax) if tiles > 1 else None
    logits, toks, new_cache = _pk.whole_step_decode(
        layer_arrays, head_arrays, x, cos, sin, walk_cache, page_table,
        phys, off, mask, logits_idx.astype(jnp.int32),
        block_fn=block_fn, head_fn=head_fn, tiles=tiles, tile_plan=plan,
    )
    if sliced:
        new_cache = {
            k: jnp.concatenate([new_cache[k], cache[k][n:]], axis=0)
            for k in new_cache
        }
    return logits, toks, new_cache


def _serve_step_whole_tp(params, cache, x, cos, sin, mask, phys, off,
                         page_table, logits_idx, *, cfg, qmax, mesh,
                         collective):
    """The TP whole-step walk: a manual ``model``-axis shard_map whose
    per-shard body scans the layers through the SAME
    :func:`_block_paged_xla` block (local head shards) with an explicit
    :func:`..serve.collectives.tp_allreduce` as the row-parallel
    reduction — issued per layer inside the walk, where the EQuARX
    quantized mode shrinks the decode collective's bytes. "exact" mode
    is lax.psum, bitwise the GSPMD reduction of the unfused step."""
    from jax.sharding import PartitionSpec as P

    from ..core.mesh import MODEL_AXIS, shard_map_unchecked
    from ..serve.collectives import tp_allreduce

    n = mesh.shape[MODEL_AXIS]
    quant = qmax is not None
    tie = cfg.tie_word_embeddings
    R = x.shape[0]

    def _model_only(spec):
        return P(*[MODEL_AXIS if s == MODEL_AXIS else None for s in spec])

    layer_specs = jax.tree.map(
        _model_only, param_pspecs(cfg)["layers"],
        is_leaf=lambda s: isinstance(s, P),
    )
    cache_specs = {
        name: _model_only(spec)
        for name, spec in paged_kv_cache_pspecs(
            cfg, kv_quant="int8" if quant else None
        ).items()
    }
    cache_names = sorted(cache)
    head_spec = (
        P(None, None) if tie else _model_only(param_pspecs(cfg)["lm_head"])
    )

    def body(layers, final_norm, head_w, x_, cos_, sin_, mask_, phys_,
             off_, pt_, li_, *cache_ops):
        cc = dict(zip(cache_names, cache_ops))

        def red(t):
            return tp_allreduce(t, MODEL_AXIS, collective)

        def scan_body(h, xs):
            if quant:
                p_l, kc, vc, ks, vs = xs
            else:
                p_l, kc, vc = xs
                ks = vs = None
            h, kc, vc, ks, vs = _block_paged_xla(
                cfg, p_l, h, cos_, sin_, mask_, kc, vc, phys_, off_,
                pt_, ks, vs, qmax, reduce_fn=red,
            )
            out = (kc, vc, ks, vs) if quant else (kc, vc)
            return h, out

        xs = (layers, cc["k"], cc["v"])
        if quant:
            xs = xs + (cc["k_scale"], cc["v_scale"])
        h, new = jax.lax.scan(scan_body, x_, xs)
        h = _rms(h, final_norm, cfg.rms_norm_eps)
        h = jnp.take_along_axis(h, li_[:, None, None], axis=1)
        if tie:
            logits = jnp.matmul(
                h, head_w.T, preferred_element_type=jnp.float32
            )[:, 0]
        else:
            part = jnp.matmul(
                h, head_w, preferred_element_type=jnp.float32
            )[:, 0]  # (R, V/n) — vocab columns live on one shard each
            logits = jax.lax.all_gather(
                part, MODEL_AXIS, axis=1, tiled=True
            )
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out_cc = {"k": new[0], "v": new[1]}
        if quant:
            out_cc["k_scale"], out_cc["v_scale"] = new[2], new[3]
        return (logits, toks) + tuple(out_cc[nm] for nm in cache_names)

    rep3 = P(None, None, None)
    in_specs = [
        layer_specs,
        P(None),                                  # final_norm
        head_spec,                                # embed / lm_head
        rep3,                                     # x
        rep3, rep3,                               # cos, sin
        rep3,                                     # mask
        P(None, None), P(None, None),             # phys, off
        P(None, None),                            # page table
        P(None),                                  # logits_idx
    ] + [cache_specs[nm] for nm in cache_names]
    out_specs = tuple(
        [P(None, None), P(None)] + [cache_specs[nm] for nm in cache_names]
    )
    head_w = params["embed"] if tie else params["lm_head"]
    fn = shard_map_unchecked(
        body, mesh, tuple(in_specs), out_specs,
        manual_axes={MODEL_AXIS},
    )
    outs = jax.jit(fn)(
        params["layers"], params["final_norm"], head_w, x, cos, sin,
        mask, phys.astype(jnp.int32), off.astype(jnp.int32),
        page_table.astype(jnp.int32), logits_idx.astype(jnp.int32),
        *[cache[nm] for nm in cache_names],
    )
    logits, toks = outs[0], outs[1]
    new_cache = dict(zip(cache_names, outs[2:]))
    return logits, toks, new_cache


def copy_page_kv(
    cache: Dict[str, jnp.ndarray],
    src: jnp.ndarray,  # () int32 physical page
    dst: jnp.ndarray,  # () int32 physical page
) -> Dict[str, jnp.ndarray]:
    """Copy one physical page's K/V lines (all layers) to another page —
    the device half of prefix-cache copy-on-write (serve/
    prefix_cache.py): a request appending into a shared cached tail page
    writes into a private copy, never the cached original. Dtype-
    agnostic by construction: every cache buffer — bf16 or int8 pools
    AND the quantized layout's (L, P+1, KV) scale rows — copies through
    the same pool-row gather/scatter, so COW moves codes and their
    scales together byte-for-byte."""
    return {
        name: buf.at[:, dst].set(buf[:, src])  # (L, P+1, ps|KV, ...)
        for name, buf in cache.items()
    }


def gather_page_kv(
    cache: Dict[str, jnp.ndarray],
    page: jnp.ndarray,  # () int32 physical page
) -> Dict[str, jnp.ndarray]:
    """Slice one physical page's content out of every cache buffer —
    the device half of a hierarchical-KV SPILL (serve/prefix_cache.py
    host tier): the engine starts an async device→host copy on the
    returned pytree and the page returns to the free list. Covers K/V
    pools AND the quantized layout's per-page scale rows, so a spilled
    page re-admits byte-for-byte."""
    return {name: buf[:, page] for name, buf in cache.items()}


def scatter_page_kv(
    cache: Dict[str, jnp.ndarray],
    page: jnp.ndarray,  # () int32 physical page
    values: Dict[str, jnp.ndarray],
) -> Dict[str, jnp.ndarray]:
    """Write a previously spilled page's content (the pytree
    :func:`gather_page_kv` produced) into pool row ``page`` — the
    device half of a host-tier RE-ADMIT. Exact inverse of the gather:
    codes and scales land byte-for-byte, which is what keeps
    spilled-then-readmitted generation bitwise identical to the
    never-evicted warm path."""
    return {
        name: buf.at[:, page].set(values[name])
        for name, buf in cache.items()
    }


def commit_kv_paged(
    cache: Dict[str, jnp.ndarray],
    page_table: jnp.ndarray,  # (R, NP) int32
    src: jnp.ndarray,         # (R, K) int32 cache lines (tree node lines)
    dst: jnp.ndarray,         # (R, K) int32 destination lines
    *,
    kv_quant: Optional[str] = None,
) -> Dict[str, jnp.ndarray]:
    """:func:`commit_kv` through the page table: accepted speculative
    lines move between table-resolved (page, offset) pairs. Functional
    gather-then-scatter, so overlapping ranges stay safe; scratch→
    scratch no-ops are harmless duplicates (identical values).

    On a quantized pool the codes cannot move verbatim (source and
    destination pages carry different scales): the lines dequantize at
    their source page's scale and re-commit through the standard
    quantized write (serve/kv_quant.quant_commit_lines), updating the
    destination pages' amax scales exactly as a fresh write would."""
    ps = cache["k"].shape[2]
    s_phys, s_off = _page_lookup(page_table, src, ps)
    d_phys, d_off = _page_lookup(page_table, dst, ps)
    if kv_quant is not None:
        from ..serve.kv_quant import quant_commit_lines, resolve_spec

        qmax = resolve_spec(kv_quant).qmax
        out = dict(cache)
        for name in ("k", "v"):
            out[name], out[name + "_scale"] = quant_commit_lines(
                cache[name], cache[name + "_scale"],
                s_phys, s_off, d_phys, d_off, qmax,
            )
        return out
    out = {}
    for name, buf in cache.items():  # (L, P+1, ps, KV, dk)
        rows = buf[:, s_phys, s_off]  # (L, R, K, KV, dk)
        out[name] = buf.at[:, d_phys, d_off].set(rows)
    return out


def reorder_slots_paged(
    cache: Dict[str, jnp.ndarray],
    page_table: jnp.ndarray,  # (R, NP) int32
    src: jnp.ndarray,         # (R,) int32
) -> Dict[str, jnp.ndarray]:
    """:func:`reorder_slots` for the paged layout: page OWNERSHIP stays
    with each slot (the host table is untouched) and page CONTENT is
    copied — new slot r's pages receive slot src[r]'s lines. Requires
    the destination slots to have (at least) the source slots' pages
    allocated, which beam search guarantees by construction (equal-
    length hypotheses)."""
    src_pages = page_table[src].reshape(-1)   # (R*NP,)
    dst_pages = page_table.reshape(-1)
    return {
        name: buf.at[:, dst_pages].set(buf[:, src_pages])
        for name, buf in cache.items()
    }


def commit_kv(
    cache: Dict[str, jnp.ndarray],
    src: jnp.ndarray,  # (R, K) int32 cache lines to keep (tree node lines)
    dst: jnp.ndarray,  # (R, K) int32 destination lines (contiguous suffix)
) -> Dict[str, jnp.ndarray]:
    """Move accepted speculative K/V lines into their committed positions
    — the TPU-native version of the reference's token-commit copy kernels
    (reference ``request_manager.cu`` commit_tokens + the KV-cache commit
    in ``tree_inc_multihead_self_attention.cu``). Unused slots should map
    scratch→scratch. Functional gather-then-scatter, so overlapping
    src/dst ranges are safe."""
    R = src.shape[0]
    bidx = jnp.arange(R)[:, None]
    out = {}
    for name, buf in cache.items():  # (L, R, S1, KV, dk)
        rows = buf[:, bidx, src]     # (L, R, K, KV, dk)
        out[name] = buf.at[:, bidx, dst].set(rows)
    return out


def reorder_slots(
    cache: Dict[str, jnp.ndarray], src: jnp.ndarray  # (R,) int32
) -> Dict[str, jnp.ndarray]:
    """Gather cache slots: new slot r takes slot src[r]'s lines — beam
    search reorders hypotheses across request slots this way (the
    reference's beam attention forks sub-request KV instead,
    spec_inc_multihead_self_attention.cu)."""
    return {name: buf[:, src] for name, buf in cache.items()}


def num_params(cfg: LLaMAConfig) -> int:
    L, D, F, V = (
        cfg.num_hidden_layers,
        cfg.hidden_size,
        cfg.intermediate_size,
        cfg.vocab_size,
    )
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    per_layer = D * (H * dk) + 2 * D * (KV * dk) + (H * dk) * D + 3 * D * F + 2 * D
    head = 0 if cfg.tie_word_embeddings else D * V
    return V * D + L * per_layer + D + head


def flops_per_token(cfg: LLaMAConfig, seq_len: int) -> int:
    """Forward FLOPs/token ≈ 2*n_params + attention quadratic term."""
    return 2 * num_params(cfg) + 4 * cfg.num_hidden_layers * cfg.hidden_size * seq_len


def convert_hf_state_dict(sd: Dict[str, Any], cfg: LLaMAConfig) -> Dict[str, Any]:
    """HF ``LlamaForCausalLM`` state dict → framework pytree (stacked
    layer dim). The analog of the reference's per-layer weight-file
    conversion (reference ``python/flexflow/serve/serve.py:167-227``,
    ``inference/file_loader.cc:792``)."""
    from .hf_utils import linear_w, stack, to_np

    dt = cfg.dtype
    L = cfg.num_hidden_layers
    pre = "model."

    def mats(fmt):
        return stack([linear_w(sd, pre + fmt.format(i)) for i in range(L)], dt)

    def vecs(fmt):
        return stack([to_np(sd[pre + fmt.format(i)]) for i in range(L)], dt)

    layers = {
        "attn_norm": vecs("layers.{}.input_layernorm.weight"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "wo": mats("layers.{}.self_attn.o_proj.weight"),
        "ffn_norm": vecs("layers.{}.post_attention_layernorm.weight"),
        "w1": mats("layers.{}.mlp.gate_proj.weight"),
        "w2": mats("layers.{}.mlp.down_proj.weight"),
        "w3": mats("layers.{}.mlp.up_proj.weight"),
    }
    params = {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm": jnp.asarray(to_np(sd[pre + "norm.weight"]), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(linear_w(sd, "lm_head.weight"), dt)
    return params


def from_hf(hf: Dict[str, Any], **kw) -> LLaMAConfig:
    """Module-level alias so the family registry has a uniform
    ``from_hf`` entry point across model modules."""
    return LLaMAConfig.from_hf(hf, **kw)
