"""OPT model family (reference ``inference/models/opt.cc`` and
``python/flexflow/serve/models/opt.py``): decoder-only with learned
positional embeddings at offset 2, pre-LayerNorm blocks, biased MHA and
ReLU FFN, tied LM head. Runs on the generic decoder
(:mod:`.transformer`)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=50272,
        hidden_size=768,
        intermediate_size=3072,
        num_hidden_layers=12,
        num_attention_heads=12,
        num_key_value_heads=12,
        max_position_embeddings=2048,
        norm_type="layernorm",
        norm_bias=True,
        norm_eps=1e-5,
        positions="learned",
        learned_pos_offset=2,
        activation="relu",
        glu=False,
        parallel_block=False,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        tie_word_embeddings=True,
    )
    d.update(kw)
    return DecoderConfig(**d)


def opt_125m(**kw) -> DecoderConfig:
    return config(**kw)


def opt_6_7b(**kw) -> DecoderConfig:
    d = dict(
        hidden_size=4096,
        intermediate_size=16384,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=32,
    )
    d.update(kw)
    return config(**d)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    assert hf.get("word_embed_proj_dim", hf["hidden_size"]) == hf["hidden_size"], (
        "OPT word_embed_proj_dim != hidden_size (350m-style projection) "
        "is not supported"
    )
    assert hf.get("do_layer_norm_before", True), "post-norm OPT not supported"
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["ffn_dim"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf["num_attention_heads"],
        max_position_embeddings=hf["max_position_embeddings"],
        activation=hf.get("activation_function", "relu"),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(sd: Dict[str, Any], cfg: DecoderConfig) -> Dict[str, Any]:
    """HF ``OPTForCausalLM`` state dict → framework pytree."""
    dt = cfg.dtype
    pre = "model.decoder."
    if pre + "embed_tokens.weight" not in sd and "decoder.embed_tokens.weight" in sd:
        pre = "decoder."
    L = cfg.num_hidden_layers

    def per_layer(fmt, conv):
        return [conv(sd, pre + fmt.format(i)) for i in range(L)]

    layers = {
        "attn_norm_scale": stack(
            per_layer("layers.{}.self_attn_layer_norm.weight", lambda s, n: to_np(s[n])), dt
        ),
        "attn_norm_bias": stack(
            per_layer("layers.{}.self_attn_layer_norm.bias", lambda s, n: to_np(s[n])), dt
        ),
        "wq": stack(per_layer("layers.{}.self_attn.q_proj.weight", linear_w), dt),
        "wk": stack(per_layer("layers.{}.self_attn.k_proj.weight", linear_w), dt),
        "wv": stack(per_layer("layers.{}.self_attn.v_proj.weight", linear_w), dt),
        "wo": stack(per_layer("layers.{}.self_attn.out_proj.weight", linear_w), dt),
        "bq": stack(per_layer("layers.{}.self_attn.q_proj.bias", lambda s, n: to_np(s[n])), dt),
        "bk": stack(per_layer("layers.{}.self_attn.k_proj.bias", lambda s, n: to_np(s[n])), dt),
        "bv": stack(per_layer("layers.{}.self_attn.v_proj.bias", lambda s, n: to_np(s[n])), dt),
        "bo": stack(per_layer("layers.{}.self_attn.out_proj.bias", lambda s, n: to_np(s[n])), dt),
        "mlp_norm_scale": stack(
            per_layer("layers.{}.final_layer_norm.weight", lambda s, n: to_np(s[n])), dt
        ),
        "mlp_norm_bias": stack(
            per_layer("layers.{}.final_layer_norm.bias", lambda s, n: to_np(s[n])), dt
        ),
        "w_up": stack(per_layer("layers.{}.fc1.weight", linear_w), dt),
        "b_up": stack(per_layer("layers.{}.fc1.bias", lambda s, n: to_np(s[n])), dt),
        "w_down": stack(per_layer("layers.{}.fc2.weight", linear_w), dt),
        "b_down": stack(per_layer("layers.{}.fc2.bias", lambda s, n: to_np(s[n])), dt),
    }
    return {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "pos_embed": jnp.asarray(to_np(sd[pre + "embed_positions.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "final_layer_norm.weight"]), dt),
        "final_norm_bias": jnp.asarray(to_np(sd[pre + "final_layer_norm.bias"]), dt),
    }
