"""Mistral model family — LLaMA-architecture dense decoder with
sliding-window attention (HF ``MistralForCausalLM``), beyond the
reference zoo (``inference/models/*`` has no Mistral and no windowed
attention). Runs on the generic decoder (:mod:`.transformer`) with
``sliding_window`` > 0: queries attend only the last w key positions;
training masks and the serving cache masks both enforce it."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import layer_stackers, linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        max_position_embeddings=4096,
        norm_type="rmsnorm",
        norm_bias=False,
        norm_eps=1e-5,
        positions="rope",
        rope_theta=10000.0,
        activation="silu",
        glu=True,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        sliding_window=4096,
    )
    d.update(kw)
    return DecoderConfig(**d)


def mistral_7b(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        sliding_window=8,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get(
            "num_key_value_heads", hf["num_attention_heads"]
        ),
        max_position_embeddings=hf["max_position_embeddings"],
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        # null/absent window (mistral-v0.3-style configs) = full causal
        sliding_window=hf.get("sliding_window") or 0,
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(
    sd: Dict[str, Any], cfg: DecoderConfig
) -> Dict[str, Any]:
    """HF ``MistralForCausalLM`` state dict → framework pytree (same
    tensor names as LLaMA's HF layout)."""
    dt = cfg.dtype
    L = cfg.num_hidden_layers
    pre = "model."

    mats, vecs = layer_stackers(sd, pre, L, dt)

    layers = {
        "attn_norm_scale": vecs("layers.{}.input_layernorm.weight"),
        "mlp_norm_scale": vecs("layers.{}.post_attention_layernorm.weight"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "wo": mats("layers.{}.self_attn.o_proj.weight"),
        "w_gate": mats("layers.{}.mlp.gate_proj.weight"),
        "w_up": mats("layers.{}.mlp.up_proj.weight"),
        "w_down": mats("layers.{}.mlp.down_proj.weight"),
    }
    out: Dict[str, Any] = {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "norm.weight"]), dt),
    }
    if not cfg.tie_word_embeddings:
        out["lm_head"] = jnp.asarray(to_np(sd["lm_head.weight"]).T, dt)
    return out
