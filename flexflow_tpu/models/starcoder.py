"""Starcoder / GPTBigCode model family (reference
``inference/models/starcoder.cc`` and ``python/flexflow/serve/models/
starcoder.py``): learned absolute positions, multi-query attention,
biased projections, gelu-tanh FFN, tied LM head. Runs on the generic
decoder (:mod:`.transformer`)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import linear_w, stack, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=49152,
        hidden_size=6144,
        intermediate_size=4 * 6144,
        num_hidden_layers=40,
        num_attention_heads=48,
        num_key_value_heads=1,  # multi-query
        max_position_embeddings=8192,
        norm_type="layernorm",
        norm_bias=True,
        norm_eps=1e-5,
        positions="learned",
        learned_pos_offset=0,
        activation="gelu_tanh",
        glu=False,
        parallel_block=False,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        tie_word_embeddings=True,
    )
    d.update(kw)
    return DecoderConfig(**d)


def starcoder_15b(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["n_embd"],
        intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
        num_hidden_layers=hf["n_layer"],
        num_attention_heads=hf["n_head"],
        num_key_value_heads=1 if hf.get("multi_query", True) else hf["n_head"],
        max_position_embeddings=hf["n_positions"],
        norm_eps=hf.get("layer_norm_epsilon", 1e-5),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(sd: Dict[str, Any], cfg: DecoderConfig) -> Dict[str, Any]:
    """HF ``GPTBigCodeForCausalLM`` state dict → framework pytree. The
    fused ``c_attn`` packs [H*dk query | KV*dk key | KV*dk value] columns."""
    dt = cfg.dtype
    pre = "transformer."
    L = cfg.num_hidden_layers
    H, KV, dk = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    qd, kvd = H * dk, KV * dk

    wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
    for i in range(L):
        w = linear_w(sd, f"{pre}h.{i}.attn.c_attn.weight")  # (D, qd+2*kvd)
        b = to_np(sd[f"{pre}h.{i}.attn.c_attn.bias"])
        wq.append(w[:, :qd])
        wk.append(w[:, qd : qd + kvd])
        wv.append(w[:, qd + kvd :])
        bq.append(b[:qd])
        bk.append(b[qd : qd + kvd])
        bv.append(b[qd + kvd :])

    def vec(fmt):
        return stack([to_np(sd[pre + fmt.format(i)]) for i in range(L)], dt)

    layers = {
        "attn_norm_scale": vec("h.{}.ln_1.weight"),
        "attn_norm_bias": vec("h.{}.ln_1.bias"),
        "wq": stack(wq, dt),
        "wk": stack(wk, dt),
        "wv": stack(wv, dt),
        "bq": stack(bq, dt),
        "bk": stack(bk, dt),
        "bv": stack(bv, dt),
        "wo": stack([linear_w(sd, f"{pre}h.{i}.attn.c_proj.weight") for i in range(L)], dt),
        "bo": vec("h.{}.attn.c_proj.bias"),
        "mlp_norm_scale": vec("h.{}.ln_2.weight"),
        "mlp_norm_bias": vec("h.{}.ln_2.bias"),
        "w_up": stack([linear_w(sd, f"{pre}h.{i}.mlp.c_fc.weight") for i in range(L)], dt),
        "b_up": vec("h.{}.mlp.c_fc.bias"),
        "w_down": stack([linear_w(sd, f"{pre}h.{i}.mlp.c_proj.weight") for i in range(L)], dt),
        "b_down": vec("h.{}.mlp.c_proj.bias"),
    }
    return {
        "embed": jnp.asarray(to_np(sd[pre + "wte.weight"]), dt),
        "pos_embed": jnp.asarray(to_np(sd[pre + "wpe.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "ln_f.weight"]), dt),
        "final_norm_bias": jnp.asarray(to_np(sd[pre + "ln_f.bias"]), dt),
    }
