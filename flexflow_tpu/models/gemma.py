"""Gemma model family (HF ``GemmaForCausalLM``) — beyond the reference
zoo. Runs on the generic decoder with the Gemma knobs: a head_dim
decoupled from hidden/heads (Gemma-7B: 16 heads x 256 over D=3072),
RMSNorm scaling by (1 + w), sqrt(D) input-embedding scaling, GeGLU FFN
and tied embeddings."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import layer_stackers, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=256000,
        hidden_size=3072,
        intermediate_size=24576,
        num_hidden_layers=28,
        num_attention_heads=16,
        num_key_value_heads=16,
        head_dim_override=256,
        max_position_embeddings=8192,
        norm_type="rmsnorm",
        norm_bias=False,
        norm_eps=1e-6,
        norm_plus_one=True,
        embed_scale=True,
        positions="rope",
        rope_theta=10000.0,
        activation="gelu_tanh",
        glu=True,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=False,
        tie_word_embeddings=True,
    )
    d.update(kw)
    return DecoderConfig(**d)


def gemma_7b(**kw) -> DecoderConfig:
    return config(**kw)


def gemma_2b(**kw) -> DecoderConfig:
    d = dict(
        hidden_size=2048,
        intermediate_size=16384,
        num_hidden_layers=18,
        num_attention_heads=8,
        num_key_value_heads=1,
    )
    d.update(kw)
    return config(**d)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,
        head_dim_override=32,
        max_position_embeddings=128,
    )
    d.update(kw)
    return config(**d)


_HF_ACTS = {
    "gelu": "gelu_tanh",  # HF Gemma's "gelu" is the tanh approximation
    "gelu_pytorch_tanh": "gelu_tanh",
    "gelu_fast": "gelu_tanh",
    "silu": "silu",
    "relu": "relu",
}


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    mt = hf.get("model_type", "gemma")
    if mt != "gemma":
        # detect_family's substring fallback would route gemma2/gemma3
        # checkpoints here; their extra machinery (pre/post-FFN norms,
        # logit softcapping, interleaved local attention) does not fit
        # this converter — silently wrong logits, so fail loudly
        raise NotImplementedError(
            f"model_type {mt!r} is not Gemma-1; gemma2/gemma3 "
            "architectures are unsupported"
        )
    act = hf.get("hidden_activation") or hf.get("hidden_act") or "gelu"
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get(
            "num_key_value_heads", hf["num_attention_heads"]
        ),
        head_dim_override=hf.get("head_dim", 256),
        max_position_embeddings=hf["max_position_embeddings"],
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rope_theta=hf.get("rope_theta", 10000.0),
        activation=_HF_ACTS.get(act, act),
        tie_word_embeddings=hf.get("tie_word_embeddings", True),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(
    sd: Dict[str, Any], cfg: DecoderConfig
) -> Dict[str, Any]:
    """HF ``GemmaForCausalLM`` state dict → framework pytree (LLaMA HF
    tensor layout; norm weights stay as HF's 1+w offsets — the decoder
    adds the 1 at run time via ``norm_plus_one``)."""
    dt = cfg.dtype
    L = cfg.num_hidden_layers
    pre = "model."
    mats, vecs = layer_stackers(sd, pre, L, dt)

    layers = {
        "attn_norm_scale": vecs("layers.{}.input_layernorm.weight"),
        "mlp_norm_scale": vecs("layers.{}.post_attention_layernorm.weight"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "wo": mats("layers.{}.self_attn.o_proj.weight"),
        "w_gate": mats("layers.{}.mlp.gate_proj.weight"),
        "w_up": mats("layers.{}.mlp.up_proj.weight"),
        "w_down": mats("layers.{}.mlp.down_proj.weight"),
    }
    out: Dict[str, Any] = {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(to_np(sd[pre + "norm.weight"]), dt),
    }
    if not cfg.tie_word_embeddings:
        out["lm_head"] = jnp.asarray(to_np(sd["lm_head.weight"]).T, dt)
    return out
