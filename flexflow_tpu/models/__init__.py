from . import llama

__all__ = ["llama"]
