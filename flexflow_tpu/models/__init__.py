from . import llama, transformer, opt, falcon, mpt, starcoder, hf_utils

# Model-family registry (reference python/flexflow/serve/models/__init__.py
# maps HF architectures to FlexFlow builders).
FAMILIES = {
    "llama": llama,
    "opt": opt,
    "falcon": falcon,
    "mpt": mpt,
    "starcoder": starcoder,
    "gpt_bigcode": starcoder,
}

__all__ = [
    "llama", "transformer", "opt", "falcon", "mpt", "starcoder",
    "hf_utils", "FAMILIES",
]
