from . import llama, transformer, opt, falcon, mpt, starcoder, qwen2, hf_utils

# Model-family registry (reference python/flexflow/serve/models/__init__.py
# maps HF architectures to FlexFlow builders; qwen2 goes beyond the
# reference's five-family zoo).
FAMILIES = {
    "llama": llama,
    "opt": opt,
    "falcon": falcon,
    "mpt": mpt,
    "starcoder": starcoder,
    "gpt_bigcode": starcoder,
    "qwen2": qwen2,
}

__all__ = [
    "llama", "transformer", "opt", "falcon", "mpt", "starcoder", "qwen2",
    "hf_utils", "FAMILIES",
]
