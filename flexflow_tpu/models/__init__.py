from . import llama, transformer, opt, falcon, mpt, starcoder, qwen2, qwen2_moe, mixtral, mistral, gemma, phi, gpt2, hf_utils

# Model-family registry (reference python/flexflow/serve/models/__init__.py
# maps HF architectures to FlexFlow builders; qwen2 and mixtral go beyond
# the reference's five-family zoo — mixtral adds sparse-MoE serving).
FAMILIES = {
    "llama": llama,
    "opt": opt,
    "falcon": falcon,
    "mpt": mpt,
    "starcoder": starcoder,
    "gpt_bigcode": starcoder,
    "qwen2": qwen2,
    "mixtral": mixtral,
    "mistral": mistral,
    "qwen2_moe": qwen2_moe,
    "gemma": gemma,
    "phi": phi,
    "gpt2": gpt2,
}

__all__ = [
    "llama", "transformer", "opt", "falcon", "mpt", "starcoder", "qwen2",
    "mixtral", "mistral", "qwen2_moe", "gemma", "phi", "gpt2",
    "hf_utils", "FAMILIES",
]
