"""Phi model family (HF ``PhiForCausalLM``, Phi-1/1.5/2) — beyond the
reference zoo. Runs on the generic decoder with partial rotary
embeddings (``rotary_pct``: only the first fraction of each head
rotates), a Falcon-style parallel block sharing one input LayerNorm,
biased everything (QKV/out/MLP/LM head), and gelu_tanh FFN."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from . import transformer
from .transformer import (  # noqa: F401  (engine serving protocol)
    DecoderConfig,
    FUSED_DECODE,
    commit_kv,
    commit_kv_paged,
    copy_page_kv,
    forward,
    gather_page_kv,
    init_kv_cache,
    init_paged_kv_cache,
    init_params,
    kv_cache_pspecs,
    num_params,
    paged_kv_cache_pspecs,
    param_pspecs,
    reorder_slots,
    reorder_slots_paged,
    scatter_page_kv,
    serve_debug_activations,
    serve_step,
    serve_step_paged,
    serve_step_whole,
    whole_step_tile_roles,
    whole_step_weight_layout,
)
from .hf_utils import layer_stackers, to_np


def config(**kw) -> DecoderConfig:
    d: Dict[str, Any] = dict(
        vocab_size=51200,
        hidden_size=2560,
        intermediate_size=10240,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=32,
        max_position_embeddings=2048,
        norm_type="layernorm",
        norm_bias=True,
        norm_eps=1e-5,
        positions="rope",
        rope_theta=10000.0,
        rotary_pct=0.4,
        activation="gelu_tanh",
        glu=False,
        parallel_block=True,
        parallel_two_norms=False,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        tie_word_embeddings=False,
        lm_head_bias=True,
    )
    d.update(kw)
    return DecoderConfig(**d)


def phi_2(**kw) -> DecoderConfig:
    return config(**kw)


def tiny(**kw) -> DecoderConfig:
    d = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=128,
        rotary_pct=0.5,
    )
    d.update(kw)
    return config(**d)


_HF_ACTS = {
    "gelu_new": "gelu_tanh",
    "gelu_pytorch_tanh": "gelu_tanh",
    "gelu_fast": "gelu_tanh",
    "gelu": "gelu",
    "relu": "relu",
    "silu": "silu",
}


def from_hf(hf: Dict[str, Any], **kw) -> DecoderConfig:
    mt = hf.get("model_type", "phi")
    if mt != "phi":
        # detect_family's substring fallback would route phi3/phi4/
        # phimoe checkpoints here; their fused qkv/gate_up projections
        # and SwiGLU do not fit this converter
        raise NotImplementedError(
            f"model_type {mt!r} is not Phi-1/2; phi3/phi4/phimoe "
            "architectures are unsupported"
        )
    if hf.get("qk_layernorm"):
        # q/k per-head layernorm weights would be silently dropped —
        # wrong logits with no error
        raise NotImplementedError(
            "Phi qk_layernorm=True is not supported"
        )
    act = hf.get("hidden_act", "gelu_new")
    d = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get(
            "num_key_value_heads", hf["num_attention_heads"]
        ),
        max_position_embeddings=hf["max_position_embeddings"],
        norm_eps=hf.get("layer_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        rotary_pct=hf.get("partial_rotary_factor", 0.5),
        activation=_HF_ACTS.get(act, act),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    d.update(kw)
    return config(**d)


def convert_hf_state_dict(
    sd: Dict[str, Any], cfg: DecoderConfig
) -> Dict[str, Any]:
    """HF ``PhiForCausalLM`` state dict → framework pytree."""
    dt = cfg.dtype
    L = cfg.num_hidden_layers
    pre = "model."
    mats, vecs = layer_stackers(sd, pre, L, dt)

    layers = {
        "attn_norm_scale": vecs("layers.{}.input_layernorm.weight"),
        "attn_norm_bias": vecs("layers.{}.input_layernorm.bias"),
        "wq": mats("layers.{}.self_attn.q_proj.weight"),
        "wk": mats("layers.{}.self_attn.k_proj.weight"),
        "wv": mats("layers.{}.self_attn.v_proj.weight"),
        "wo": mats("layers.{}.self_attn.dense.weight"),
        "bq": vecs("layers.{}.self_attn.q_proj.bias"),
        "bk": vecs("layers.{}.self_attn.k_proj.bias"),
        "bv": vecs("layers.{}.self_attn.v_proj.bias"),
        "bo": vecs("layers.{}.self_attn.dense.bias"),
        "w_up": mats("layers.{}.mlp.fc1.weight"),
        "b_up": vecs("layers.{}.mlp.fc1.bias"),
        "w_down": mats("layers.{}.mlp.fc2.weight"),
        "b_down": vecs("layers.{}.mlp.fc2.bias"),
    }
    return {
        "embed": jnp.asarray(to_np(sd[pre + "embed_tokens.weight"]), dt),
        "layers": layers,
        "final_norm_scale": jnp.asarray(
            to_np(sd[pre + "final_layernorm.weight"]), dt
        ),
        "final_norm_bias": jnp.asarray(
            to_np(sd[pre + "final_layernorm.bias"]), dt
        ),
        "lm_head": jnp.asarray(to_np(sd["lm_head.weight"]).T, dt),
        "lm_head_bias": jnp.asarray(to_np(sd["lm_head.bias"]), dt),
    }
