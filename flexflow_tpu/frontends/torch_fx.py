"""PyTorch → FFModel importer via torch.fx symbolic tracing.

TPU-native counterpart of the reference's fx frontend (reference
``python/flexflow/torch/model.py:1-2607``: ``PyTorchModel.torch_to_ff``
walks a symbolically-traced graph and emits one FFModel layer call per
fx node). Same architecture here: trace → per-node translation table →
FFModel builder calls; weights are converted from the module's
state_dict into the framework's per-op pytrees (HF linear layout
transposed to (in, out)).

Only imported when torch is available; the rest of the framework has no
torch dependency.
"""
from __future__ import annotations

import operator
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


import functools


@functools.lru_cache(maxsize=None)
def _cmp_ops():
    """Comparison dispatch table, built on first use (torch import
    deferred — the module must import without torch installed)."""
    import torch

    return {
        operator.gt: "gt", torch.gt: "gt", "gt": "gt",
        operator.lt: "lt", torch.lt: "lt", "lt": "lt",
        operator.ge: "ge", torch.ge: "ge", "ge": "ge",
        operator.le: "le", torch.le: "le", "le": "le",
        operator.eq: "eq", torch.eq: "eq", "eq": "eq",
    }


class PyTorchModel:
    """Wraps a ``torch.nn.Module``; ``to_ff(ffmodel, input_tensors)``
    replays its fx graph as FFModel layers and returns the outputs
    (reference ``PyTorchModel.torch_to_ff``).

    HuggingFace ``PreTrainedModel``s are traced through
    ``transformers.utils.fx.symbolic_trace`` (shape-dependent control
    flow defeats plain ``torch.fx``), matching the reference's
    HF-traceable importer (reference
    ``python/flexflow/torch/model.py:2408-2444`` + ``tests/align``).
    Pass ``input_names`` (e.g. ``["input_ids", "attention_mask"]``) to
    pick the traced signature."""

    def __init__(
        self,
        module,
        batch_size: Optional[int] = None,
        input_names: Optional[Sequence[str]] = None,
    ):
        import torch.fx

        self.module = module.eval()
        traced = None
        try:
            from transformers import PreTrainedModel

            if isinstance(module, PreTrainedModel):
                from transformers.utils import fx as hf_fx

                traced = hf_fx.symbolic_trace(
                    module, input_names=list(input_names or ["input_ids"])
                )
        except ImportError:
            pass
        self.graph_module = traced or torch.fx.symbolic_trace(module)
        self.batch_size = batch_size

    # ------------------------------------------------------------------

    def to_ff(self, ffmodel, input_tensors: Sequence[Any]) -> List[Any]:
        """Translate the traced graph into ``ffmodel`` layer calls.
        ``input_tensors`` are FFModel Tensors (one per fx placeholder,
        in order). Returns the list of output Tensors; converted weights
        are stored on ``ffmodel._imported_params`` keyed by node name so
        ``compile()``-initialised params can be overwritten via
        :meth:`load_weights`."""
        import torch

        env: Dict[str, Any] = {}
        placeholders = [
            n for n in self.graph_module.graph.nodes if n.op == "placeholder"
        ]
        assert len(placeholders) == len(input_tensors), (
            f"model takes {len(placeholders)} inputs, got {len(input_tensors)}"
        )
        for node, t in zip(placeholders, input_tensors):
            env[node.name] = t

        self._weights: Dict[str, Dict[str, np.ndarray]] = {}
        outputs: List[Any] = []

        for node in self.graph_module.graph.nodes:
            if node.op == "placeholder":
                continue
            if node.op == "output":
                args = node.args[0]
                if isinstance(args, dict):  # HF ModelOutput-shaped returns
                    args = list(args.values())
                outputs = list(args) if isinstance(args, (tuple, list)) else [args]
                outputs = [self._arg(env, a) for a in outputs]
                continue
            if node.op == "call_module":
                mod = self.graph_module.get_submodule(node.target)
                env[node.name] = self._module_node(ffmodel, node, mod, env)
            elif node.op in ("call_function", "call_method"):
                env[node.name] = self._function_node(ffmodel, node, env)
            elif node.op == "get_attr":
                # registered buffers (position_ids, token_type_ids,
                # causal masks): fold to numpy, materialised as a
                # `constant` op only if an FF op consumes them. A
                # TRAINABLE nn.Parameter must not be silently frozen
                # into a constant — keep the loud failure for those.
                obj = self.module
                for part in node.target.split("."):
                    obj = getattr(obj, part)
                if isinstance(obj, torch.nn.Parameter):
                    raise NotImplementedError(
                        f"get_attr on trainable parameter {node.target!r}: "
                        "folding it to a constant would silently freeze "
                        "it; wrap it in a module the importer understands"
                    )
                env[node.name] = obj.detach().cpu().numpy()
        ffmodel._imported_params = getattr(ffmodel, "_imported_params", {})
        ffmodel._imported_params.update(self._weights)
        return outputs

    def load_weights(self, ffmodel) -> None:
        """Overwrite ``ffmodel.params`` entries with the converted torch
        weights (call after ``compile()``)."""
        from . import load_imported_weights

        load_imported_weights(ffmodel)

    # ------------------------------------------------------------------

    def _arg(self, env, a):
        """Recursively resolve fx Nodes — indices arrive as tuples of
        slices whose bounds are themselves traced size() nodes."""
        import torch.fx

        if isinstance(a, torch.fx.Node):
            return env[a.name]
        if isinstance(a, slice):
            return slice(
                self._arg(env, a.start),
                self._arg(env, a.stop),
                self._arg(env, a.step),
            )
        if isinstance(a, (tuple, list)):
            return type(a)(self._arg(env, x) for x in a)
        if isinstance(a, dict):
            return {k: self._arg(env, v) for k, v in a.items()}
        return a

    @staticmethod
    def _is_ff(v) -> bool:
        return hasattr(v, "ref")

    def _ensure_ff(self, ff, v, name: str):
        """Materialise a folded numpy value as a `constant` op the
        moment a real FF op needs it as input."""
        if self._is_ff(v):
            return v
        return ff.constant(np.asarray(v), name=f"{name}_const")

    @staticmethod
    def _np_dtype(dt):
        """torch.dtype / np.dtype / DataType-ish → numpy dtype (int4 has
        no numpy equivalent and never appears in traced graphs)."""
        s = str(dt).replace("torch.", "")
        return np.dtype({"long": "int64", "half": "float16"}.get(s, s))

    def _module_node(self, ff, node, mod, env):
        import torch.nn as nn

        x = self._arg(env, node.args[0])
        name = node.name

        if isinstance(mod, nn.Linear):
            out = ff.dense(x, mod.out_features, use_bias=mod.bias is not None,
                           name=name)
            w = {"kernel": mod.weight.detach().numpy().T}
            if mod.bias is not None:
                w["bias"] = mod.bias.detach().numpy()
            self._weights[name] = w
            return out
        if isinstance(mod, nn.Conv2d):
            out = ff.conv2d(
                x, mod.out_channels, mod.kernel_size[0], mod.kernel_size[1],
                mod.stride[0], mod.stride[1], mod.padding[0], mod.padding[1],
                groups=mod.groups, use_bias=mod.bias is not None, name=name,
            )
            # framework conv kernels are OIHW like torch
            w = {"kernel": mod.weight.detach().numpy()}
            if mod.bias is not None:
                w["bias"] = mod.bias.detach().numpy()
            self._weights[name] = w
            return out
        if isinstance(mod, nn.Embedding):
            x = self._ensure_ff(ff, x, name)  # folded position-id buffers
            out = ff.embedding(x, mod.num_embeddings, mod.embedding_dim, name=name)
            self._weights[name] = {"table": mod.weight.detach().numpy()}
            return out
        if isinstance(mod, nn.LayerNorm):
            out = ff.layer_norm(x, eps=mod.eps,
                                elementwise_affine=mod.elementwise_affine,
                                name=name)
            if mod.elementwise_affine:
                self._weights[name] = {
                    "gamma": mod.weight.detach().numpy(),
                    "beta": mod.bias.detach().numpy(),
                }
            return out
        if isinstance(mod, nn.BatchNorm2d):
            return ff.batch_norm(x, relu=False, name=name)
        if isinstance(mod, nn.MaxPool2d):
            kh, kw = self._pair(mod.kernel_size)
            sh, sw = self._pair(mod.stride or mod.kernel_size)
            ph, pw = self._pair(mod.padding)
            return ff.pool2d(x, kh, kw, sh, sw, ph, pw, pool_type="max", name=name)
        if isinstance(mod, nn.AvgPool2d):
            kh, kw = self._pair(mod.kernel_size)
            sh, sw = self._pair(mod.stride or mod.kernel_size)
            ph, pw = self._pair(mod.padding)
            return ff.pool2d(x, kh, kw, sh, sw, ph, pw, pool_type="avg", name=name)
        if isinstance(mod, nn.ReLU):
            return ff.relu(x, name=name)
        if isinstance(mod, nn.GELU):
            return ff.gelu(x, name=name)
        if isinstance(mod, nn.Sigmoid):
            return ff.sigmoid(x, name=name)
        if isinstance(mod, nn.Tanh):
            return ff.tanh(x, name=name)
        if isinstance(mod, nn.Softmax):
            return ff.softmax(x, axis=mod.dim if mod.dim is not None else -1,
                              name=name)
        if isinstance(mod, nn.Dropout):
            return ff.dropout(x, rate=mod.p, name=name)
        if isinstance(mod, nn.Flatten):
            return ff.flat(x, name=name)
        if isinstance(mod, nn.Identity):
            return x
        raise NotImplementedError(f"fx module {type(mod).__name__} ({node.target})")

    @staticmethod
    def _pair(v):
        return (v, v) if isinstance(v, int) else (v[0], v[1])

    def _function_node(self, ff, node, env):
        import torch
        import torch.nn.functional as F

        args = [self._arg(env, a) for a in node.args]
        kwargs = {k: self._arg(env, v) for k, v in node.kwargs.items()}
        t = node.target
        name = node.name
        tname = getattr(t, "__name__", str(t))

        # ---- meta values: when no FF tensor is involved, the node is
        # shape/buffer arithmetic from the trace — fold it eagerly (the
        # reference's importer resolves symbolic shapes the same way)
        any_ff = any(
            self._is_ff(v)
            for v in (*args, *kwargs.values())
        ) or any(
            isinstance(v, (tuple, list)) and any(self._is_ff(x) for x in v)
            for v in args
        )
        if not any_ff:
            folded = self._fold_meta(tname, t, args, kwargs)
            if folded is not NotImplemented:
                return folded

        if t in (operator.add, torch.add, "add"):
            a, b = args[0], args[1]
            if not self._is_ff(a):
                a, b = b, a  # commutative: tensor first
            if self._is_ff(b) or np.ndim(b) > 0:
                return ff.add(a, self._ensure_ff(ff, b, name), name=name)
            return ff.scalar_add(a, float(b), name=name)
        if t in (operator.mul, torch.mul, "mul"):
            a, b = args[0], args[1]
            if not self._is_ff(a):
                a, b = b, a  # commutative: tensor first
            if self._is_ff(b) or np.ndim(b) > 0:
                return ff.multiply(
                    a, self._ensure_ff(ff, b, name), name=name
                )
            return ff.scalar_multiply(a, float(b), name=name)
        if t in (operator.sub, torch.sub, "sub", "rsub", torch.rsub):
            a, b = args[0], args[1]
            if t in ("rsub", torch.rsub):
                a, b = b, a
            if self._is_ff(a) and (self._is_ff(b) or np.ndim(b) > 0):
                return ff.subtract(
                    a, self._ensure_ff(ff, b, name), name=name
                )
            if not self._is_ff(a):  # scalar/array - tensor
                neg = ff.scalar_multiply(b, -1.0, name=f"{name}_neg")
                if np.ndim(a) > 0:
                    return ff.add(
                        neg, self._ensure_ff(ff, a, name), name=name
                    )
                return ff.scalar_add(neg, float(a), name=name)
            return ff.scalar_sub(a, float(b), name=name)
        if t in (operator.truediv, torch.div, "div"):
            return ff.scalar_truediv(args[0], float(args[1]), name=name)
        if tname == "scaled_dot_product_attention":
            return self._sdpa(ff, name, args, kwargs)
        if t is getattr:
            obj, attr = args[0], args[1]
            if self._is_ff(obj):
                if attr == "dtype":
                    return np.dtype(obj.spec.dtype.value)
                if attr == "shape":
                    return tuple(obj.shape)
                raise NotImplementedError(f"getattr({attr!r}) on traced tensor")
            return getattr(obj, attr)
        if t is operator.getitem or tname == "getitem":
            return self._getitem(ff, name, args[0], args[1])
        if tname in ("masked_fill", "masked_fill_"):
            x, m, v = args[0], args[1], args[2]
            # fill where m (a 0/1-valued traced mask) is set:
            # x·(1-m) + m·v — elementwise, broadcasting like torch.
            # ±inf fills (the standard attention-mask idiom) are clamped
            # to the framework's finite mask constant: m·(-inf) would
            # turn every UNmasked position into 0·-inf = NaN.
            v = float(np.clip(float(v), -1e30, 1e30))
            m = self._ensure_ff(ff, m, name)
            keep = ff.scalar_add(
                ff.scalar_multiply(m, -1.0, name=f"{name}_negm"),
                1.0,
                name=f"{name}_keep",
            )
            return ff.add(
                ff.multiply(x, keep, name=f"{name}_kept"),
                ff.scalar_multiply(m, float(v), name=f"{name}_fill"),
                name=name,
            )
        if tname in ("expand", "expand_as"):
            # consumers broadcast; shape metadata alone needs no op
            return args[0]
        if tname in ("to", "type_as", "float", "bool", "contiguous", "clone",
                     "detach") or t is torch.clone:
            x = args[0]
            if tname == "to" and len(args) > 1 and self._is_ff(x):
                try:
                    target = self._np_dtype(args[1])
                except TypeError:
                    return x  # .to(device) / .to(memory_format)
                if target == np.bool_:
                    # traced masks are already 0/1-valued floats
                    return x
                if str(target) != x.spec.dtype.value:
                    return ff.cast(x, str(target), name=name)
            return x
        if tname == "size":
            x = args[0]
            shape = tuple(int(d) for d in x.shape)
            return shape[args[1]] if len(args) > 1 else shape
        if tname == "dim":
            return len(args[0].shape)
        if tname in ("unsqueeze",):
            x = args[0]
            d = args[1] % (len(x.shape) + 1)
            shape = list(x.shape)
            shape.insert(d, 1)
            return ff.reshape(x, tuple(shape), name=name)
        if tname == "permute":
            perm = args[1] if isinstance(args[1], (tuple, list)) else args[1:]
            return ff.transpose(args[0], tuple(int(p) for p in perm), name=name)
        if t in (torch.matmul, torch.bmm, "matmul", "bmm"):
            return ff.batch_matmul(args[0], args[1], name=name)
        cmp = _cmp_ops().get(t)
        if (
            cmp is not None
            and self._is_ff(args[0])
            and not self._is_ff(args[1])
            and np.ndim(args[1]) == 0
        ):
            # traced masks: (x > 0).float() — 0/1 in x's dtype, so the
            # following .float()/.bool() casts are identities. (Array
            # comparands fall through to the loud unsupported error.)
            return ff.scalar_compare(args[0], cmp, float(args[1]), name=name)
        if t in (F.relu, torch.relu, "relu"):
            return ff.relu(args[0], name=name)
        if t in (F.gelu, "gelu"):
            return ff.gelu(args[0], name=name)
        if t in (torch.sigmoid, F.sigmoid, "sigmoid"):
            return ff.sigmoid(args[0], name=name)
        if t in (torch.tanh, F.tanh, "tanh"):
            return ff.tanh(args[0], name=name)
        if t in (F.softmax, torch.softmax, "softmax"):
            axis = kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return ff.softmax(args[0], axis=axis if axis is not None else -1,
                              name=name)
        if t in (torch.flatten, "flatten"):
            return ff.flat(args[0], name=name)
        if t in (torch.cat, "cat"):
            tensors = args[0]
            axis = kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return ff.concat(tensors, axis=axis, name=name)
        if t in (torch.reshape, "reshape", "view"):
            x = args[0]
            shape = args[1] if isinstance(args[1], (tuple, list)) else args[1:]
            shape = [int(s) for s in shape]
            if -1 in shape:  # resolve from the static input shape
                total = 1
                for d in x.shape:
                    total *= int(d)
                known = 1
                for s in shape:
                    if s != -1:
                        known *= s
                shape[shape.index(-1)] = total // known
            return ff.reshape(x, tuple(shape), name=name)
        if t in (torch.transpose, "transpose"):
            x = args[0]
            d0, d1 = int(args[1]), int(args[2])
            ndim = len(x.shape)
            perm = list(range(ndim))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(x, perm, name=name)
        if t in (torch.exp, "exp"):
            return ff.exp(args[0], name=name)
        if t in (torch.pow, operator.pow, "pow"):
            return ff.pow(args[0], float(args[1]), name=name)
        if t in (F.dropout, "dropout"):
            return ff.dropout(args[0], rate=kwargs.get("p", 0.5), name=name)
        raise NotImplementedError(f"fx function/method {t} unsupported")

    # -- traced-transformer helpers ------------------------------------

    @staticmethod
    def _fold_meta(tname, t, args, kwargs):
        """Evaluate a node eagerly when every argument is a folded
        python/numpy value (shape arithmetic, buffer slicing, dtype
        plumbing from the HF trace). Returns NotImplemented when the
        target isn't meta-foldable."""
        import torch

        if t in (operator.add, operator.sub, operator.mul, operator.eq,
                 operator.floordiv, operator.truediv, operator.getitem):
            return t(*args)
        if tname == "expand":
            return np.broadcast_to(
                np.asarray(args[0]), tuple(int(d) for d in args[1:])
            )
        if tname == "size":
            shape = tuple(np.asarray(args[0]).shape)
            return shape[args[1]] if len(args) > 1 else shape
        if tname == "dim":
            return np.asarray(args[0]).ndim
        if t is torch.tensor or tname == "tensor":
            dt = kwargs.get("dtype")
            return np.asarray(
                args[0],
                dtype=PyTorchModel._np_dtype(dt) if dt is not None else None,
            )
        if t is torch.finfo or tname == "finfo":
            return np.finfo(PyTorchModel._np_dtype(args[0]))
        if t is getattr:
            return getattr(args[0], args[1])
        if tname in ("to", "contiguous", "clone", "detach", "float", "bool"):
            return args[0]
        return NotImplemented

    def _getitem(self, ff, name, obj, idx):
        """getitem over folded values (tuples, buffers) or over traced
        tensors — the latter only for the None/full-slice indexing HF
        uses to grow mask dims (``mask[:, None, None, :]``)."""
        if not self._is_ff(obj):
            return obj[idx]
        if not isinstance(idx, tuple):
            idx = (idx,)
        shape = list(obj.shape)
        out_shape = []
        dim = 0
        for e in idx:
            if e is None:
                out_shape.append(1)
            elif isinstance(e, slice):
                start, stop, step = e.indices(shape[dim])
                if step != 1:
                    raise NotImplementedError("strided tensor slicing")
                if (start, stop) != (0, shape[dim]):
                    raise NotImplementedError(
                        "partial tensor slicing (only full slices / None "
                        "unsqueezing supported on traced tensors)"
                    )
                out_shape.append(shape[dim])
                dim += 1
            else:
                raise NotImplementedError(
                    f"integer tensor indexing in trace: {idx}"
                )
        out_shape.extend(shape[dim:])
        return ff.reshape(obj, tuple(out_shape), name=name)

    def _sdpa(self, ff, name, args, kwargs):
        """torch.scaled_dot_product_attention → QK^T·scale (+ additive
        mask) → softmax → PV, on existing graph ops (the training-path
        attention; the reference's traced MHA lowers to its attention op
        the same way)."""
        import math

        q, k, v = args[0], args[1], args[2]
        mask = kwargs.get("attn_mask", args[3] if len(args) > 3 else None)
        is_causal = kwargs.get(
            "is_causal", args[5] if len(args) > 5 else False
        )
        dk = int(q.shape[-1])
        n = len(k.shape)
        perm = list(range(n))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        kt = ff.transpose(k, tuple(perm), name=f"{name}_kT")
        scores = ff.scalar_multiply(
            ff.batch_matmul(q, kt, name=f"{name}_qk"),
            kwargs.get("scale") or 1.0 / math.sqrt(dk),
            name=f"{name}_scaled",
        )
        if is_causal:
            S, T = int(q.shape[-2]), int(k.shape[-2])
            causal = np.where(
                np.tril(np.ones((S, T), bool)), 0.0, -1e9
            ).astype(np.float32)
            mask_ff = ff.constant(causal, name=f"{name}_causal")
            scores = ff.add(scores, mask_ff, name=f"{name}_cmasked")
        if mask is not None:
            scores = ff.add(
                scores, self._ensure_ff(ff, mask, name), name=f"{name}_masked"
            )
        probs = ff.softmax(scores, axis=-1, name=f"{name}_probs")
        return ff.batch_matmul(probs, v, name=name)
