"""PyTorch → FFModel importer via torch.fx symbolic tracing.

TPU-native counterpart of the reference's fx frontend (reference
``python/flexflow/torch/model.py:1-2607``: ``PyTorchModel.torch_to_ff``
walks a symbolically-traced graph and emits one FFModel layer call per
fx node). Same architecture here: trace → per-node translation table →
FFModel builder calls; weights are converted from the module's
state_dict into the framework's per-op pytrees (HF linear layout
transposed to (in, out)).

Only imported when torch is available; the rest of the framework has no
torch dependency.
"""
from __future__ import annotations

import operator
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class PyTorchModel:
    """Wraps a ``torch.nn.Module``; ``to_ff(ffmodel, input_tensors)``
    replays its fx graph as FFModel layers and returns the outputs
    (reference ``PyTorchModel.torch_to_ff``)."""

    def __init__(self, module, batch_size: Optional[int] = None):
        import torch.fx

        self.module = module.eval()
        self.graph_module = torch.fx.symbolic_trace(module)
        self.batch_size = batch_size

    # ------------------------------------------------------------------

    def to_ff(self, ffmodel, input_tensors: Sequence[Any]) -> List[Any]:
        """Translate the traced graph into ``ffmodel`` layer calls.
        ``input_tensors`` are FFModel Tensors (one per fx placeholder,
        in order). Returns the list of output Tensors; converted weights
        are stored on ``ffmodel._imported_params`` keyed by node name so
        ``compile()``-initialised params can be overwritten via
        :meth:`load_weights`."""
        import torch

        env: Dict[str, Any] = {}
        placeholders = [
            n for n in self.graph_module.graph.nodes if n.op == "placeholder"
        ]
        assert len(placeholders) == len(input_tensors), (
            f"model takes {len(placeholders)} inputs, got {len(input_tensors)}"
        )
        for node, t in zip(placeholders, input_tensors):
            env[node.name] = t

        self._weights: Dict[str, Dict[str, np.ndarray]] = {}
        outputs: List[Any] = []

        for node in self.graph_module.graph.nodes:
            if node.op == "placeholder":
                continue
            if node.op == "output":
                args = node.args[0]
                outputs = list(args) if isinstance(args, (tuple, list)) else [args]
                outputs = [env[a.name] for a in outputs]
                continue
            if node.op == "call_module":
                mod = self.graph_module.get_submodule(node.target)
                env[node.name] = self._module_node(ffmodel, node, mod, env)
            elif node.op in ("call_function", "call_method"):
                env[node.name] = self._function_node(ffmodel, node, env)
            elif node.op == "get_attr":
                raise NotImplementedError(
                    f"get_attr nodes (free parameters) unsupported: {node.target}"
                )
        ffmodel._imported_params = getattr(ffmodel, "_imported_params", {})
        ffmodel._imported_params.update(self._weights)
        return outputs

    def load_weights(self, ffmodel) -> None:
        """Overwrite ``ffmodel.params`` entries with the converted torch
        weights (call after ``compile()``)."""
        from . import load_imported_weights

        load_imported_weights(ffmodel)

    # ------------------------------------------------------------------

    def _arg(self, env, a):
        import torch.fx

        if isinstance(a, torch.fx.Node):
            return env[a.name]
        return a

    def _module_node(self, ff, node, mod, env):
        import torch.nn as nn

        x = self._arg(env, node.args[0])
        name = node.name

        if isinstance(mod, nn.Linear):
            out = ff.dense(x, mod.out_features, use_bias=mod.bias is not None,
                           name=name)
            w = {"kernel": mod.weight.detach().numpy().T}
            if mod.bias is not None:
                w["bias"] = mod.bias.detach().numpy()
            self._weights[name] = w
            return out
        if isinstance(mod, nn.Conv2d):
            out = ff.conv2d(
                x, mod.out_channels, mod.kernel_size[0], mod.kernel_size[1],
                mod.stride[0], mod.stride[1], mod.padding[0], mod.padding[1],
                groups=mod.groups, use_bias=mod.bias is not None, name=name,
            )
            # framework conv kernels are OIHW like torch
            w = {"kernel": mod.weight.detach().numpy()}
            if mod.bias is not None:
                w["bias"] = mod.bias.detach().numpy()
            self._weights[name] = w
            return out
        if isinstance(mod, nn.Embedding):
            out = ff.embedding(x, mod.num_embeddings, mod.embedding_dim, name=name)
            self._weights[name] = {"table": mod.weight.detach().numpy()}
            return out
        if isinstance(mod, nn.LayerNorm):
            out = ff.layer_norm(x, eps=mod.eps,
                                elementwise_affine=mod.elementwise_affine,
                                name=name)
            if mod.elementwise_affine:
                self._weights[name] = {
                    "gamma": mod.weight.detach().numpy(),
                    "beta": mod.bias.detach().numpy(),
                }
            return out
        if isinstance(mod, nn.BatchNorm2d):
            return ff.batch_norm(x, relu=False, name=name)
        if isinstance(mod, nn.MaxPool2d):
            kh, kw = self._pair(mod.kernel_size)
            sh, sw = self._pair(mod.stride or mod.kernel_size)
            ph, pw = self._pair(mod.padding)
            return ff.pool2d(x, kh, kw, sh, sw, ph, pw, pool_type="max", name=name)
        if isinstance(mod, nn.AvgPool2d):
            kh, kw = self._pair(mod.kernel_size)
            sh, sw = self._pair(mod.stride or mod.kernel_size)
            ph, pw = self._pair(mod.padding)
            return ff.pool2d(x, kh, kw, sh, sw, ph, pw, pool_type="avg", name=name)
        if isinstance(mod, nn.ReLU):
            return ff.relu(x, name=name)
        if isinstance(mod, nn.GELU):
            return ff.gelu(x, name=name)
        if isinstance(mod, nn.Sigmoid):
            return ff.sigmoid(x, name=name)
        if isinstance(mod, nn.Tanh):
            return ff.tanh(x, name=name)
        if isinstance(mod, nn.Softmax):
            return ff.softmax(x, axis=mod.dim if mod.dim is not None else -1,
                              name=name)
        if isinstance(mod, nn.Dropout):
            return ff.dropout(x, rate=mod.p, name=name)
        if isinstance(mod, nn.Flatten):
            return ff.flat(x, name=name)
        if isinstance(mod, nn.Identity):
            return x
        raise NotImplementedError(f"fx module {type(mod).__name__} ({node.target})")

    @staticmethod
    def _pair(v):
        return (v, v) if isinstance(v, int) else (v[0], v[1])

    def _function_node(self, ff, node, env):
        import torch
        import torch.nn.functional as F

        args = [self._arg(env, a) for a in node.args]
        kwargs = {k: self._arg(env, v) for k, v in node.kwargs.items()}
        t = node.target
        name = node.name

        if t in (operator.add, torch.add, "add"):
            if hasattr(args[1], "ref"):
                return ff.add(args[0], args[1], name=name)
            return ff.scalar_add(args[0], float(args[1]), name=name)
        if t in (operator.mul, torch.mul, "mul"):
            if hasattr(args[1], "ref"):
                return ff.multiply(args[0], args[1], name=name)
            return ff.scalar_multiply(args[0], float(args[1]), name=name)
        if t in (operator.sub, torch.sub, "sub"):
            if hasattr(args[1], "ref"):
                return ff.subtract(args[0], args[1], name=name)
            return ff.scalar_sub(args[0], float(args[1]), name=name)
        if t in (operator.truediv, torch.div, "div"):
            return ff.scalar_truediv(args[0], float(args[1]), name=name)
        if t in (F.relu, torch.relu, "relu"):
            return ff.relu(args[0], name=name)
        if t in (F.gelu, "gelu"):
            return ff.gelu(args[0], name=name)
        if t in (torch.sigmoid, F.sigmoid, "sigmoid"):
            return ff.sigmoid(args[0], name=name)
        if t in (torch.tanh, F.tanh, "tanh"):
            return ff.tanh(args[0], name=name)
        if t in (F.softmax, torch.softmax, "softmax"):
            axis = kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return ff.softmax(args[0], axis=axis if axis is not None else -1,
                              name=name)
        if t in (torch.flatten, "flatten"):
            return ff.flat(args[0], name=name)
        if t in (torch.cat, "cat"):
            tensors = args[0]
            axis = kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return ff.concat(tensors, axis=axis, name=name)
        if t in (torch.reshape, "reshape", "view"):
            shape = args[1] if isinstance(args[1], (tuple, list)) else args[1:]
            shape = tuple(int(s) for s in shape)
            if shape[0] == -1 and self.batch_size is not None:
                shape = (self.batch_size,) + shape[1:]
            return ff.reshape(args[0], shape, name=name)
        if t in (torch.transpose, "transpose"):
            x = args[0]
            d0, d1 = int(args[1]), int(args[2])
            ndim = len(x.shape)
            perm = list(range(ndim))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(x, perm, name=name)
        if t in (torch.exp, "exp"):
            return ff.exp(args[0], name=name)
        if t in (torch.pow, operator.pow, "pow"):
            return ff.pow(args[0], float(args[1]), name=name)
        if t == "contiguous" or t is torch.clone:
            return args[0]
        if t in (F.dropout, "dropout"):
            return ff.dropout(args[0], rate=kwargs.get("p", 0.5), name=name)
        raise NotImplementedError(f"fx function/method {t} unsupported")
