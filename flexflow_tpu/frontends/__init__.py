"""Model-import frontends.

The reference ships three import paths into FFModel (SURVEY.md §2.4):
a torch.fx tracer (reference ``python/flexflow/torch/model.py:2408``),
an ONNX graph translator (``python/flexflow/onnx/model.py``), and a
near-complete Keras clone (``python/flexflow/keras/``). The TPU
equivalents map onto the same FFModel layer-builder API; weights
convert to the framework's per-op pytrees so imported models are
immediately trainable/servable on the mesh.
"""
def load_imported_weights(ffmodel) -> None:
    """Overwrite compiled params with frontend-converted weights stored
    on ``ffmodel._imported_params``, and non-trainable state (batch-norm
    running stats) from ``ffmodel._imported_state`` (shared by all
    importers)."""
    import jax.numpy as jnp

    assert ffmodel.params is not None, "compile() the model first"
    for name, w in getattr(ffmodel, "_imported_params", {}).items():
        if name in ffmodel.params:
            ffmodel.params[name] = {
                k: jnp.asarray(v, ffmodel.params[name][k].dtype)
                for k, v in w.items()
            }
    imported_state = getattr(ffmodel, "_imported_state", {})
    if imported_state:
        by_name = {n.name: n.id for n in ffmodel.graph.nodes}
        for name, st in imported_state.items():
            nid = by_name.get(name)
            if nid is not None and nid in ffmodel.model_state:
                ffmodel.model_state[nid] = {
                    k: jnp.asarray(v, ffmodel.model_state[nid][k].dtype)
                    for k, v in st.items()
                }


from .torch_fx import PyTorchModel
from .onnx_model import ONNXModel

__all__ = ["PyTorchModel", "ONNXModel", "load_imported_weights"]
