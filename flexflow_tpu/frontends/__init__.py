"""Model-import frontends.

The reference ships three import paths into FFModel (SURVEY.md §2.4):
a torch.fx tracer (reference ``python/flexflow/torch/model.py:2408``),
an ONNX graph translator (``python/flexflow/onnx/model.py``), and a
near-complete Keras clone (``python/flexflow/keras/``). The TPU
equivalents map onto the same FFModel layer-builder API; weights
convert to the framework's per-op pytrees so imported models are
immediately trainable/servable on the mesh.
"""
def load_imported_weights(ffmodel) -> None:
    """Overwrite compiled params with frontend-converted weights stored
    on ``ffmodel._imported_params`` (shared by all importers)."""
    import jax.numpy as jnp

    assert ffmodel.params is not None, "compile() the model first"
    for name, w in getattr(ffmodel, "_imported_params", {}).items():
        if name in ffmodel.params:
            ffmodel.params[name] = {
                k: jnp.asarray(v, ffmodel.params[name][k].dtype)
                for k, v in w.items()
            }


from .torch_fx import PyTorchModel
from .onnx_model import ONNXModel

__all__ = ["PyTorchModel", "ONNXModel", "load_imported_weights"]
