"""ONNX → FFModel importer.

TPU-native counterpart of the reference's ONNX frontend (reference
``python/flexflow/onnx/model.py:1-375``: per-node ``handleX`` methods
emitting FFModel layer calls). Same per-op translation-table shape.
Initializers (weights) convert into the framework's per-op pytrees.

``onnx`` isn't a baked-in dependency; the importer accepts any object
with the ONNX ModelProto interface (``graph.node``, ``graph.initializer``)
— in tests a lightweight stand-in is used when the real package is
missing.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _attr_map(node) -> Dict[str, Any]:
    out = {}
    for a in node.attribute:
        # minimal AttributeProto decoding: ints, floats, int-lists
        if a.type == 2:     # INT
            out[a.name] = a.i
        elif a.type == 1:   # FLOAT
            out[a.name] = a.f
        elif a.type == 7:   # INTS
            out[a.name] = list(a.ints)
        elif a.type == 6:   # FLOATS
            out[a.name] = list(a.floats)
        elif a.type == 3:   # STRING
            out[a.name] = a.s.decode() if isinstance(a.s, bytes) else a.s
    return out


def _tensor_to_np(t) -> np.ndarray:
    try:
        from onnx import numpy_helper

        return numpy_helper.to_array(t)
    except ImportError:
        # minimal decode: raw_data + dims + TensorProto data_type
        dtypes = {1: np.float32, 6: np.int32, 7: np.int64, 11: np.float64,
                  10: np.float16, 9: np.bool_}
        dt = dtypes.get(getattr(t, "data_type", 1), np.float32)
        return np.frombuffer(t.raw_data, dtype=dt).reshape(tuple(t.dims))


class ONNXModel:
    """``ONNXModel(model_proto_or_path).to_ff(ffmodel, inputs)`` replays
    the ONNX graph as FFModel layers (reference ``ONNXModel.apply``)."""

    def __init__(self, model: Any):
        if isinstance(model, (str, bytes)):
            import onnx

            model = onnx.load(model)
        self.model = model
        self.initializers: Dict[str, np.ndarray] = {
            t.name: _tensor_to_np(t) for t in model.graph.initializer
        }

    def to_ff(self, ffmodel, input_tensors: Sequence[Any]) -> List[Any]:
        env: Dict[str, Any] = {}
        graph_inputs = [
            i for i in self.model.graph.input
            if i.name not in self.initializers
        ]
        assert len(graph_inputs) == len(input_tensors)
        for gi, t in zip(graph_inputs, input_tensors):
            env[gi.name] = t
        self._weights: Dict[str, Dict[str, np.ndarray]] = {}
        self._state: Dict[str, Dict[str, np.ndarray]] = {}

        for node in self.model.graph.node:
            handler = getattr(self, f"_op_{node.op_type.lower()}", None)
            if handler is None:
                raise NotImplementedError(f"ONNX op {node.op_type}")
            outs = handler(ffmodel, node, env)
            if not isinstance(outs, (tuple, list)):
                outs = [outs]
            for name, val in zip(node.output, outs):
                env[name] = val

        ffmodel._imported_params = getattr(ffmodel, "_imported_params", {})
        ffmodel._imported_params.update(self._weights)
        ffmodel._imported_state = getattr(ffmodel, "_imported_state", {})
        ffmodel._imported_state.update(self._state)
        return [env[o.name] for o in self.model.graph.output]

    def load_weights(self, ffmodel) -> None:
        from . import load_imported_weights

        load_imported_weights(ffmodel)

    # ------------------------------------------------------------------
    # per-op handlers (reference handleX methods)

    def _name(self, node):
        return node.name or node.output[0]

    def _op_gemm(self, ff, node, env):
        x = env[node.input[0]]
        w = self.initializers[node.input[1]]
        attrs = _attr_map(node)
        if attrs.get("transA", 0) or attrs.get("alpha", 1.0) != 1.0 or \
                attrs.get("beta", 1.0) not in (0.0, 1.0):
            raise NotImplementedError(
                f"Gemm with transA/alpha/beta != defaults: {attrs}"
            )
        if attrs.get("transB", 0):
            w = w.T
        out_dim = w.shape[1]
        use_bias = len(node.input) > 2
        name = self._name(node)
        out = ff.dense(x, out_dim, use_bias=use_bias, name=name)
        weights = {"kernel": w}
        if use_bias:
            weights["bias"] = self.initializers[node.input[2]]
        self._weights[name] = weights
        return out

    def _op_matmul(self, ff, node, env):
        if node.input[1] in self.initializers:
            w = self.initializers[node.input[1]]
            name = self._name(node)
            out = ff.dense(env[node.input[0]], w.shape[1], use_bias=False,
                           name=name)
            self._weights[name] = {"kernel": w}
            return out
        return ff.batch_matmul(env[node.input[0]], env[node.input[1]],
                               name=self._name(node))

    def _op_conv(self, ff, node, env):
        x = env[node.input[0]]
        w = self.initializers[node.input[1]]  # OIHW
        attrs = _attr_map(node)
        strides = attrs.get("strides", [1, 1])
        pads = attrs.get("pads", [0, 0, 0, 0])
        groups = attrs.get("group", 1)
        name = self._name(node)
        out = ff.conv2d(
            x, w.shape[0], w.shape[2], w.shape[3],
            strides[0], strides[1], pads[0], pads[1],
            groups=groups, use_bias=len(node.input) > 2, name=name,
        )
        weights = {"kernel": w}  # framework conv kernels are OIHW
        if len(node.input) > 2:
            weights["bias"] = self.initializers[node.input[2]]
        self._weights[name] = weights
        return out

    def _op_maxpool(self, ff, node, env):
        a = _attr_map(node)
        k = a["kernel_shape"]; s = a.get("strides", k); p = a.get("pads", [0]*4)
        return ff.pool2d(env[node.input[0]], k[0], k[1], s[0], s[1], p[0], p[1],
                         pool_type="max", name=self._name(node))

    def _op_averagepool(self, ff, node, env):
        a = _attr_map(node)
        k = a["kernel_shape"]; s = a.get("strides", k); p = a.get("pads", [0]*4)
        if any(p) and not a.get("count_include_pad", 0):
            # our avg pool divides by kh*kw including padded cells
            raise NotImplementedError(
                "AveragePool with pads and count_include_pad=0"
            )
        return ff.pool2d(env[node.input[0]], k[0], k[1], s[0], s[1], p[0], p[1],
                         pool_type="avg", name=self._name(node))

    def _op_relu(self, ff, node, env):
        return ff.relu(env[node.input[0]], name=self._name(node))

    def _op_sigmoid(self, ff, node, env):
        return ff.sigmoid(env[node.input[0]], name=self._name(node))

    def _op_tanh(self, ff, node, env):
        return ff.tanh(env[node.input[0]], name=self._name(node))

    def _op_softmax(self, ff, node, env):
        axis = _attr_map(node).get("axis", -1)
        return ff.softmax(env[node.input[0]], axis=axis, name=self._name(node))

    def _op_flatten(self, ff, node, env):
        return ff.flat(env[node.input[0]], name=self._name(node))

    def _op_add(self, ff, node, env):
        return ff.add(env[node.input[0]], env[node.input[1]],
                      name=self._name(node))

    def _op_mul(self, ff, node, env):
        return ff.multiply(env[node.input[0]], env[node.input[1]],
                           name=self._name(node))

    def _op_sub(self, ff, node, env):
        return ff.subtract(env[node.input[0]], env[node.input[1]],
                           name=self._name(node))

    def _op_concat(self, ff, node, env):
        axis = _attr_map(node).get("axis", 0)
        return ff.concat([env[i] for i in node.input], axis=axis,
                         name=self._name(node))

    def _op_dropout(self, ff, node, env):
        return ff.dropout(env[node.input[0]],
                          rate=_attr_map(node).get("ratio", 0.5),
                          name=self._name(node))

    def _op_reshape(self, ff, node, env):
        shape = self.initializers[node.input[1]].astype(int).tolist()
        x = env[node.input[0]]
        total = 1
        for d in x.shape:
            total *= d
        # ONNX: 0 copies the input dim, -1 infers (at most one)
        shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
        if -1 in shape:
            known = 1
            for s in shape:
                if s != -1:
                    known *= s
            shape[shape.index(-1)] = total // known
        return ff.reshape(x, tuple(shape), name=self._name(node))

    def _op_transpose(self, ff, node, env):
        perm = _attr_map(node)["perm"]
        return ff.transpose(env[node.input[0]], perm, name=self._name(node))

    def _op_identity(self, ff, node, env):
        return env[node.input[0]]

    # -- widened op set (reference handle* coverage,
    #    python/flexflow/onnx/model.py handleBatchNormalization etc.) --

    def _op_batchnormalization(self, ff, node, env):
        name = self._name(node)
        out = ff.batch_norm(env[node.input[0]], relu=False,
                            eps=_attr_map(node).get("epsilon", 1e-5),
                            name=name)
        self._weights[name] = {
            "scale": self.initializers[node.input[1]],
            "bias": self.initializers[node.input[2]],
        }
        # trained running stats (inputs 3/4) go to the model's
        # non-trainable STATE, not the params — without them inference
        # would silently normalise with mean=0/var=1
        if len(node.input) > 4:
            self._state[name] = {
                "mean": self.initializers[node.input[3]],
                "var": self.initializers[node.input[4]],
            }
        return out

    def _op_layernormalization(self, ff, node, env):
        name = self._name(node)
        out = ff.layer_norm(env[node.input[0]],
                            eps=_attr_map(node).get("epsilon", 1e-5),
                            name=name)
        w = {"gamma": self.initializers[node.input[1]]}
        if len(node.input) > 2:
            w["beta"] = self.initializers[node.input[2]]
        self._weights[name] = w
        return out

    def _op_globalaveragepool(self, ff, node, env):
        return ff.mean(env[node.input[0]], axes=(2, 3), keepdims=True,
                       name=self._name(node))

    def _int_list(self, node, key, input_idx=1):
        """Opset-13+ int-list decode: attribute form, else an
        initializer input; None when neither is present. Dynamic
        (non-initializer) list inputs are refused loudly."""
        val = _attr_map(node).get(key)
        if val is not None:
            return [int(v) for v in val]
        if len(node.input) > input_idx:
            iname = node.input[input_idx]
            if iname not in self.initializers:
                raise NotImplementedError(
                    f"{node.op_type}: dynamic (non-initializer) "
                    f"{key!r} input {iname!r} is not supported"
                )
            return self.initializers[iname].astype(int).tolist()
        return None

    def _op_gather(self, ff, node, env):
        axis = _attr_map(node).get("axis", 0)
        # embedding lookup: axis-0 row gather from an initializer table
        if node.input[0] in self.initializers and axis == 0:
            table = self.initializers[node.input[0]]
            name = self._name(node)
            out = ff.embedding(env[node.input[1]], table.shape[0],
                               table.shape[1], name=name)
            self._weights[name] = {"table": table}
            return out
        # ONNX Gather is np.take (output rank = data.rank-1+idx.rank);
        # the framework's gather op is take_along_axis — the two only
        # coincide for rank-1 data with rank-1 indices. Refuse the rest
        # rather than silently compute the wrong gather.
        data, idx = env[node.input[0]], env[node.input[1]]
        if self._is_ff_rank1(data) and self._is_ff_rank1(idx):
            return ff.gather(data, idx, axis=0, name=self._name(node))
        raise NotImplementedError(
            "general ONNX Gather (np.take semantics) is only supported "
            "for axis-0 initializer tables (embedding) or rank-1 inputs"
        )

    @staticmethod
    def _is_ff_rank1(t) -> bool:
        return len(t.shape) == 1

    def _op_split(self, ff, node, env):
        x = env[node.input[0]]
        axis = _attr_map(node).get("axis", 0)
        sizes = self._int_list(node, "split")
        if sizes is None:
            n = len(node.output)
            sizes = [x.shape[axis] // n] * n
        return ff.split(x, list(sizes), axis=axis, name=self._name(node))

    # onnx.TensorProto dtype enum → numpy name (the onnx package is
    # optional; proto-shaped stand-ins must import too)
    _CAST_DTYPES = {
        1: "float32", 6: "int32", 7: "int64", 9: "bool",
        10: "float16", 11: "float64", 16: "bfloat16",
    }

    def _op_cast(self, ff, node, env):
        to = int(_attr_map(node)["to"])
        return ff.cast(env[node.input[0]], self._CAST_DTYPES[to],
                       name=self._name(node))

    def _op_reducemean(self, ff, node, env):
        axes = self._int_list(node, "axes")
        if axes is None:  # ONNX default: reduce over ALL dims
            axes = tuple(range(len(env[node.input[0]].shape)))
        return ff.mean(env[node.input[0]], axes=tuple(axes),
                       keepdims=bool(_attr_map(node).get("keepdims", 1)),
                       name=self._name(node))

    def _op_gelu(self, ff, node, env):
        return ff.gelu(env[node.input[0]], name=self._name(node))

    def _op_unsqueeze(self, ff, node, env):
        x = env[node.input[0]]
        axes = self._int_list(node, "axes")
        # ONNX: axes are relative to the OUTPUT rank (input rank +
        # number of inserted dims) — e.g. axes=[2,3] on (B,C) must give
        # (B,C,1,1), not an input-rank-relative insertion
        out_rank = len(x.shape) + len(axes)
        where = sorted(int(a) % out_rank for a in axes)
        assert len(set(where)) == len(where), f"duplicate axes {axes}"
        shape = []
        it = iter(x.shape)
        for i in range(out_rank):
            shape.append(1 if i in where else next(it))
        return ff.reshape(x, tuple(shape), name=self._name(node))

    def _op_squeeze(self, ff, node, env):
        x = env[node.input[0]]
        axes = self._int_list(node, "axes")
        if axes is None:
            shape = [d for d in x.shape if d != 1]
        else:
            drop = {int(a) % len(x.shape) for a in axes}
            shape = [d for i, d in enumerate(x.shape) if i not in drop]
        return ff.reshape(x, tuple(shape), name=self._name(node))
