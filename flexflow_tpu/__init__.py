"""flexflow-tpu: a TPU-native deep learning framework with the
capabilities of FlexFlow (training with auto-parallelization; LLM serving
with speculative inference), re-designed for JAX/XLA/Pallas/pjit.

Reference: ArulselvanMadhavan/FlexFlow (studied at /root/reference);
see SURVEY.md for the full capability map.
"""

from .config import FFConfig, init, get_config
from .core import (
    DataType,
    TensorSpec,
    MachineSpec,
    Graph,
    TensorRef,
)
from .model import FFModel, Tensor, TRAINING, INFERENCE
from .data import SingleDataLoader
from .optimizers import SGDOptimizer, AdamOptimizer
from . import losses, metrics, initializers
from . import keras, frontends  # noqa: F401  (import frontends)

__version__ = "0.1.0"

__all__ = [
    "FFConfig",
    "init",
    "get_config",
    "DataType",
    "TensorSpec",
    "MachineSpec",
    "Graph",
    "TensorRef",
    "FFModel",
    "Tensor",
    "TRAINING",
    "INFERENCE",
    "SGDOptimizer",
    "AdamOptimizer",
    "losses",
    "metrics",
    "initializers",
    "keras",
    "frontends",
]
