"""flexflow-tpu: a TPU-native deep learning framework with the
capabilities of FlexFlow (training with auto-parallelization; LLM serving
with speculative inference), re-designed for JAX/XLA/Pallas/pjit.

Reference: ArulselvanMadhavan/FlexFlow (studied at /root/reference);
see SURVEY.md for the full capability map.
"""

import jax as _jax

# Sharding-invariant RNG. On jax 0.4.x `jax_threefry_partitionable`
# defaults to False, which makes jax.random values under GSPMD depend on
# the OUTPUT SHARDING of the jitted computation that draws them: the
# same init key produced different row-parallel weights under TP=2 than
# on one device (tests/test_parallel.py::
# test_ffmodel_tp_loss_matches_single_device — the whole
# layout-equivalence contract rests on init being layout-invariant).
# Newer jax flipped the default to True; pin it here for every entry
# point (tests, bench, CLI), not just the test harness.
_jax.config.update("jax_threefry_partitionable", True)

from .config import FFConfig, init, get_config
from .core import (
    DataType,
    TensorSpec,
    MachineSpec,
    Graph,
    TensorRef,
)
from .model import FFModel, Tensor, TRAINING, INFERENCE
from .data import SingleDataLoader
from .optimizers import SGDOptimizer, AdamOptimizer
from . import losses, metrics, initializers
from . import keras, frontends  # noqa: F401  (import frontends)

__version__ = "0.1.0"

__all__ = [
    "FFConfig",
    "init",
    "get_config",
    "DataType",
    "TensorSpec",
    "MachineSpec",
    "Graph",
    "TensorRef",
    "FFModel",
    "Tensor",
    "TRAINING",
    "INFERENCE",
    "SGDOptimizer",
    "AdamOptimizer",
    "losses",
    "metrics",
    "initializers",
    "keras",
    "frontends",
]
