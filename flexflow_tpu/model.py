"""FFModel — the central model-building and training API.

TPU-native equivalent of the reference's ``FFModel`` (reference
``include/flexflow/model.h:396-1281``, ``src/runtime/model.cc``): ~70
layer-builder methods append to an operator graph; ``compile()`` lowers the
graph plus optimizer/loss/metrics into executable form. Where the
reference lowers to a Legion task graph placed by the Unity search, we
lower to **one XLA SPMD program**: a jitted train step whose parallelism
comes from sharding annotations over a named device mesh — compilation
*is* the reference's ``begin_trace``/``end_trace`` replay (SURVEY.md §7
design mapping).
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core.mesh import set_mesh as _set_mesh
from .config import FFConfig, get_config
from .core.dtypes import DataType
from .core.graph import Graph, OpNode, TensorRef
from .core.mesh import DATA_AXIS, MODEL_AXIS, MachineSpec
from .core.tensor import TensorSpec
from .losses import get_loss
from .metrics import PerfMetrics, compute_metrics
from .optimizers import Optimizer, SGDOptimizer
from .ops.registry import OpContext, get_op

# Computation modes (reference CompMode / InferenceMode enums).
TRAINING = "training"
INFERENCE = "inference"


class Tensor:
    """Symbolic tensor handle returned by layer builders (reference
    ``FFModel`` returns ``Tensor`` layer outputs)."""

    __slots__ = ("model", "ref")

    def __init__(self, model: "FFModel", ref: TensorRef):
        self.model = model
        self.ref = ref

    @property
    def spec(self) -> TensorSpec:
        return self.model.graph.out_spec(self.ref)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.spec.shape

    @property
    def dtype(self) -> DataType:
        return self.spec.dtype

    def __repr__(self):
        return f"Tensor({self.spec!r} @node{self.ref.node_id}.{self.ref.out_idx})"


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None, seed: int = 0):
        self.config = config or get_config()
        self.graph = Graph()
        self.input_nodes: List[int] = []
        self.seed = seed or self.config.seed
        self.optimizer: Optional[Optimizer] = None
        self.loss_type: Optional[str] = None
        self.metrics_names: Sequence[str] = ()
        self.mesh: Optional[Mesh] = None
        self.params = None
        self.opt_state = None
        self.model_state: Dict[int, Any] = {}
        self._train_step = None
        self._eval_step = None
        self._fwd = None
        self._output_ref: Optional[TensorRef] = None
        self._step_count = 0
        # sharding overrides installed by the parallelize pass
        self._param_pspecs: Optional[Dict[str, Any]] = None
        self._search_report = None
        # per-node activation constraints (SAMPLE/ATTR searched states)
        self._act_constraints: Dict[str, Any] = {}
        self._compile_args: Optional[Dict[str, Any]] = None
        self._recompile_state = None

    # ------------------------------------------------------------------
    # graph construction

    def _add(
        self,
        op_type: str,
        attrs: Dict[str, Any],
        inputs: Sequence[Tensor],
        name: str = "",
    ) -> Union[Tensor, Tuple[Tensor, ...]]:
        in_refs = [t.ref for t in inputs]
        in_specs = [self.graph.out_spec(r) for r in in_refs]
        out_specs = get_op(op_type).infer(in_specs, attrs)
        node = self.graph.add_node(op_type, attrs, in_refs, out_specs, name=name)
        outs = tuple(Tensor(self, TensorRef(node.id, i)) for i in range(len(out_specs)))
        return outs if len(outs) > 1 else outs[0]

    def create_tensor(
        self, shape: Sequence[int], dtype=DataType.FLOAT, name: str = "input"
    ) -> Tensor:
        dt = DataType.from_any(dtype)
        node = self.graph.add_node(
            "input",
            {"shape": tuple(shape), "dtype": dt.value},
            [],
            [TensorSpec(tuple(shape), dt)],
            name=name,
        )
        self.input_nodes.append(node.id)
        return Tensor(self, TensorRef(node.id, 0))

    # --- layer builders (reference model.h:407-805 names) --------------

    def dense(
        self,
        input: Tensor,
        out_dim: int,
        activation: Optional[str] = None,
        use_bias: bool = True,
        kernel_initializer=None,
        bias_initializer=None,
        kernel_regularizer=None,
        name: str = "",
    ) -> Tensor:
        """``kernel_regularizer``: ``("l1"|"l2", lambda)`` — the penalty
        joins the loss through the op aux-loss channel (reference
        Linear + REG_MODE_L1/L2, keras/regularizers.py)."""
        return self._add(
            "dense",
            dict(
                out_dim=out_dim,
                activation=activation,
                use_bias=use_bias,
                kernel_initializer=kernel_initializer,
                bias_initializer=bias_initializer,
                kernel_regularizer=(
                    tuple(kernel_regularizer) if kernel_regularizer else None
                ),
            ),
            [input],
            name,
        )

    def embedding(
        self,
        input: Tensor,
        num_entries: int,
        out_dim: int,
        aggr: str = "none",
        dtype=DataType.FLOAT,
        kernel_initializer=None,
        name: str = "",
    ) -> Tensor:
        return self._add(
            "embedding",
            dict(
                num_entries=num_entries,
                out_dim=out_dim,
                aggr=aggr,
                dtype=DataType.from_any(dtype).value,
                kernel_initializer=kernel_initializer,
            ),
            [input],
            name,
        )

    def constant(self, value, name: str = "") -> Tensor:
        """Inline constant tensor (frontend-imported buffers: position
        ids, masks)."""
        value = np.asarray(value)
        if value.dtype == np.int64:
            value = value.astype(np.int32)
        if value.dtype == np.float64:
            value = value.astype(np.float32)
        return self._add(
            "constant",
            dict(
                shape=tuple(value.shape),
                dtype=str(value.dtype),
                data=value.tobytes(),
            ),
            [],
            name,
        )

    def transformer_decoder_stack(
        self,
        input: Tensor,
        num_layers: int,
        num_heads: int,
        intermediate_size: int,
        num_kv_heads: Optional[int] = None,
        eps: float = 1e-6,
        rope_theta: float = 10000.0,
        remat: bool = True,
        remat_policy: Optional[str] = None,  # None (full) | "dots"
        attention: str = "xla",
        name: str = "",
    ) -> Tensor:
        """N fused causal decoder blocks over (B, S, D) hidden states as
        ONE graph node (ops/fused_transformer.py): scan-over-layers +
        remat + optional Pallas flash attention — the fast-path bridge
        that lets ``compile(auto_parallel=True)`` reach the same program
        quality as the hand-sharded ``models/llama.make_train_step``
        (the reference's FusedOp + transformer substitutions,
        src/ops/fused.cc)."""
        return self._add(
            "transformer_decoder_stack",
            dict(
                num_layers=num_layers,
                num_heads=num_heads,
                num_kv_heads=num_kv_heads,
                intermediate_size=intermediate_size,
                eps=eps,
                rope_theta=rope_theta,
                remat=remat,
                remat_policy=remat_policy,
                attention=attention,
            ),
            [input],
            name,
        )

    def conv2d(
        self,
        input: Tensor,
        out_channels: int,
        kernel_h: int,
        kernel_w: int,
        stride_h: int = 1,
        stride_w: int = 1,
        padding_h: int = 0,
        padding_w: int = 0,
        activation: Optional[str] = None,
        groups: int = 1,
        use_bias: bool = True,
        kernel_initializer=None,
        bias_initializer=None,
        kernel_regularizer=None,
        name: str = "",
    ) -> Tensor:
        return self._add(
            "conv2d",
            dict(
                out_channels=out_channels,
                kernel_h=kernel_h,
                kernel_w=kernel_w,
                stride_h=stride_h,
                stride_w=stride_w,
                padding_h=padding_h,
                padding_w=padding_w,
                activation=activation,
                groups=groups,
                use_bias=use_bias,
                kernel_initializer=kernel_initializer,
                bias_initializer=bias_initializer,
                kernel_regularizer=(
                    tuple(kernel_regularizer) if kernel_regularizer else None
                ),
            ),
            [input],
            name,
        )

    def pool2d(
        self,
        input: Tensor,
        kernel_h: int,
        kernel_w: int,
        stride_h: int = 1,
        stride_w: int = 1,
        padding_h: int = 0,
        padding_w: int = 0,
        pool_type: str = "max",
        activation: Optional[str] = None,
        name: str = "",
    ) -> Tensor:
        return self._add(
            "pool2d",
            dict(
                kernel_h=kernel_h,
                kernel_w=kernel_w,
                stride_h=stride_h,
                stride_w=stride_w,
                padding_h=padding_h,
                padding_w=padding_w,
                pool_type=pool_type,
                activation=activation,
            ),
            [input],
            name,
        )

    def batch_norm(
        self, input: Tensor, relu: bool = True, eps: float = 1e-5,
        name: str = "",
    ) -> Tensor:
        return self._add("batch_norm", dict(relu=relu, eps=eps), [input], name)

    def layer_norm(
        self,
        input: Tensor,
        axes: Sequence[int] = (-1,),
        elementwise_affine: bool = True,
        eps: float = 1e-5,
        use_bias: bool = True,
        name: str = "",
    ) -> Tensor:
        return self._add(
            "layer_norm",
            dict(
                axes=tuple(axes),
                elementwise_affine=elementwise_affine,
                eps=eps,
                use_bias=use_bias,
            ),
            [input],
            name,
        )

    def rms_norm(self, input: Tensor, eps: float = 1e-6, dim: int = -1, name: str = "") -> Tensor:
        return self._add("rms_norm", dict(eps=eps, dim=dim), [input], name)

    def residual_rms_norm(
        self, input: Tensor, residual: Tensor, eps: float = 1e-6, name: str = ""
    ):
        return self._add("residual_rms_norm", dict(eps=eps), [input, residual], name)

    def residual_layer_norm(
        self,
        input: Tensor,
        residual1: Tensor,
        residual2: Optional[Tensor] = None,
        eps: float = 1e-5,
        elementwise_affine: bool = True,
        use_bias: bool = True,
        name: str = "",
    ):
        inputs = [input, residual1] + ([residual2] if residual2 is not None else [])
        return self._add(
            "residual_layer_norm",
            dict(eps=eps, elementwise_affine=elementwise_affine, use_bias=use_bias),
            inputs,
            name,
        )

    def add_bias_residual_layer_norm(
        self, input: Tensor, residual: Tensor, eps: float = 1e-5, name: str = ""
    ):
        return self._add(
            "add_bias_residual_layer_norm", dict(eps=eps), [input, residual], name
        )

    def sigmoid_silu_multi(self, x1: Tensor, x2: Tensor, name: str = "") -> Tensor:
        return self._add("sigmoid_silu_multi", {}, [x1, x2], name)

    def multihead_attention(
        self,
        query: Tensor,
        key: Tensor,
        value: Tensor,
        embed_dim: int,
        num_heads: int,
        kdim: int = 0,
        vdim: int = 0,
        dropout: float = 0.0,
        bias: bool = True,
        causal: bool = False,
        name: str = "",
    ) -> Tensor:
        return self._add(
            "multihead_attention",
            dict(
                embed_dim=embed_dim,
                num_heads=num_heads,
                kdim=kdim or None,
                vdim=vdim or None,
                dropout=dropout,
                bias=bias,
                causal=causal,
            ),
            [query, key, value],
            name,
        )

    def softmax(self, input: Tensor, axis: int = -1, name: str = "") -> Tensor:
        return self._add("softmax", dict(axis=axis), [input], name)

    def dropout(self, input: Tensor, rate: float = 0.5, name: str = "") -> Tensor:
        return self._add("dropout", dict(rate=rate), [input], name)

    def cast(self, input: Tensor, dtype, name: str = "") -> Tensor:
        return self._add(
            "cast", dict(dtype=DataType.from_any(dtype).value), [input], name
        )

    def concat(self, tensors: Sequence[Tensor], axis: int = 0, name: str = "") -> Tensor:
        return self._add("concat", dict(axis=axis), list(tensors), name)

    def split(self, input: Tensor, sizes: Sequence[int], axis: int = 0, name: str = ""):
        return self._add("split", dict(sizes=tuple(sizes), axis=axis), [input], name)

    def reshape(self, input: Tensor, shape: Sequence[int], name: str = "") -> Tensor:
        return self._add("reshape", dict(shape=tuple(shape)), [input], name)

    def transpose(self, input: Tensor, perm: Sequence[int], name: str = "") -> Tensor:
        return self._add("transpose", dict(perm=tuple(perm)), [input], name)

    def reverse(self, input: Tensor, axis: int = 0, name: str = "") -> Tensor:
        return self._add("reverse", dict(axis=axis), [input], name)

    def flat(self, input: Tensor, name: str = "") -> Tensor:
        return self._add("flat", {}, [input], name)

    def reduce_sum(
        self, input: Tensor, axes: Sequence[int], keepdims: bool = False, name: str = ""
    ) -> Tensor:
        return self._add(
            "reduce", dict(op="sum", axes=tuple(axes), keepdims=keepdims), [input], name
        )

    def mean(
        self, input: Tensor, axes: Sequence[int], keepdims: bool = False, name: str = ""
    ) -> Tensor:
        return self._add(
            "reduce", dict(op="mean", axes=tuple(axes), keepdims=keepdims), [input], name
        )

    def gather(self, input: Tensor, index: Tensor, axis: int = -1, name: str = "") -> Tensor:
        return self._add("gather", dict(axis=axis), [input, index], name)

    def batch_matmul(self, a: Tensor, b: Tensor, name: str = "") -> Tensor:
        return self._add("batch_matmul", {}, [a, b], name)

    # elementwise builders
    # --- MoE builders (reference model.h:509-645) ----------------------

    def top_k(self, input: Tensor, k: int, name: str = ""):
        """Router top-k values+indices (reference ``FFModel::top_k``)."""
        return self._add("top_k", dict(k=k), [input], name)

    def group_by(
        self,
        input: Tensor,
        probs: Tensor,
        k: int,
        capacity_factor: float = 1.25,
        name: str = "",
    ):
        """Dispatch tokens into per-expert buckets (reference
        ``FFModel::group_by``; alpha → capacity_factor)."""
        return self._add(
            "group_by",
            dict(k=k, capacity_factor=capacity_factor),
            [input, probs],
            name,
        )

    def aggregate(
        self,
        expert_out: Tensor,
        combine: Tensor,
        probs: Tensor,
        load_balance_lambda: float = 1e-2,
        name: str = "",
    ):
        """Weighted combine + load-balance loss (reference
        ``FFModel::aggregate`` with λ)."""
        return self._add(
            "aggregate",
            dict(load_balance_lambda=load_balance_lambda),
            [expert_out, combine, probs],
            name,
        )

    def aggregate_spec(
        self,
        expert_out: Tensor,
        combine: Tensor,
        probs: Tensor,
        name: str = "",
    ):
        """Spec-mode combine: fixed routing, no gate gradient / aux loss
        (reference ``FFModel::aggregate_spec``, ops/aggregate_spec.h:14)."""
        return self._add(
            "aggregate_spec", {}, [expert_out, combine, probs], name
        )

    def cache(self, input: Tensor, name: str = ""):
        """Memoize an activation across batches; inference serves the
        cached copy (reference ``FFModel::cache``, ops/cache.h:8)."""
        return self._add("cache", {}, [input], name)

    def moe(
        self,
        input: Tensor,
        num_experts: int,
        top_k: int,
        expert_hidden: int,
        capacity_factor: float = 1.25,
        activation: str = "relu",
        load_balance_lambda: float = 1e-2,
        use_bias: bool = False,
        name: str = "",
    ) -> Tensor:
        """Fused MoE layer (reference ``FFModel::moe``, model.h:622-645)."""
        return self._add(
            "moe",
            dict(
                num_experts=num_experts,
                top_k=top_k,
                expert_hidden=expert_hidden,
                capacity_factor=capacity_factor,
                activation=activation,
                load_balance_lambda=load_balance_lambda,
                use_bias=use_bias,
            ),
            [input],
            name,
        )

    def experts(
        self,
        input: Tensor,
        idx: Tensor,
        gates: Tensor,
        num_experts: int,
        top_k: int,
        expert_hidden: int,
        capacity_factor: float = 2.0,
        activation: str = "gelu",
        name: str = "",
    ) -> Tensor:
        """Fused inference experts on precomputed routing (reference
        ``FFModel::experts``, src/ops/experts.cc)."""
        return self._add(
            "experts",
            dict(
                num_experts=num_experts,
                top_k=top_k,
                expert_hidden=expert_hidden,
                capacity_factor=capacity_factor,
                activation=activation,
            ),
            [input, idx, gates],
            name,
        )

    def _unary(self, op, input, name="", scalar=None):
        attrs = {"op": op}
        if scalar is not None:
            attrs["scalar"] = scalar
        return self._add("element_unary", attrs, [input], name)

    def _binary(self, op, a, b, name=""):
        return self._add("element_binary", dict(op=op), [a, b], name)

    def relu(self, x, name=""):
        return self._unary("relu", x, name)

    def sigmoid(self, x, name=""):
        return self._unary("sigmoid", x, name)

    def tanh(self, x, name=""):
        return self._unary("tanh", x, name)

    def elu(self, x, name=""):
        return self._unary("elu", x, name)

    def gelu(self, x, name=""):
        return self._unary("gelu", x, name)

    def identity(self, x, name=""):
        return self._unary("identity", x, name)

    def exp(self, x, name=""):
        return self._unary("exp", x, name)

    def sin(self, x, name=""):
        return self._unary("sin", x, name)

    def cos(self, x, name=""):
        return self._unary("cos", x, name)

    def pow(self, x, exponent, name=""):
        return self._unary("pow", x, name, scalar=exponent)

    def scalar_multiply(self, x, scalar, name=""):
        return self._unary("scalar_multiply", x, name, scalar=scalar)

    def scalar_add(self, x, scalar, name=""):
        return self._unary("scalar_add", x, name, scalar=scalar)

    def scalar_sub(self, x, scalar, name=""):
        return self._unary("scalar_sub", x, name, scalar=scalar)

    def scalar_truediv(self, x, scalar, name=""):
        return self._unary("scalar_truediv", x, name, scalar=scalar)

    def scalar_compare(self, x, op: str, scalar, name=""):
        """Elementwise compare against a scalar → 0/1 mask in x's dtype
        (op in gt/lt/ge/le/eq)."""
        return self._unary(f"scalar_{op}", x, name, scalar=scalar)

    def add(self, a, b, name=""):
        return self._binary("add", a, b, name)

    def subtract(self, a, b, name=""):
        return self._binary("subtract", a, b, name)

    def multiply(self, a, b, name=""):
        return self._binary("multiply", a, b, name)

    def divide(self, a, b, name=""):
        return self._binary("divide", a, b, name)

    def max(self, a, b, name=""):
        return self._binary("max", a, b, name)

    def min(self, a, b, name=""):
        return self._binary("min", a, b, name)

    # ------------------------------------------------------------------
    # execution

    def _node_attrs(self, node: OpNode) -> Dict[str, Any]:
        d = node.attrs_dict
        d["_node"] = node.id
        return d

    def run_graph(
        self,
        params,
        inputs: Dict[str, Any],
        *,
        training: bool,
        rng=None,
        state=None,
        upto: Optional[TensorRef] = None,
        batch_meta=None,
    ):
        """Interpret the graph — the analog of the reference's per-op task
        launch loop (``FFModel::forward``, reference ``model.cc:2782``),
        except the whole loop is traced into one XLA program under jit."""
        ctx = OpContext(
            training=training,
            rng=rng,
            mesh=self.mesh,
            state=state or {},
            state_updates={} if training else None,
            batch_meta=batch_meta,
        )
        vals: Dict[Tuple[int, int], Any] = {}
        target = upto.node_id if upto is not None else len(self.graph.nodes) - 1
        for node in self.graph.nodes:
            if node.id > target:
                break
            if node.op_type == "input":
                if node.name not in inputs:
                    raise KeyError(f"missing input {node.name!r}")
                vals[(node.id, 0)] = inputs[node.name]
                continue
            op = get_op(node.op_type)
            in_vals = [vals[(r.node_id, r.out_idx)] for r in node.inputs]
            outs = op.forward(
                params.get(node.name, {}), in_vals, self._node_attrs(node), ctx
            )
            spec = self._act_constraints.get(node.name)
            if spec is not None:
                # searched SAMPLE/ATTR states: GSPMD can't infer these
                # from weight shardings, so pin the output layout
                outs = tuple(
                    jax.lax.with_sharding_constraint(o, spec)
                    if hasattr(o, "ndim") and o.ndim >= len(spec)
                    else o
                    for o in outs
                )
            for i, o in enumerate(outs):
                vals[(node.id, i)] = o
        out_ref = upto if upto is not None else TensorRef(target, 0)
        return vals[(out_ref.node_id, out_ref.out_idx)], (ctx.state_updates or {})

    def init_params(self, key=None):
        key = key if key is not None else jax.random.PRNGKey(self.seed)
        params = {}
        for node in self.graph.nodes:
            if node.op_type == "input":
                continue
            op = get_op(node.op_type)
            in_specs = [self.graph.out_spec(r) for r in node.inputs]
            w = op.init(jax.random.fold_in(key, node.id), in_specs, node.attrs_dict)
            if w:
                params[node.name] = w
        return params

    def init_state(self):
        state = {}
        for node in self.graph.nodes:
            op = get_op(node.op_type)
            fn = getattr(op, "init_state", None)
            if fn is None:
                continue
            in_specs = [self.graph.out_spec(r) for r in node.inputs]
            st = fn(in_specs, node.attrs_dict)
            if st:
                state[node.id] = st
        return state

    # ------------------------------------------------------------------
    # compile

    def _make_mesh(self) -> Mesh:
        spec = self.config.machine_spec()
        return spec.make_mesh()

    def _run_unity_search(
        self, output: Optional[Tensor], comp_mode: str
    ) -> Optional[TensorRef]:
        """Unity-style auto-parallelization (reference compile step 2:
        GRAPH_OPTIMIZE_TASK_ID → graph_optimize_task, model.cc:3337,
        graph.cc:2108). Rewrites self.graph, sets mesh degrees and the
        weight-sharding override from the found strategy; honors the
        import/export strategy files (config.h:171-172).

        Returns the ``output`` re-resolved against the (possibly
        rewritten) graph, or None when no output was given. Rewrites
        re-number node ids but preserve NAMES (substitutions.rebuild),
        so mid-graph outputs — metric taps, multi-head graphs — survive
        the search by name."""
        from . import search as unity
        from .core.mesh import MachineSpec

        cfgf = self.config
        out_name = (
            self.graph.nodes[output.ref.node_id].name
            if output is not None
            else None
        )
        out_idx = output.ref.out_idx if output is not None else 0
        # the output coordinate is minted against the PRE-search graph:
        # only rewrite generations from here on may redirect it
        out_gen = self.graph.alias_generation()
        if cfgf.import_strategy_file:
            strategy = unity.ParallelStrategy.load(cfgf.import_strategy_file)
            if strategy.graph is not None:
                # The exported search rewrote the graph: adopt the
                # rewritten graph so the imported per-node choices bind
                # to the node ids they were searched for (reference
                # deserializes graph + views together, graph.cc:2225).
                self.graph = strategy.graph
                self.input_nodes = [
                    n.id for n in self.graph.nodes if n.op_type == "input"
                ]
        else:
            # The search owns the ICI axes not explicitly configured:
            # fixed pipeline/expert/sequence degrees carve the device
            # count down first (the reference likewise fixes inference
            # PP outside its search).
            fixed = (
                cfgf.pipeline_parallelism_degree
                * cfgf.expert_parallelism_degree
                * cfgf.sequence_parallelism_degree
            )
            assert cfgf.num_devices % fixed == 0, (
                f"num_devices={cfgf.num_devices} not divisible by fixed "
                f"pipe*expert*seq degrees = {fixed}"
            )
            budget = cfgf.search_budget if cfgf.search_budget > 0 else 32
            extra_rules = None
            if cfgf.substitution_json_file:
                from .search.substitutions import load_substitutions_json

                extra_rules = load_substitutions_json(
                    cfgf.substitution_json_file
                )
            topo = None
            if cfgf.machine_config_file:
                from .search.machine_model import TPUTopology

                topo = TPUTopology.from_file(cfgf.machine_config_file)
                if topo.num_chips != cfgf.num_devices // fixed:
                    raise ValueError(
                        f"machine config {cfgf.machine_config_file!r} "
                        f"describes {topo.num_chips} chips but the "
                        f"search places over {cfgf.num_devices // fixed} "
                        "devices (num_devices / fixed pipe*expert*seq "
                        "degrees) — the cost model would rank against a "
                        "machine that doesn't exist"
                    )
            if cfgf.search_calibrate_chip:
                import dataclasses as _dc

                from .search.machine_model import (
                    TPUChip, TPUTopology, calibrate_chip,
                )

                topo = topo or TPUTopology(
                    chip=TPUChip.v5e(), num_chips=cfgf.num_devices // fixed
                )
                topo = _dc.replace(topo, chip=calibrate_chip(topo.chip))
                self._calibrated_chip = topo.chip
            graph2, strategy, report = unity.optimize(
                self.graph,
                cfgf.num_devices // fixed,
                topo,
                training=(comp_mode == TRAINING),
                budget=budget,
                alpha=cfgf.search_alpha,
                measured=cfgf.search_measured,
                measured_cache=cfgf.search_measured_cache,
                enable_sample=cfgf.enable_sample_parallel,
                enable_attribute=cfgf.enable_attribute_parallel,
                enable_parameter=cfgf.enable_parameter_parallel,
                # a user-fixed expert degree was already carved out of
                # the searched device count — don't enumerate it again
                allow_expert=cfgf.expert_parallelism_degree == 1,
                extra_rules=extra_rules,
            )
            self.graph = graph2
            self._search_report = report
        strategy.stamp(self.graph)
        self._strategy = strategy
        self._param_pspecs = strategy.weight_pspecs(self.graph)
        self._act_constraints = strategy.activation_constraints(self.graph)
        if strategy.machine.expert > 1:
            cfgf.expert_parallelism_degree = strategy.machine.expert
        cfgf.tensor_parallelism_degree = strategy.machine.model
        cfgf.data_parallelism_degree = (
            cfgf.num_devices
            // cfgf.tensor_parallelism_degree
            // cfgf.pipeline_parallelism_degree
            // cfgf.expert_parallelism_degree
            // cfgf.sequence_parallelism_degree
        )
        if cfgf.export_strategy_file:
            strategy.save(cfgf.export_strategy_file, graph=self.graph)
        if out_name is None:
            return None
        # follow rewrite aliases: a fused-away output (e.g. relu folded
        # into dense) resolves to the node its value was redirected to
        node, out_idx = self.graph.resolve_name(
            out_name, out_idx, start_gen=out_gen
        )
        if node is None:
            raise ValueError(
                f"output node {out_name!r} was rewritten away by the "
                "search with no redirect; name an op the substitutions "
                "keep so the output can be re-resolved after rewrites"
            )
        return TensorRef(node.id, out_idx)

    def _param_shardings(self):
        """PartitionSpec tree matching params, from per-op TP rules (or the
        parallelize pass's overrides)."""
        if self._param_pspecs is not None:
            return self._param_pspecs
        pspecs = {}
        for node in self.graph.nodes:
            if node.op_type == "input":
                continue
            op = get_op(node.op_type)
            in_specs = [self.graph.out_spec(r) for r in node.inputs]
            w = jax.eval_shape(
                lambda: op.init(jax.random.PRNGKey(0), in_specs, node.attrs_dict)
            )
            if w:
                pspecs[node.name] = op.weight_pspecs(
                    in_specs, node.attrs_dict, MODEL_AXIS
                )
        return pspecs

    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type: str = "sparse_categorical_crossentropy",
        metrics: Sequence[str] = ("accuracy",),
        comp_mode: str = TRAINING,
        output: Optional[Tensor] = None,
        auto_parallel: bool = False,
        _output_name: Optional[Tuple[str, int, int]] = None,
    ):
        """Lower the graph to jitted step functions (reference
        ``FFModel::compile``, model.cc:3314). With ``auto_parallel`` the
        Unity-style search (flexflow_tpu.search) picks mesh degrees +
        per-op shardings and may rewrite the graph; otherwise the
        config's explicit degrees apply (plus an import-strategy file,
        the reference's ``--import-strategy``)."""
        if self.config.quantization_type is not None or self.config.cpu_offload:
            # The reference too applies these only to serving
            # (file_loader.cc:651, SERVE.md offload docs). Raise rather
            # than silently training in bf16.
            raise NotImplementedError(
                "quantization/offload apply to the serving path: pass "
                "quantization=/offload= to serve.LLM.compile (training "
                "quantization is not supported, matching the reference)"
            )
        self.optimizer = optimizer or SGDOptimizer(lr=self.config.learning_rate)
        self.loss_type = loss_type
        self.metrics_names = tuple(metrics)
        if output is None and _output_name is not None:
            # recompile path: the Tensor handle is long stale — the
            # declared output survives by NAME (+ rewrite aliases from
            # its minting generation on: re-running the rewrite that
            # produced this coordinate would mis-redirect it).
            # Unresolvable = the alter() renamed it away: raising beats
            # silently reverting to the final node (a metric tap).
            o_name, o_idx, o_gen = _output_name
            node, idx = self.graph.resolve_name(o_name, o_idx, o_gen)
            if node is None:
                raise ValueError(
                    f"declared output {o_name!r} no longer resolves "
                    "after the graph was altered; keep the output op's "
                    "name stable across recompiles"
                )
            output = Tensor(self, TensorRef(node.id, idx))
        out_ref = output.ref if output is not None else None
        if auto_parallel or self.config.import_strategy_file:
            # rewrites re-number node ids; the search re-resolves the
            # output by NAME (mid-graph outputs / metric taps supported)
            out_ref = self._run_unity_search(output, comp_mode)
        self._compile_args = dict(
            optimizer=optimizer, loss_type=loss_type, metrics=metrics,
            comp_mode=comp_mode,
            # the output Tensor's node ref goes stale once a search (or
            # a recompile alter) rewrites the graph; recompiles pass the
            # NAME and re-resolve against the current graph instead
            output=None,
            # name + out_idx + the generation the coordinate is valid
            # from (it refers to the CURRENT, post-search graph)
            _output_name=(
                (
                    self.graph.nodes[out_ref.node_id].name,
                    out_ref.out_idx,
                    self.graph.alias_generation(),
                )
                if out_ref is not None
                else None
            ),
            auto_parallel=auto_parallel,
        )
        self.mesh = self._make_mesh()
        if self._param_pspecs is None and self.config.tensor_parallelism_degree > 1:
            from .parallel.tp import apply_tensor_parallel

            apply_tensor_parallel(self.graph, self.config.tensor_parallelism_degree)
        self._output_ref = out_ref if out_ref is not None else TensorRef(
            len(self.graph.nodes) - 1, 0
        )

        # The reference asserts CE losses consume a softmax op's output and
        # differentiates through probabilities; mirror that by detecting an
        # explicit softmax sink (loss_functions.cc:121-200).
        out_node = self.graph.nodes[self._output_ref.node_id]
        from_logits = out_node.op_type != "softmax"
        loss_fn = get_loss(loss_type, from_logits=from_logits)
        sparse = "sparse" in loss_type
        mesh = self.mesh

        param_pspecs = self._param_shardings()

        def to_sharding(tree_pspecs):
            return jax.tree.map(
                lambda p: NamedSharding(mesh, p),
                tree_pspecs,
                is_leaf=lambda x: isinstance(x, P),
            )

        # ---- initialise params/opt-state on device, sharded ----
        init_key = jax.random.PRNGKey(self.seed)
        with _set_mesh(mesh):
            params_shardings = to_sharding(param_pspecs)
            self.params = jax.jit(
                self.init_params, out_shardings=params_shardings
            )(init_key)
            self.model_state = self.init_state()
            self.opt_state = self.optimizer.init(self.params)

        data_sharding = NamedSharding(mesh, P(DATA_AXIS))
        repl = NamedSharding(mesh, P())
        opt = self.optimizer

        def train_step(params, opt_state, state, rng, inputs, labels):
            def lossf(p):
                preds, st_up = self.run_graph(
                    p,
                    inputs,
                    training=True,
                    rng=rng,
                    state=state,
                    upto=self._output_ref,
                )
                loss = loss_fn(preds, labels)
                # auxiliary losses collected by ops (MoE load-balance,
                # reference aggregate λ term)
                aux = st_up.pop("__aux__", None)
                if aux:
                    loss = loss + jnp.sum(jnp.stack(aux))
                return loss, (preds, st_up)

            (loss, (preds, st_up)), grads = jax.value_and_grad(
                lossf, has_aux=True
            )(params)
            new_params, new_opt = opt.update(grads, opt_state, params)
            new_state = dict(state)
            new_state.update(st_up)
            mvals = compute_metrics(
                self.metrics_names, preds, labels, sparse_labels=sparse,
                from_logits=from_logits,
            )
            return new_params, new_opt, new_state, loss, mvals

        def eval_step(params, state, inputs, labels):
            preds, _ = self.run_graph(
                params, inputs, training=False, state=state, upto=self._output_ref
            )
            loss = loss_fn(preds, labels)
            mvals = compute_metrics(
                self.metrics_names, preds, labels, sparse_labels=sparse,
                from_logits=from_logits,
            )
            return loss, mvals

        def fwd(params, state, inputs):
            preds, _ = self.run_graph(
                params, inputs, training=False, state=state, upto=self._output_ref
            )
            return preds

        self._train_step = jax.jit(train_step, donate_argnums=(0, 1, 2))
        self._eval_step = jax.jit(eval_step)
        self._fwd = jax.jit(fwd)
        self._data_sharding = data_sharding
        return self

    # ------------------------------------------------------------------
    # data feeding + loops

    def _input_names(self) -> List[str]:
        return [self.graph.nodes[i].name for i in self.input_nodes]

    def _shard_batch(self, arrays: Dict[str, np.ndarray]):
        out = {}
        for k, v in arrays.items():
            spec = P(DATA_AXIS) if np.ndim(v) >= 1 else P()
            out[k] = jax.device_put(v, NamedSharding(self.mesh, spec))
        return out

    def fit(
        self,
        x: Union[np.ndarray, Dict[str, np.ndarray], "Any"],
        y: Optional[np.ndarray] = None,
        batch_size: Optional[int] = None,
        epochs: Optional[int] = None,
        shuffle: bool = True,
        verbose: bool = True,
    ) -> PerfMetrics:
        """Training loop (reference ``FFModel.fit``, flexflow_cffi.py:3537).
        ``x`` may be a :class:`flexflow_tpu.data.SingleDataLoader` (the
        native prefetching feed) instead of arrays."""
        assert self._train_step is not None, "call compile() first"
        from .data import SingleDataLoader

        if isinstance(x, SingleDataLoader):
            # the loader owns batching/shuffling — conflicting args are
            # a caller error, not something to silently ignore
            assert y is None and batch_size is None, (
                "a SingleDataLoader carries its own labels, batch size "
                "and shuffle settings; don't pass y/batch_size with one"
            )
            loader = x
            steps = loader.batches_per_epoch
            name = self._input_names()[0]

            def epoch_batches(_epoch):
                for _ in range(steps):
                    xb, yb = loader.next_batch()
                    yield {name: xb}, yb

        else:
            assert y is not None, "fit(x, y) requires labels (or a loader)"
            bs = batch_size or self.config.batch_size
            names = self._input_names()
            if not isinstance(x, dict):
                x = {names[0]: x}
            n = len(y)
            steps = n // bs
            # seed with the step counter so repeated fit() calls (keras'
            # per-epoch loop, checkpoint resume) continue the shuffle
            # sequence instead of replaying the first permutation
            rng = np.random.default_rng(self.seed + self._step_count)

            def epoch_batches(_epoch):
                order = rng.permutation(n) if shuffle else np.arange(n)
                for s in range(steps):
                    idx = order[s * bs : (s + 1) * bs]
                    yield {k: v[idx] for k, v in x.items()}, y[idx]

        epochs = epochs or self.config.epochs
        perf = PerfMetrics()
        profiling = self.config.profiling
        if profiling:
            from .profiling import StepTimes

            self.step_times = StepTimes()
        for epoch in range(epochs):
            perf = PerfMetrics()
            for xb, yb in epoch_batches(epoch):
                # per-step mesh context: a recompile triggered by
                # recompile_on_condition may install a NEW mesh mid-epoch
                with _set_mesh(self.mesh):
                    batch = self._shard_batch(xb)
                    yb_dev = self._shard_batch({"y": yb})["y"]
                    step_rng = jax.random.PRNGKey(
                        self.seed * 1000003 + self._step_count
                    )
                    t0 = time.perf_counter() if profiling else 0.0
                    (
                        self.params,
                        self.opt_state,
                        self.model_state,
                        loss,
                        mvals,
                    ) = self._train_step(
                        self.params,
                        self.opt_state,
                        self.model_state,
                        step_rng,
                        batch,
                        yb_dev,
                    )
                    self._step_count += 1
                    perf.update(jax.device_get(loss), jax.device_get(mvals))
                self._maybe_recompile()
                if profiling:
                    # device_get above synced the step; wall time
                    # includes host feed — the number a user can act
                    # on (reference --profiling prints per-op times)
                    self.step_times.record(time.perf_counter() - t0)
            if verbose:
                msg = f"epoch {epoch}: {perf.report()}"
                if profiling:
                    msg += f" | {self.step_times.report()}"
                print(msg)
        return perf

    def evaluate(
        self,
        x: Union[np.ndarray, Dict[str, np.ndarray]],
        y: np.ndarray,
        batch_size: Optional[int] = None,
    ) -> Dict[str, float]:
        assert self._eval_step is not None, "call compile() first"
        bs = batch_size or self.config.batch_size
        names = self._input_names()
        if not isinstance(x, dict):
            x = {names[0]: x}
        n = len(y)
        perf = PerfMetrics()
        with _set_mesh(self.mesh):
            for s in range(n // bs):
                sl = slice(s * bs, (s + 1) * bs)
                batch = self._shard_batch({k: v[sl] for k, v in x.items()})
                yb = self._shard_batch({"y": y[sl]})["y"]
                loss, mvals = self._eval_step(
                    self.params, self.model_state, batch, yb
                )
                perf.update(jax.device_get(loss), jax.device_get(mvals))
        return perf.averages()

    def forward(self, inputs: Union[np.ndarray, Dict[str, Any]]):
        assert self._fwd is not None, "call compile() first"
        if not isinstance(inputs, dict):
            inputs = {self._input_names()[0]: inputs}
        with _set_mesh(self.mesh):
            return self._fwd(self.params, self.model_state, inputs)

    # ------------------------------------------------------------------
    # recompile-on-condition (reference RecompileState, recompile.h:26-41
    # + FFModel::recompile_on_condition, model.cc:2789 — the MoE example
    # uses it to rebalance experts mid-training)

    def recompile_on_condition(self, trigger, alter) -> None:
        """Register a per-step condition: when ``trigger(model)`` returns
        True, ``alter(model)`` may mutate the graph/config and the model
        recompiles in place. Parameters of unchanged layers (same name
        and shapes) carry over; new/resized layers re-initialize, and
        optimizer state resets (the reference rebuilds task launchers the
        same way)."""
        from .recompile import RecompileState

        self._recompile_state = RecompileState(trigger=trigger, alter=alter)

    def _maybe_recompile(self) -> bool:
        state = getattr(self, "_recompile_state", None)
        if state is None or not state.trigger(self):
            return False
        state.alter(self)
        old_params = self.params
        old_lr = (self.opt_state or {}).get("lr")
        assert self._compile_args is not None
        self.compile(**self._compile_args)
        if old_lr is not None and "lr" in self.opt_state:
            # a scheduler-set LR survives the recompile
            self.opt_state["lr"] = jax.device_put(
                old_lr, self.opt_state["lr"].sharding
            )
        # carry over parameters whose layer name + leaf shapes survived
        for name, leaves in (old_params or {}).items():
            if name not in self.params:
                continue
            try:
                new = self.params[name]
                if jax.tree.structure(new) == jax.tree.structure(leaves) and all(
                    a.shape == b.shape
                    for a, b in zip(jax.tree.leaves(new), jax.tree.leaves(leaves))
                ):
                    self.params[name] = jax.tree.map(
                        lambda old, cur: jax.device_put(old, cur.sharding),
                        leaves,
                        new,
                    )
            except Exception as e:
                import warnings

                warnings.warn(
                    f"recompile: layer {name!r} could not carry its "
                    f"weights over ({e}); it re-initialized", stacklevel=2,
                )
                continue
        state.recompilations += 1
        return True

    # ------------------------------------------------------------------
    # profiling (reference --profiling per-op timing + Legion Prof)

    def profile_ops(self, iters: int = 5) -> Dict[str, float]:
        """Per-op on-device forward times in ms (see profiling.profile_ops)."""
        from .profiling import profile_ops

        return profile_ops(self, iters=iters)

    def profile_trace(self, logdir: str):
        """jax.profiler capture context: ``with model.profile_trace(d): fit()``."""
        from .profiling import trace

        return trace(logdir)

    # ------------------------------------------------------------------
    # checkpoint / resume (orbax; beyond the reference — SURVEY.md §5
    # asks for async sharded checkpointing where the reference has only
    # host get_tensor/set_tensor)

    def _train_state(self) -> Dict[str, Any]:
        assert self.params is not None, "call compile() first"
        return {
            "params": self.params,
            "opt_state": self.opt_state,
            "model_state": self.model_state,
            "step": np.asarray(self._step_count, np.int64),
        }

    def save_checkpoint(self, directory: str, *, wait: bool = False) -> None:
        """Async-save params + optimizer state + model state + step."""
        from .checkpoint import save_train_state

        save_train_state(
            directory, self._step_count, self._train_state(), wait=wait
        )

    def restore_checkpoint(
        self, directory: str, step: Optional[int] = None
    ) -> None:
        """Restore into a compiled model (shardings come from the live
        state, so each process loads only its own shards)."""
        from .checkpoint import restore_train_state

        restored = restore_train_state(
            directory, self._train_state(), step=step
        )
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.model_state = restored["model_state"]
        self._step_count = int(restored["step"])

    # ------------------------------------------------------------------
    # weight access (reference ParallelTensorBase::get_tensor/set_tensor)

    def validate_search(self, iters: int = 5) -> Dict[str, float]:
        """Compare the Unity search's predicted step time against the
        real compiled step on the current devices (the closing of the
        simulator-fidelity loop the reference gets from re-measuring
        with ``inner_measure_operator_cost``). Returns predicted /
        measured seconds and their ratio."""
        assert self._train_step is not None, "call compile() first"
        assert self._search_report is not None, (
            "validate_search needs an auto_parallel compile"
        )
        bs = self.config.batch_size
        rng = np.random.default_rng(0)
        x = {}
        for i in self.input_nodes:
            node = self.graph.nodes[i]
            spec = node.out_specs[0]
            if "int" in str(spec.dtype):
                x[node.name] = rng.integers(
                    0, 8, size=spec.shape
                ).astype(np.int32)
            else:
                x[node.name] = rng.normal(size=spec.shape).astype(np.float32)
        out_id = self._output_ref.node_id if self._output_ref else -1
        out_shape = self.graph.nodes[out_id].out_specs[0].shape
        n_out = out_shape[-1]
        loss_type = (self._compile_args or {}).get(
            "loss_type", "sparse_categorical_crossentropy"
        )
        if loss_type.startswith("sparse"):
            # labels match the output's leading dims: (B,) for a
            # classifier head, (B, S) for a sequence model
            y = rng.integers(
                0, max(2, n_out), size=tuple(out_shape[:-1]) or (bs,)
            ).astype(np.int32)
        else:  # dense targets (categorical CE / MSE)
            y = rng.normal(size=tuple(out_shape)).astype(np.float32)
        import time as _time

        # snapshot: timing runs real (donated) optimizer steps on noise;
        # the trained state must survive this diagnostic untouched
        live = (self.params, self.opt_state, self.model_state)
        snap = jax.device_get(live)
        shardings = jax.tree.map(lambda a: a.sharding, live)
        try:
            with _set_mesh(self.mesh):
                batch = self._shard_batch(x)
                yb = self._shard_batch({"y": y})["y"]
                key = jax.random.PRNGKey(0)
                params, opt, st = live
                # warm
                params, opt, st, loss, _ = self._train_step(
                    params, opt, st, key, batch, yb
                )
                jax.block_until_ready(loss)
                t0 = _time.perf_counter()
                for _ in range(iters):
                    params, opt, st, loss, _ = self._train_step(
                        params, opt, st, key, batch, yb
                    )
                jax.block_until_ready(loss)
                measured = (_time.perf_counter() - t0) / iters
        finally:
            # the first warm step donated the live buffers — restore even
            # when the timing loop dies, or every later fit() hits
            # "Array has been deleted"
            with _set_mesh(self.mesh):
                self.params, self.opt_state, self.model_state = jax.tree.map(
                    jax.device_put, snap, shardings
                )
        predicted = float(self._search_report.best_cost)
        return {
            "predicted_s": predicted,
            "measured_s": measured,
            "ratio": predicted / max(measured, 1e-12),
        }

    def export_dot(self, path: str, strategy=None) -> None:
        """Write the (strategy-colored, when available) computation graph
        as graphviz dot — reference ``--export-strategy-computation-
        graph-file`` (config.h:173-175)."""
        strategy = strategy or getattr(self, "_strategy", None)
        text = (
            strategy.to_dot(self.graph)
            if strategy is not None
            else self.graph.to_dot()
        )
        with open(path, "w") as f:
            f.write(text)

    def set_learning_rate(self, lr: float) -> None:
        """Change the LR in place (device scalar in opt_state — no
        recompile; the reference's ``Optimizer::set_learning_rate``)."""
        assert self.opt_state is not None and "lr" in self.opt_state, (
            "call compile() first"
        )
        cur = self.opt_state["lr"]
        new = jnp.asarray(lr, jnp.float32)
        if isinstance(cur.sharding, NamedSharding):
            new = jax.device_put(new, cur.sharding)
        # else: before the first train step the scalar is still the
        # UNCOMMITTED device-0 array compile() made; committing the
        # replacement would pin it there and the next train_step fails
        # with mixed device sets (params already live on the mesh — the
        # LearningRateScheduler-before-first-epoch case). Leave it
        # uncommitted and let jit place it with everything else.
        self.opt_state["lr"] = new

    def get_weights(self, layer_name: str):
        return jax.device_get(self.params[layer_name])

    def set_weights(self, layer_name: str, weights: Dict[str, np.ndarray]):
        cur = self.params[layer_name]
        self.params[layer_name] = jax.tree.map(
            lambda c, w: jax.device_put(jnp.asarray(w, c.dtype), c.sharding),
            cur,
            dict(weights),
        )
