"""Unity-searched training benchmark — the BASELINE.md north-star #2 path.

Builds the flagship LLaMA-style LM through the graph IR (embedding →
fused decoder stack → lm head), lets ``compile(auto_parallel=True)``
run the Unity-style search, and times the resulting compiled step. With
the fused :class:`~flexflow_tpu.ops.fused_transformer
.TransformerDecoderStackOp` the searched strategy executes the same
scan + remat + flash-attention program as the hand-sharded
``models/llama.make_train_step`` — the search reaches the fast path
instead of the interpreted per-op graph (reference: the searched PCG is
lowered back to real operators via ``convert_graph_to_operators``,
src/runtime/graph.cc:2108 + model.cc:3347).
"""
from __future__ import annotations

from .core.mesh import set_mesh as _set_mesh

import time
from typing import Any, Dict, Optional


def build_searched_lm(
    *,
    vocab_size: int,
    hidden_size: int,
    intermediate_size: int,
    num_layers: int,
    num_heads: int,
    batch: int,
    seq: int,
    dtype,
    attention: str = "xla",
    remat_policy=None,
    config=None,
):
    """FFModel: tokens (B, S) → embed → fused decoder stack → logits."""
    from .config import FFConfig
    from .core.dtypes import DataType
    from .model import FFModel

    config = config or FFConfig(batch_size=batch, num_devices=1)
    ff = FFModel(config)
    dt = DataType.from_any(dtype)
    tokens = ff.create_tensor((batch, seq), dtype=DataType.INT32, name="tokens")
    x = ff.embedding(
        tokens, num_entries=vocab_size, out_dim=hidden_size, dtype=dt,
        name="embed",
    )
    x = ff.transformer_decoder_stack(
        x,
        num_layers=num_layers,
        num_heads=num_heads,
        intermediate_size=intermediate_size,
        attention=attention,
        remat_policy=remat_policy,
        name="decoder",
    )
    ff.dense(x, vocab_size, use_bias=False, name="lm_head")
    return ff


def searched_train_mfu(
    on_tpu: bool, iters: int = 10, attention_override: Optional[str] = None
) -> Dict[str, Any]:
    """Compile the flagship LM with auto_parallel=True, time the searched
    step, and return MFU + the search-fidelity ratio from
    ``validate_search`` (predicted/measured ∈ [0.5, 2] is the
    acceptance band)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .config import FFConfig
    from .models import llama
    from .optimizers import AdamOptimizer

    if on_tpu:
        V, D, F, L, H = 32000, 2048, 5504, 16, 16
        B, S = 8, 1024
        dt, attention = jnp.bfloat16, "flash"
        remat_policy = "dots"
    else:
        V, D, F, L, H = 256, 64, 128, 2, 4
        B, S = 2, 32
        dt, attention = jnp.float32, "xla"
        remat_policy = None
        iters = 2
    if attention_override is not None:
        attention = attention_override

    # On the chip, swap the preset efficiency guesses for measured ones
    # (machine_model.calibrate_chip) so validate_search judges the
    # calibrated model, not the guesses.
    cfg = FFConfig(
        batch_size=B, num_devices=1, search_budget=8,
        search_calibrate_chip=on_tpu,
    )
    ff = build_searched_lm(
        vocab_size=V, hidden_size=D, intermediate_size=F, num_layers=L,
        num_heads=H, batch=B, seq=S, dtype=dt, attention=attention,
        remat_policy=remat_policy, config=cfg,
    )
    ff.compile(
        optimizer=AdamOptimizer(lr=1e-4),
        loss_type="sparse_categorical_crossentropy",
        metrics=(),
        auto_parallel=True,
    )

    rng = np.random.default_rng(0)
    data = rng.integers(0, V, size=(B, S + 1)).astype(np.int32)
    inputs, labels = {"tokens": data[:, :-1][:, :S]}, data[:, 1 : S + 1]
    with _set_mesh(ff.mesh):
        batch = ff._shard_batch(inputs)
        yb = ff._shard_batch({"y": labels})["y"]
        key = jax.random.PRNGKey(0)
        params, opt, st = ff.params, ff.opt_state, ff.model_state
        params, opt, st, loss, _ = ff._train_step(
            params, opt, st, key, batch, yb
        )
        _ = float(loss)  # sync (compile + first step)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt, st, loss, _ = ff._train_step(
                params, opt, st, key, batch, yb
            )
        _ = float(loss)
        dt_s = (time.perf_counter() - t0) / iters
        ff.params, ff.opt_state, ff.model_state = params, opt, st

    lcfg = llama.LLaMAConfig(
        vocab_size=V, hidden_size=D, intermediate_size=F,
        num_hidden_layers=L, num_attention_heads=H, num_key_value_heads=H,
        max_position_embeddings=S,
    )
    flops = 3 * llama.flops_per_token(lcfg, S) * B * S
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak FLOP/s
    report = ff._search_report
    fidelity = ff.validate_search(iters=max(2, iters // 2))
    return {
        "mfu": flops / dt_s / peak,
        "step_ms": round(dt_s * 1e3, 2),
        "tokens_per_sec": round(B * S / dt_s, 1),
        "search_machine": f"dp{report.machine.data}xtp{report.machine.model}",
        "search_candidates": report.candidates_evaluated,
        # predicted/measured ∈ [0.5, 2] is the acceptance band ON TPU —
        # the prediction uses the TPU roofline, so a CPU run's ratio is
        # meaninglessly tiny (report the raw times alongside)
        "search_fidelity_ratio": round(fidelity["ratio"], 4),
        "search_predicted_ms": round(fidelity["predicted_s"] * 1e3, 3),
        "search_measured_ms": round(fidelity["measured_s"] * 1e3, 3),
        "attention": attention,
        **(
            {
                "calibrated_mxu_eff": round(chip.mxu_efficiency, 3),
                "calibrated_hbm_eff": round(chip.hbm_efficiency, 3),
            }
            if (chip := getattr(ff, "_calibrated_chip", None)) is not None
            else {}
        ),
    }
