"""CLI driver — ``python -m flexflow_tpu <cmd>``.

The reference ships C++ app drivers plus a ``flexflow_python``
interpreter launcher (reference ``inference/incr_decoding``,
``inference/spec_infer/spec_infer.cc:260``, ``python/flexflow/core/
flexflow_python``, flags parsed by ``FFConfig::parse_args``
model.cc:4049-4200). The TPU framework's equivalents:

  train        MLP training smoke (the mnist_mlp example)
  serve        incremental decoding or SpecInfer over an HF checkpoint
               directory (or a tiny random model when omitted)
  search       Unity auto-parallel compile + strategy/dot export
  serve-search offline ServingConfig search over the serving cost model
  spec-distill distill a draft from target logits + rank the draft
               ladder by measured accept-rate-per-draft-GFLOP
  bench        the headline benchmark (bench.py)

Reference-style degree flags are accepted with either one or two
leading dashes (-tensor-parallelism-degree / --tensor-parallelism-degree).
"""
from __future__ import annotations

import argparse
import os
import sys

if os.environ.get("JAX_PLATFORMS"):
    # The container sitecustomize (axon plugin) sets jax_platforms
    # PROGRAMMATICALLY, which overrides the env var — re-assert the
    # user's explicit choice so `JAX_PLATFORMS=cpu python -m
    # flexflow_tpu ...` behaves as documented (same fix as
    # tests/conftest.py and bench.py).
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _degree_args(p: argparse.ArgumentParser):
    for flag, dest in [
        ("tensor-parallelism-degree", "tp"),
        ("pipeline-parallelism-degree", "pp"),
        ("data-parallelism-degree", "dp"),
        ("expert-parallelism-degree", "ep"),
        ("sequence-parallelism-degree", "sp"),
    ]:
        p.add_argument(
            f"--{flag}", f"-{flag}", dest=dest, type=int, default=1
        )


def _load_repo_module(relpath: str, name: str):
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def cmd_train(args):
    mnist_mlp = _load_repo_module("examples/mnist_mlp.py", "mnist_mlp")
    mnist_mlp.main(num_devices=args.devices, epochs=args.epochs,
                   profiling=args.profiling)


def cmd_serve(args):
    import jax

    from .core.mesh import MachineSpec
    from .serve import GenerationConfig, ServingConfig, SpecConfig
    from .serve.llm import LLM, SSM

    n = args.tp * args.pp * args.ep * args.sp * max(1, args.dp)
    mesh = MachineSpec.from_degrees(
        n, tensor=args.tp, pipeline=args.pp, expert=args.ep,
        sequence=args.sp,
    ).make_mesh(jax.devices()[:n])
    if args.model_dir:
        llm = LLM.from_pretrained(args.model_dir, mesh=mesh)
    else:
        import jax.numpy as jnp

        from .models import llama

        cfg = llama.LLaMAConfig(
            vocab_size=512, hidden_size=128, intermediate_size=344,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, max_position_embeddings=512,
            dtype=jnp.float32,
        )
        llm = LLM(llama, cfg, mesh=mesh)
    sc = ServingConfig(
        max_requests_per_batch=args.max_requests_per_batch,
        max_sequence_length=args.max_sequence_length,
        kernels="pallas" if args.pallas else "xla",
        kv_layout=args.kv_layout,
        page_size=args.page_size,
        max_cached_tokens=args.max_cached_tokens,
        kv_quant=args.kv_quant,
        kv_shard=args.kv_shard,
        context_shards=args.context_shards,
        prefix_caching=args.prefix_caching,
        host_cache_bytes=args.host_cache_bytes,
        cache_policy=args.cache_policy,
        fused_decode=tuple(
            s for s in (args.fused_decode or "").split(",") if s
        ),
        quantized_allreduce=args.quantized_allreduce,
        replicas=args.replicas,
        router_policy=args.router_policy,
        prefill_replicas=args.prefill_replicas,
        decode_replicas=args.decode_replicas,
        slo_queue_delay_s=args.slo_queue_delay_s,
        migration_queue_budget=args.migration_queue_budget,
        replica_transport=args.replica_transport,
        replica_endpoints=tuple(
            s for s in (args.replica_endpoints or "").split(",") if s
        ),
        standby_replicas=args.standby_replicas,
        journal_dir=args.journal_dir,
        autoscale=args.autoscale,
        slo_ttft_s=args.slo_ttft_s,
        slo_tpot_s=args.slo_tpot_s,
        autoscale_cooldown_steps=args.autoscale_cooldown_steps,
        autoscale_min_replicas=args.autoscale_min_replicas,
        autoscale_max_replicas=(
            args.autoscale_max_replicas or args.replicas
        ),
    )
    ssms = []
    spec = None
    if args.ssm_dir or args.spec:
        if args.ssm_dir:
            ssms = [SSM.from_pretrained(args.ssm_dir, mesh=mesh)]
        else:  # layer-skip self-draft
            import dataclasses

            # round up to a multiple of pp so the draft stack also
            # shards over the pipe axis
            k = max(args.pp, llm.cfg.num_hidden_layers // 4)
            k = ((k + args.pp - 1) // args.pp) * args.pp
            dcfg = dataclasses.replace(llm.cfg, num_hidden_layers=k)
            dparams = dict(llm.params)
            dparams["layers"] = {
                nme: v[:k] for nme, v in llm.params["layers"].items()
            }
            ssms = [SSM(llm.family, dcfg, dparams, mesh=mesh)]
        spec = SpecConfig(beam_width=2, beam_depth=4)
    llm.compile(sc, ssms=ssms, spec=spec,
                quantization=args.quantization, offload=args.offload,
                output_file=args.output_file)
    if args.fault_plan:
        from .serve.cluster import ClusterManager, FaultPlan

        if not isinstance(llm.rm, ClusterManager):
            raise SystemExit(
                "--fault-plan requires a cluster (--replicas > 1 or "
                "disaggregated pools) — faults inject at the Replica "
                "surface"
            )
        llm.rm.attach_faults(FaultPlan.from_json(args.fault_plan))
    obs_buf = None
    recorder = None
    if args.trace_out or args.metrics_out or args.flight_recorder:
        # Observability (flexflow_tpu/obs): tracing + flight recorder
        # attach to whichever manager compile built (bare scheduler or
        # cluster); exports are written after the run below.
        from .obs import FlightRecorder, attach_observability

        if args.flight_recorder:
            recorder = FlightRecorder(out_dir=args.flight_recorder)
        obs_buf = attach_observability(llm.rm, recorder=recorder)
    prompts = args.prompt or [[3, 17, 91, 42, 7]]
    gen = GenerationConfig(num_beams=args.num_beams)
    outs = llm.generate(
        prompts,
        gen=gen if args.num_beams > 1 else None,
        max_new_tokens=args.max_new_tokens,
    )
    if obs_buf is not None:
        from .obs import write_chrome_trace, write_prometheus
        from .serve.cluster import ClusterManager

        if args.trace_out:
            doc = write_chrome_trace(args.trace_out, obs_buf)
            print(f"trace: {len(doc['traceEvents'])} events -> "
                  f"{args.trace_out} (load in ui.perfetto.dev)")
        if args.metrics_out:
            if isinstance(llm.rm, ClusterManager):
                sched = {str(r.index): r.rm.stats for r in llm.rm.replicas}
                cluster = llm.rm.stats
            else:
                sched = {"0": llm.rm.stats}
                cluster = None
            write_prometheus(
                args.metrics_out, scheduler=sched, cluster=cluster,
                profiles=[o.profile for o in outs],
            )
            print(f"metrics: prometheus snapshot -> {args.metrics_out}")
        if recorder is not None and recorder.paths:
            print(f"flight recorder: {len(recorder.paths)} dump(s) -> "
                  f"{args.flight_recorder}")
    for o in outs:
        p = o.profile
        print(o.output_text or o.output_tokens)
        print(
            f"  [steps={p.llm_decoding_steps} accepted={p.accepted_tokens} "
            f"latency={p.latency_s:.2f}s]"
        )


def cmd_search(args):
    import numpy as np

    import flexflow_tpu as ff

    cfg = ff.FFConfig(
        batch_size=8 * args.devices, num_devices=args.devices,
        search_budget=args.budget, search_measured=args.measured,
        export_strategy_file=args.export_strategy,
    )
    m = ff.FFModel(cfg)
    t = m.create_tensor((cfg.batch_size, 64), name="x")
    for _ in range(args.layers):
        t = m.dense(t, args.hidden, activation="relu")
    t = m.dense(t, 8)
    t = m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(lr=0.05), auto_parallel=True)
    print("strategy:", m._search_report.machine)
    print("predicted step:", f"{m._search_report.best_cost*1e3:.3f} ms")
    if args.export_dot:
        m.export_dot(args.export_dot)
        print("dot written to", args.export_dot)


def cmd_serve_search(args):
    from .serve.autotune import (
        ModelGeometry,
        TrafficProfile,
        search_serving_config,
    )

    if args.model_dir:
        import json
        import types

        with open(os.path.join(args.model_dir, "config.json")) as f:
            geom = ModelGeometry.from_model_config(
                types.SimpleNamespace(**json.load(f))
            )
    else:
        geom = ModelGeometry(
            hidden_size=args.hidden_size,
            num_layers=args.num_layers,
            num_heads=args.num_heads,
            num_kv_heads=args.num_kv_heads or args.num_heads,
            intermediate_size=args.intermediate_size,
            vocab_size=args.vocab_size,
            param_bytes=args.param_bytes,
        )
    traffic = TrafficProfile(
        arrival_rate_rps=args.arrival_rate_rps,
        prompt_len_p50=args.prompt_p50,
        prompt_len_p99=args.prompt_p99 or 4 * args.prompt_p50,
        output_len_p50=args.output_p50,
        output_len_p99=args.output_p99 or 4 * args.output_p50,
        prefix_share=args.prefix_share,
        spec_accept_rate=args.spec_accept_rate,
    )
    best, report = search_serving_config(
        geom, traffic,
        chip_budget=args.chip_budget,
        slo_ttft_s=args.slo_ttft_s,
        slo_tpot_s=args.slo_tpot_s,
        max_requests_per_batch=args.max_requests_per_batch,
        max_sequence_length=args.max_sequence_length,
        allow_disagg=not args.no_disagg,
        top_k=args.top_k,
    )
    print(report.summary())
    if best is None:
        raise SystemExit(2)
    for cand, pred in report.table:
        print(
            f"  tp={cand.tp} pp={cand.pp} replicas={cand.replicas} "
            f"page={cand.page_size} kv={cand.kv_quant or 'fp'} "
            f"spec={'on' if cand.speculation else 'off'} "
            f"disagg={cand.prefill_replicas}p/{cand.decode_replicas}d "
            f"-> {pred.tokens_per_s:.0f} tok/s "
            f"ttft_p99={pred.ttft_s_p99 * 1e3:.1f}ms "
            f"tpot_p99={pred.tpot_s_p99 * 1e3:.2f}ms "
            f"{'feasible' if pred.feasible else pred.reason}"
        )
    sc = best.to_serving_config()
    sc.validate_cluster()
    flags = [
        "--kv-layout paged",
        f"--page-size {sc.page_size}",
        f"--max-requests-per-batch {sc.max_requests_per_batch}",
        f"--max-sequence-length {sc.max_sequence_length}",
        f"--replicas {sc.replicas}",
        f"--tensor-parallelism-degree {best.tp}",
        f"--pipeline-parallelism-degree {best.pp}",
    ]
    if sc.kv_quant:
        flags.append(f"--kv-quant {sc.kv_quant}")
    if sc.prefill_replicas:
        flags += [f"--prefill-replicas {sc.prefill_replicas}",
                  f"--decode-replicas {sc.decode_replicas}"]
    if "whole_step" in sc.fused_decode:
        flags.append("--fused-decode whole_step --pallas")
    if sc.quantized_allreduce:
        flags.append(f"--quantized-allreduce {sc.quantized_allreduce}")
    if best.speculation:
        flags.append("--spec")
    print("serve with: python -m flexflow_tpu serve " + " ".join(flags))


def cmd_spec_distill(args):
    import dataclasses
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    from .serve import (
        InferenceEngine,
        ServingConfig,
        SpecConfig,
        SpecInferManager,
    )
    from .serve import spec_distill as sd

    if args.model_dir:
        from .serve.llm import LLM

        llm = LLM.from_pretrained(args.model_dir)
        family, cfg, params = llm.family, llm.cfg, llm.params
    else:
        from .models import llama as family

        cfg = family.LLaMAConfig(
            vocab_size=512, hidden_size=128, intermediate_size=344,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, max_position_embeddings=512,
            dtype=jnp.float32,
        )
        params = family.init_params(jax.random.PRNGKey(0), cfg)

    def make_sc():
        return ServingConfig(
            max_requests_per_batch=4,
            max_sequence_length=args.max_sequence_length,
            max_spec_tree_tokens=16,
            cache_dtype=cfg.dtype,
        )

    k = max(1, cfg.num_hidden_layers // 4)
    dcfg = dataclasses.replace(cfg, num_hidden_layers=k)
    dparams = dict(params)
    dparams["layers"] = {n: v[:k] for n, v in params["layers"].items()}

    def make_mgr(draft_cfg=None, draft_params=None, spec=None):
        eng = InferenceEngine(family, cfg, params, make_sc())
        ssms = []
        if draft_cfg is not None:
            ssms = [InferenceEngine(family, draft_cfg, draft_params,
                                    make_sc())]
        return SpecInferManager(
            eng, ssms, spec or SpecConfig(2, 4, adaptive=True)
        )

    rng = np.random.RandomState(args.seed)
    prompts = [
        rng.randint(1, cfg.vocab_size, size=rng.randint(4, 12)).tolist()
        for _ in range(args.num_prompts)
    ]

    # 1. harvest teacher logits: offline trace replay, or live from the
    #    layer-skip manager's verify rounds
    if args.trace_file:
        with open(args.trace_file) as f:
            traces = json.load(f)
        buf = sd.harvest_offline(family, cfg, params, traces)
        print(f"harvested {len(buf)} examples from "
              f"{len(traces)} offline trace(s)")
    else:
        buf = sd.harvest_online(
            make_mgr(dcfg, dparams), prompts,
            max_new_tokens=args.max_new_tokens,
        )
        print(f"harvested {len(buf)} examples from live verify rounds")

    # 2. distill the student
    distill = sd.DistillConfig(
        hidden_size=args.hidden, num_layers=args.layers,
        num_heads=args.heads, seq_len=args.seq_len,
        batch_size=args.batch_size, steps=args.steps, lr=args.lr,
        temperature=args.temperature, seed=args.seed,
    )
    scfg, sparams, hist = sd.train_distilled_draft(
        buf, cfg, distill, family=family
    )
    print(f"distilled {distill.num_layers}L/{distill.hidden_size}h draft: "
          f"loss {hist[0]:.4f} -> {hist[-1]:.4f} over {len(hist)} steps")

    # 3. rank the draft ladder by measured accept-rate-per-draft-GFLOP
    evals = [
        sd.measure_draft_utility(
            make_mgr(scfg, sparams), prompts,
            max_new_tokens=args.max_new_tokens, name="distilled",
        ),
        sd.measure_draft_utility(
            make_mgr(dcfg, dparams), prompts,
            max_new_tokens=args.max_new_tokens, name="layer_skip",
        ),
        sd.measure_draft_utility(
            make_mgr(spec=SpecConfig(2, 4, adaptive=True,
                                     draft="early_exit", draft_layers=k)),
            prompts, max_new_tokens=args.max_new_tokens, name="early_exit",
        ),
    ]
    print(f"{'draft':<12} {'accept':>8} {'GF/tok':>10} {'accept/GF':>12}")
    for e in sd.rank_drafts(evals):
        print(f"{e.name:<12} {e.accept_rate:>8.3f} "
              f"{e.draft_gflops_per_token:>10.6f} "
              f"{e.accept_rate_per_gflop:>12.2f}")
    best = sd.rank_drafts(evals)[0]
    print(f"best draft: {best.name} "
          f"(feed measured_accept_rate={best.accept_rate:.3f} to the "
          f"serving cost model)")

    if args.out:
        sd.save_distilled_draft(args.out, scfg, sparams)
        print(f"distilled draft checkpoint -> {args.out} "
              f"(load as an SSM spec)")


def cmd_bench(args):
    _load_repo_module("bench.py", "bench").main()


def main(argv=None):
    p = argparse.ArgumentParser(prog="flexflow_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="MLP training smoke run")
    t.add_argument("--devices", type=int, default=1)
    t.add_argument("--epochs", type=int, default=2)
    t.add_argument("--profiling", action="store_true")
    t.set_defaults(fn=cmd_train)

    s = sub.add_parser("serve", help="incremental / speculative serving")
    s.add_argument("--model-dir", default=None)
    s.add_argument("--ssm-dir", default=None)
    s.add_argument("--spec", action="store_true",
                   help="SpecInfer with a layer-skip self-draft")
    s.add_argument("--prompt", action="append", default=None)
    s.add_argument("--max-new-tokens", type=int, default=32)
    s.add_argument("--max-requests-per-batch", type=int, default=4)
    s.add_argument("--max-sequence-length", type=int, default=512)
    s.add_argument("--num-beams", type=int, default=1)
    s.add_argument("--quantization", choices=["int8", "int4"], default=None)
    s.add_argument("--offload", action="store_true")
    s.add_argument("--pallas", action="store_true")
    s.add_argument("--kv-layout", choices=["dense", "paged"], default="dense",
                   help="paged = block-paged KV cache (HBM scales with "
                        "live tokens; enables high request concurrency)")
    s.add_argument("--page-size", type=int, default=128)
    s.add_argument("--max-cached-tokens", type=int, default=None,
                   help="paged KV pool budget in tokens (default: worst "
                        "case slots*max_len; smaller oversubscribes with "
                        "recompute preemption)")
    s.add_argument("--kv-quant", choices=["int8", "int4"], default=None,
                   help="quantized paged KV pages (requires "
                        "--kv-layout paged): int8 codes, or int4 "
                        "packed nibbles (two codes per byte along the "
                        "head dim, unpacked in-kernel), plus per-page "
                        "amax scales dequantized inside attention; the "
                        "--max-cached-tokens HBM budget then buys ~2x "
                        "(int8) / ~4x (int4) the pages — ≥1.9x / ≥3.8x "
                        "after scale rows. int4 generation stays "
                        "bitwise run-to-run; its logit tolerance is "
                        "wider than int8's (see README)")
    s.add_argument("--kv-shard", choices=["none", "context"],
                   default="none",
                   help="context-parallel long-context serving "
                        "(requires --kv-layout paged): shard ONE "
                        "request's KV pages across sequence shards — "
                        "logical page j stripes to shard j%%n, "
                        "--max-cached-tokens becomes a PER-SHARD HBM "
                        "budget, and prompts beyond one shard's pool "
                        "serve at the aggregate capacity via ring "
                        "ragged paged attention "
                        "(--sequence-parallelism-degree > 1 runs the "
                        "ppermute ring; a seq-degree-1 mesh uses the "
                        "bitwise table-gather layout)")
    s.add_argument("--context-shards", type=int, default=0,
                   help="context-parallel shard degree (0 = derive "
                        "from the mesh --sequence-parallelism-degree; "
                        "must match it when both are set)")
    s.add_argument("--prefix-caching", action="store_true",
                   help="automatic prefix caching (paged layout only): "
                        "reuse cached KV pages for shared prompt "
                        "prefixes, prefilling only the uncached suffix")
    s.add_argument("--host-cache-bytes", type=int, default=None,
                   help="hierarchical KV cache: spill cold prefix-"
                        "cache pages to host RAM (async DMA) instead "
                        "of evicting, up to this many bytes, and "
                        "re-admit them on a later prompt match — a "
                        "host hit instead of a prefill recompute "
                        "(requires --prefix-caching; re-admitted pages "
                        "generate bitwise the warm path)")
    s.add_argument("--cache-policy", choices=["complete", "prefill"],
                   default="complete",
                   help="when prompt blocks enter the prefix cache: at "
                        "request completion incl. generated tokens "
                        "(complete) or as soon as prefill ends (prefill)")
    s.add_argument("--fused-decode", default=None,
                   help="megakernel decode-step fusions, comma-separated "
                        "(rope_kv_write,sampling,whole_step): fold RoPE "
                        "+ the KV page write into the ragged paged "
                        "Pallas kernel (requires --kv-layout paged; "
                        "active with --pallas), the greedy/top-k "
                        "sampling epilogue into the step program, "
                        "and/or run the WHOLE decode step as one "
                        "persistent layer-walking Pallas program "
                        "(paged layouts); each fusion is "
                        "bitwise-identical to the unfused step")
    s.add_argument("--quantized-allreduce", default=None,
                   choices=["exact", "int8"],
                   help="whole_step TP decode collectives "
                        "(serve/collectives.py, EQuARX-style): 'exact' "
                        "= lax.psum (bitwise the GSPMD reduction), "
                        "'int8' = quantized codes + per-block scales "
                        "(~1/4 the reduce bytes, documented tolerance)")
    s.add_argument("--replicas", type=int, default=1,
                   help="cluster serving (serve/cluster/): drive this "
                        "many engine replicas — each its own mesh and "
                        "KV pool — behind the front-end router")
    s.add_argument("--router-policy",
                   choices=["prefix", "round_robin", "least_loaded"],
                   default="prefix",
                   help="replica placement: longest prefix-cache match "
                        "(prefix, the default — falls back to least-"
                        "loaded on a miss), round_robin, or the "
                        "smallest queue-delay estimate (least_loaded)")
    s.add_argument("--prefill-replicas", type=int, default=0,
                   help="disaggregated serving: the first N replicas "
                        "only prefill — finished prefills migrate "
                        "their KV pages to a decode-pool replica "
                        "(byte-exact; requires --kv-layout paged; "
                        "must pair with --decode-replicas and sum to "
                        "--replicas)")
    s.add_argument("--decode-replicas", type=int, default=0,
                   help="disaggregated serving: the last N replicas "
                        "only decode (see --prefill-replicas)")
    s.add_argument("--slo-queue-delay-s", type=float, default=None,
                   help="SLO admission: shed a request (terminal "
                        "GenerationResult.error, never a hang) when "
                        "every replica's queue-delay estimate exceeds "
                        "this many seconds")
    s.add_argument("--autoscale", choices=["drive", "advise"],
                   default=None,
                   help="self-driving serving (serve/autotune): a cost-"
                        "model policy loop in the cluster drive loop — "
                        "'drive' applies journaled scale_out/scale_in/"
                        "retune decisions, 'advise' journals + counts "
                        "every decision without applying (dry run); "
                        "requires --slo-ttft-s and/or --slo-tpot-s and "
                        "an --autoscale-max-replicas ceiling")
    s.add_argument("--slo-ttft-s", type=float, default=None,
                   help="autoscale objective: predicted time-to-first-"
                        "token p99 SLO in seconds (admission wait on "
                        "the routed pool + the prefill pass)")
    s.add_argument("--slo-tpot-s", type=float, default=None,
                   help="autoscale objective: predicted time-per-output-"
                        "token p99 SLO in seconds (the decode-step "
                        "interval)")
    s.add_argument("--autoscale-cooldown-steps", type=int, default=64,
                   help="minimum CLUSTER STEPS between applied "
                        "autoscale actions (hysteresis floor; never "
                        "wall clock, so replays reproduce decisions)")
    s.add_argument("--autoscale-min-replicas", type=int, default=1,
                   help="floor of the replica band the autoscaler may "
                        "move within")
    s.add_argument("--autoscale-max-replicas", type=int, default=0,
                   help="ceiling of the replica band (required >= the "
                        "floor when --autoscale is set — an unbounded "
                        "scale_out is a cost bug)")
    s.add_argument("--migration-queue-budget", type=int, default=None,
                   help="disaggregated back-pressure: at most this many "
                        "finished prefills wait for decode-pool "
                        "capacity holding their slot + pages; overflow "
                        "entries release their pages and drain through "
                        "recompute re-admission on the decode pool's "
                        "own queue (default: unbounded holds)")
    s.add_argument("--fault-plan", default=None,
                   help="deterministic fault injection "
                        "(serve/cluster/faults.py; requires a cluster): "
                        "a JSON list of faults, e.g. "
                        "'[{\"kind\": \"crash\", \"replica\": 1, "
                        "\"step\": 20}]' — replica kinds: crash, "
                        "transient, latency, migration, oom; transport "
                        "kinds (remote replicas only — rejected loudly "
                        "against --replica-transport inproc): drop, "
                        "delay, disconnect, partition. The same plan "
                        "replays the same failure scenario bit-for-bit; "
                        "failed replicas' requests fail over to "
                        "survivors via recompute re-admission")
    s.add_argument("--replica-transport", default="inproc",
                   choices=("inproc", "loopback", "socket"),
                   help="how the cluster drives its replicas: direct "
                        "method calls (inproc, default), the binary "
                        "RPC wire codec in-process (loopback — bitwise "
                        "the inproc cluster, exercises deadlines/"
                        "retries/heartbeats for real), or localhost TCP "
                        "to subprocess replica servers (socket; see "
                        "python -m flexflow_tpu.serve.cluster.server)")
    s.add_argument("--replica-endpoints", default=None,
                   help="comma-separated host:port per remote replica "
                        "(then per standby) for --replica-transport "
                        "socket")
    s.add_argument("--journal-dir", default=None, metavar="DIR",
                   help="elastic control plane: write the durable "
                        "request journal (submissions, flushed-token "
                        "deltas, terminal records, membership "
                        "snapshots) into DIR — a SIGKILL'd serve "
                        "process restarts with ClusterManager.recover "
                        "and finishes every journaled request bitwise "
                        "(forces the cluster manager even at "
                        "--replicas 1)")
    s.add_argument("--standby-replicas", type=int, default=0,
                   help="warm standbys: pre-built engines outside "
                        "routing that ADOPT a circuit-broken replica's "
                        "position — its prefix radix tree (block keys + "
                        "page bytes) ships over the transport and "
                        "re-admits on the standby before it joins "
                        "routing, instead of survivors re-seeding the "
                        "families cold")
    # reference -output-file (request_manager.cc:417-440): append each
    # finished request's latency/steps/token-ids
    s.add_argument("--output-file", "-output-file", default=None)
    s.add_argument("--trace-out", default=None,
                   help="write a Chrome/Perfetto trace_event JSON of the "
                        "run (one lane per replica; load in "
                        "ui.perfetto.dev)")
    s.add_argument("--metrics-out", default=None,
                   help="write a Prometheus text-format metrics snapshot "
                        "(SchedulerStats/ClusterStats/ProfileInfo, "
                        "drift-guarded)")
    s.add_argument("--flight-recorder", default=None, metavar="DIR",
                   help="arm the failure flight recorder: bounded "
                        "per-replica event rings dumping redacted JSON "
                        "post-mortems into DIR on DOWN trips, failover "
                        "errors and terminal request errors")
    _degree_args(s)
    s.set_defaults(fn=cmd_serve)

    q = sub.add_parser("search", help="Unity auto-parallel compile")
    q.add_argument("--devices", type=int, default=4)
    q.add_argument("--layers", type=int, default=3)
    q.add_argument("--hidden", type=int, default=256)
    q.add_argument("--budget", type=int, default=32)
    q.add_argument("--measured", action="store_true")
    q.add_argument("--export-strategy", default=None)
    q.add_argument("--export-dot", default=None)
    q.set_defaults(fn=cmd_search)

    ss = sub.add_parser(
        "serve-search",
        help="offline ServingConfig search over the serving cost model",
        description="serve/autotune offline search: enumerate + refine "
                    "serving candidates (TPxPP, replicas, page size, KV "
                    "quant, disagg, speculation) through the analytical "
                    "cost model for a model geometry and traffic "
                    "profile, under a chip budget and optional TTFT/"
                    "TPOT p99 SLO constraints; prints the leaderboard "
                    "and the validated `serve` flags for the winner.")
    ss.add_argument("--model-dir", default=None,
                    help="derive geometry from DIR/config.json instead "
                         "of the --hidden-size/... flags")
    ss.add_argument("--hidden-size", type=int, default=128)
    ss.add_argument("--num-layers", type=int, default=4)
    ss.add_argument("--num-heads", type=int, default=8)
    ss.add_argument("--num-kv-heads", type=int, default=0,
                    help="0 = same as --num-heads (no GQA)")
    ss.add_argument("--intermediate-size", type=int, default=344)
    ss.add_argument("--vocab-size", type=int, default=512)
    ss.add_argument("--param-bytes", type=float, default=2.0,
                    help="bytes per weight (2=bf16, 1=int8, 0.5=int4)")
    ss.add_argument("--arrival-rate-rps", type=float, default=1.0)
    ss.add_argument("--prompt-p50", type=float, default=128.0)
    ss.add_argument("--prompt-p99", type=float, default=0.0,
                    help="0 = 4x the p50")
    ss.add_argument("--output-p50", type=float, default=128.0)
    ss.add_argument("--output-p99", type=float, default=0.0,
                    help="0 = 4x the p50")
    ss.add_argument("--prefix-share", type=float, default=0.0,
                    help="fraction of prompt tokens expected to hit the "
                         "prefix cache")
    ss.add_argument("--spec-accept-rate", type=float, default=0.0,
                    help="expected speculative acceptance rate (0 "
                         "disables speculation candidates)")
    ss.add_argument("--chip-budget", type=int, default=8,
                    help="max chips = tp * pp * replicas")
    ss.add_argument("--slo-ttft-s", type=float, default=None,
                    help="TTFT p99 SLO constraint in seconds (breaching "
                         "candidates are infeasible, not down-weighted)")
    ss.add_argument("--slo-tpot-s", type=float, default=None,
                    help="TPOT p99 SLO constraint in seconds")
    ss.add_argument("--max-requests-per-batch", type=int, default=16)
    ss.add_argument("--max-sequence-length", type=int, default=2048)
    ss.add_argument("--no-disagg", action="store_true",
                    help="exclude disaggregated prefill/decode pools")
    ss.add_argument("--top-k", type=int, default=8,
                    help="leaderboard rows to print")
    ss.set_defaults(fn=cmd_serve_search)

    sdp = sub.add_parser(
        "spec-distill",
        help="distill a draft from target logits; rank distilled vs "
             "layer-skip vs early-exit by accept-rate-per-draft-GFLOP",
    )
    sdp.add_argument("--model-dir", default=None,
                     help="teacher HF checkpoint dir (default: tiny "
                          "random model)")
    sdp.add_argument("--trace-file", default=None,
                     help="JSON list of token-id lists to replay offline "
                          "(default: harvest live verify rounds)")
    sdp.add_argument("--out", default=None,
                     help="save the distilled draft checkpoint here")
    sdp.add_argument("--hidden", type=int, default=64)
    sdp.add_argument("--layers", type=int, default=2)
    sdp.add_argument("--heads", type=int, default=4)
    sdp.add_argument("--steps", type=int, default=200)
    sdp.add_argument("--lr", type=float, default=1e-3)
    sdp.add_argument(
        "--temperature", type=float, default=0.25,
        help="distillation temperature: softmax(teacher_logits / T) "
        "targets; < 1 sharpens toward the teacher argmax (what a "
        "greedy verify ladder accepts on)",
    )
    sdp.add_argument("--seq-len", type=int, default=64)
    sdp.add_argument("--batch-size", type=int, default=8)
    sdp.add_argument("--num-prompts", type=int, default=16)
    sdp.add_argument("--max-new-tokens", type=int, default=24)
    sdp.add_argument("--max-sequence-length", type=int, default=256)
    sdp.add_argument("--seed", type=int, default=0)
    sdp.set_defaults(fn=cmd_spec_distill)

    b = sub.add_parser("bench", help="headline benchmark (one JSON line)")
    b.set_defaults(fn=cmd_bench)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
