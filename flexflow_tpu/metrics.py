"""Training metrics.

TPU-native equivalent of the reference Metrics op (reference
``src/metrics_functions/metrics_functions.cc``, ``include/flexflow/
metrics_functions.h:44-88``): per-shard metrics computed on device and
folded into a ``PerfMetrics`` running aggregate. Here metrics are computed
inside the jitted step (GSPMD reduces across data shards automatically)
and aggregated on host with :class:`PerfMetrics`.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

ACCURACY = "accuracy"
CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
MEAN_SQUARED_ERROR = "mean_squared_error"
MEAN_ABSOLUTE_ERROR = "mean_absolute_error"


def compute_metrics(
    metric_names: Sequence[str],
    preds,
    labels,
    *,
    sparse_labels: bool,
    from_logits: bool = True,
) -> Dict[str, jnp.ndarray]:
    """Returns dict of scalar metric values for one batch (device-side)."""
    out = {}
    pf = preds.astype(jnp.float32)
    for m in metric_names:
        if m == ACCURACY:
            if sparse_labels:
                hit = jnp.argmax(pf, axis=-1).astype(jnp.int32) == labels.reshape(
                    pf.shape[:-1]
                ).astype(jnp.int32)
            else:
                hit = jnp.argmax(pf, axis=-1) == jnp.argmax(labels, axis=-1)
            out[m] = hit.mean()
        elif m in (CATEGORICAL_CROSSENTROPY,):
            lp = jnp.log(jnp.clip(pf, 1e-12, 1.0))
            out[m] = -(labels.astype(jnp.float32) * lp).sum(-1).mean()
        elif m == SPARSE_CATEGORICAL_CROSSENTROPY:
            if from_logits:
                lp = jax.nn.log_softmax(pf, axis=-1)
            else:
                lp = jnp.log(jnp.clip(pf, 1e-12, 1.0))
            lbl = labels.reshape(pf.shape[:-1]).astype(jnp.int32)
            out[m] = -jnp.take_along_axis(lp, lbl[..., None], -1).mean()
        elif m == MEAN_SQUARED_ERROR:
            d = pf - labels.astype(jnp.float32)
            out[m] = (d * d).mean()
        elif m == MEAN_ABSOLUTE_ERROR:
            out[m] = jnp.abs(pf - labels.astype(jnp.float32)).mean()
        else:
            raise ValueError(f"unknown metric {m!r}")
    return out


@dataclasses.dataclass
class PerfMetrics:
    """Host-side running aggregate — reference ``PerfMetrics`` future chain
    (``FFModel::update_metrics_task``, reference ``model.cc:3911``)."""

    iterations: int = 0
    totals: Dict[str, float] = dataclasses.field(default_factory=dict)
    loss_total: float = 0.0

    def update(self, loss: float, batch_metrics: Dict[str, float]):
        self.iterations += 1
        self.loss_total += float(loss)
        for k, v in batch_metrics.items():
            self.totals[k] = self.totals.get(k, 0.0) + float(v)

    def averages(self) -> Dict[str, float]:
        if self.iterations == 0:
            return {}
        out = {k: v / self.iterations for k, v in self.totals.items()}
        out["loss"] = self.loss_total / self.iterations
        return out

    def report(self) -> str:
        avg = self.averages()
        parts = [f"{k}={v:.6f}" for k, v in sorted(avg.items())]
        return f"[{self.iterations} iters] " + " ".join(parts)
