"""Training metrics.

TPU-native equivalent of the reference Metrics op (reference
``src/metrics_functions/metrics_functions.cc``, ``include/flexflow/
metrics_functions.h:44-88``): per-shard metrics computed on device and
folded into a ``PerfMetrics`` running aggregate. Here metrics are computed
inside the jitted step (GSPMD reduces across data shards automatically)
and aggregated on host with :class:`PerfMetrics`.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

ACCURACY = "accuracy"
CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
MEAN_SQUARED_ERROR = "mean_squared_error"
MEAN_ABSOLUTE_ERROR = "mean_absolute_error"

#: decode_step_ms reservoir bound (SchedulerStats.note_decode_step_ms)
_DECODE_MS_CAP = 4096


def compute_metrics(
    metric_names: Sequence[str],
    preds,
    labels,
    *,
    sparse_labels: bool,
    from_logits: bool = True,
) -> Dict[str, jnp.ndarray]:
    """Returns dict of scalar metric values for one batch (device-side)."""
    out = {}
    pf = preds.astype(jnp.float32)
    for m in metric_names:
        if m == ACCURACY:
            if sparse_labels:
                hit = jnp.argmax(pf, axis=-1).astype(jnp.int32) == labels.reshape(
                    pf.shape[:-1]
                ).astype(jnp.int32)
            else:
                hit = jnp.argmax(pf, axis=-1) == jnp.argmax(labels, axis=-1)
            out[m] = hit.mean()
        elif m in (CATEGORICAL_CROSSENTROPY,):
            lp = jnp.log(jnp.clip(pf, 1e-12, 1.0))
            out[m] = -(labels.astype(jnp.float32) * lp).sum(-1).mean()
        elif m == SPARSE_CATEGORICAL_CROSSENTROPY:
            if from_logits:
                lp = jax.nn.log_softmax(pf, axis=-1)
            else:
                lp = jnp.log(jnp.clip(pf, 1e-12, 1.0))
            lbl = labels.reshape(pf.shape[:-1]).astype(jnp.int32)
            out[m] = -jnp.take_along_axis(lp, lbl[..., None], -1).mean()
        elif m == MEAN_SQUARED_ERROR:
            d = pf - labels.astype(jnp.float32)
            out[m] = (d * d).mean()
        elif m == MEAN_ABSOLUTE_ERROR:
            out[m] = jnp.abs(pf - labels.astype(jnp.float32)).mean()
        else:
            raise ValueError(f"unknown metric {m!r}")
    return out


@dataclasses.dataclass
class SchedulerStats:
    """Host-side continuous-batching telemetry aggregated per scheduler
    step (the serving analog of :class:`PerfMetrics`): slot occupancy,
    prefill token-budget fill, pipeline behavior (drains = full flushes,
    the expensive sync points continuous batching exists to avoid), and
    request lifecycle counters. The RequestManager updates it on every
    dispatch/flush; the bench and ``FF_LOG=serve=debug`` read it."""

    steps: int = 0
    mixed_steps: int = 0          # pipelined mixed prefill+decode steps
    decode_steps: int = 0         # pipelined pure-decode steps
    sync_steps: int = 0           # blocking host-round-trip steps
    flushes: int = 0              # in-flight entries drained to host
    pipeline_drains: int = 0      # full _flush_all with work in flight
    admitted: int = 0
    preemptions: int = 0
    failed: int = 0
    prefill_tokens: int = 0       # chunk tokens dispatched
    decode_tokens: int = 0        # decode tokens dispatched
    occupancy_sum: float = 0.0    # active slots / total, summed per step
    budget_fill_sum: float = 0.0  # prefill tokens / budget, per mixed step
    # Automatic prefix caching (serve/prefix_cache.py): admissions that
    # reused cached KV pages vs cold admissions, tokens whose prefill
    # the cache skipped, pages published to / evicted from the radix
    # tree, and copy-on-write page copies for partially-matched tails.
    prefix_hits: int = 0
    prefix_misses: int = 0
    prefix_hit_tokens: int = 0
    prefix_inserts: int = 0
    prefix_evictions: int = 0
    prefix_cows: int = 0
    # Hierarchical KV cache host tier (serve/prefix_cache.py spill,
    # ServingConfig.host_cache_bytes): pages spilled device→host
    # instead of evicted, pages re-admitted host→device on a later
    # match, prompt tokens whose prefill a host hit skipped (the
    # recompute the tier saved — also mirrored per-request into
    # ProfileInfo.host_hit_tokens), and the host tier's current byte
    # occupancy (a gauge, not a counter).
    spills: int = 0
    readmits: int = 0
    host_hit_tokens: int = 0
    host_bytes: int = 0
    # SpecInfer adaptive speculation (serve/specinfer.py): per-request
    # verify rounds run, tree tokens DRAFTED by the SSM/early-exit
    # draft, drafted tokens the verifier accepted (root/bonus tokens in
    # neither — see ProfileInfo.speculated_tokens), and W×D ladder
    # moves the acceptance-driven controllers made. FF_LOG=serve=debug
    # reports them alongside the scheduler counters.
    spec_rounds: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_resizes: int = 0
    # Acceptance-weighted verify-skip (SpecConfig.verify_skip):
    # request-rounds that skipped the speculate+verify dispatches and
    # rode the incremental decode path (cold draft), and the periodic
    # smallest-rung re-probe rounds that re-measured the draft.
    verify_skipped_rounds: int = 0
    spec_reprobes: int = 0
    # Context-parallel long-context serving (ServingConfig.kv_shard=
    # "context", serve/paging.py + serve/kernels.py): the shard degree
    # one request's KV pages stripe over (0 = CP off), ring hops a
    # sequence-sharded mesh pays per dispatched attention step
    # ((shards-1) per step — the ppermute stat rotations of ring
    # ragged paged attention), and the pool's striping balance gauge
    # (min/max used pages across shards; 1.0 = perfectly balanced).
    cp_shards: int = 0
    ring_steps: int = 0
    shard_balance: float = 1.0
    # Whole-step decode telemetry (ROADMAP 5b: decode_step_ms is THE
    # metric the megakernel trajectory tracks): wall-clock samples of
    # the scheduler's decode-step engine call — on the pipelined path
    # this is the host-side dispatch cost (the device runs up to
    # dispatch_ahead steps ahead, so it is NOT device latency; no
    # device sync is ever added for the measurement — FF107/FF108);
    # on the blocking sync path it is the full step wall time. A
    # bounded reservoir (newest _DECODE_MS_CAP samples kept) so steady
    # traffic cannot grow host memory; snapshot() derives p50/p99 by
    # nearest-rank.
    decode_step_ms_samples: List[float] = dataclasses.field(
        default_factory=list
    )
    # Retrace sentinel (analysis/retrace.py, wired when the engine runs
    # with ServingConfig.sanitizers=("retrace",)): XLA compiles of step
    # programs observed at the engine's jit chokepoint, and how many of
    # them were RE-compiles of an already-compiled step key — the
    # steady-state perf hazard. Healthy serving: compiles settles after
    # warmup and retraces stays 0.
    compiles: int = 0
    retraces: int = 0
    # Whole-step megakernel VMEM gate (serve/engine._whole_step_vmem_
    # gate): times the gate fell back to the per-layer path because a
    # step shape's working set exceeded the budget at EVERY legal
    # sub-block tiling (counter — healthy serving keeps it 0: over-
    # budget layers get a tile count, not a fallback), and the gate's
    # priced decode working-set estimate in bytes (gauge). Mirrored
    # from the engine at the scheduler's stats chokepoint, like
    # cp_shards/shard_balance.
    whole_step_fallbacks: int = 0
    whole_step_vmem_est: int = 0

    def record_step(
        self,
        kind: str,                # "mixed" | "decode" | "sync"
        *,
        active_slots: int,
        num_slots: int,
        prefill_tokens: int = 0,
        decode_tokens: int = 0,
        budget: int = 0,
    ) -> None:
        self.steps += 1
        if kind == "mixed":
            self.mixed_steps += 1
            if budget > 0:
                self.budget_fill_sum += prefill_tokens / budget
        elif kind == "decode":
            self.decode_steps += 1
        else:
            self.sync_steps += 1
        self.prefill_tokens += int(prefill_tokens)
        self.decode_tokens += int(decode_tokens)
        if num_slots > 0:
            self.occupancy_sum += active_slots / num_slots

    def note_decode_step_ms(self, ms: float) -> None:
        """Record one decode-step wall sample (bounded reservoir)."""
        s = self.decode_step_ms_samples
        s.append(float(ms))
        if len(s) > _DECODE_MS_CAP:
            del s[: len(s) - _DECODE_MS_CAP]

    def _decode_ms_pct(self, q: float) -> float:
        s = self.decode_step_ms_samples
        if not s:
            return 0.0
        ordered = sorted(s)
        idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
        return ordered[idx]

    @property
    def decode_step_ms_p50(self) -> float:
        return self._decode_ms_pct(0.50)

    @property
    def decode_step_ms_p99(self) -> float:
        return self._decode_ms_pct(0.99)

    @property
    def mean_occupancy(self) -> float:
        return self.occupancy_sum / self.steps if self.steps else 0.0

    @property
    def mean_budget_fill(self) -> float:
        return (
            self.budget_fill_sum / self.mixed_steps if self.mixed_steps else 0.0
        )

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admissions that reused at least one cached page."""
        n = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / n if n else 0.0

    @property
    def host_hit_rate(self) -> float:
        """Fraction of prefix-cache hit tokens served from the HOST
        tier (re-admitted spilled pages) rather than live HBM pages —
        how much of the cache's value survived memory pressure thanks
        to spilling instead of eviction."""
        if not self.prefix_hit_tokens:
            return 0.0
        return self.host_hit_tokens / self.prefix_hit_tokens

    @property
    def spec_accept_rate(self) -> float:
        """Drafted-accept rate: drafted tokens the verifier accepted
        over drafted tokens — the honest speculation-efficiency figure
        (free root/bonus tokens in neither side)."""
        if not self.spec_drafted:
            return 0.0
        return self.spec_accepted / self.spec_drafted

    def snapshot(self) -> Dict[str, float]:
        return {
            "steps": self.steps,
            "mixed_steps": self.mixed_steps,
            "decode_steps": self.decode_steps,
            "sync_steps": self.sync_steps,
            "flushes": self.flushes,
            "pipeline_drains": self.pipeline_drains,
            "admitted": self.admitted,
            "preemptions": self.preemptions,
            "failed": self.failed,
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "mean_occupancy": round(self.mean_occupancy, 4),
            "mean_budget_fill": round(self.mean_budget_fill, 4),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_hit_rate": round(self.prefix_hit_rate, 4),
            "prefix_inserts": self.prefix_inserts,
            "prefix_evictions": self.prefix_evictions,
            "prefix_cows": self.prefix_cows,
            "spills": self.spills,
            "readmits": self.readmits,
            "host_hit_tokens": self.host_hit_tokens,
            "host_hit_rate": round(self.host_hit_rate, 4),
            "host_bytes": self.host_bytes,
            "spec_rounds": self.spec_rounds,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_resizes": self.spec_resizes,
            "verify_skipped_rounds": self.verify_skipped_rounds,
            "spec_reprobes": self.spec_reprobes,
            "spec_accept_rate": round(self.spec_accept_rate, 4),
            "cp_shards": self.cp_shards,
            "ring_steps": self.ring_steps,
            "shard_balance": round(self.shard_balance, 4),
            "decode_step_ms_p50": round(self.decode_step_ms_p50, 3),
            "decode_step_ms_p99": round(self.decode_step_ms_p99, 3),
            "compiles": self.compiles,
            "retraces": self.retraces,
            "whole_step_fallbacks": self.whole_step_fallbacks,
            "whole_step_vmem_est": self.whole_step_vmem_est,
        }

    def report(self) -> str:
        s = self.snapshot()
        return (
            f"[serve {s['steps']} steps] "
            f"mixed={s['mixed_steps']} decode={s['decode_steps']} "
            f"sync={s['sync_steps']} drains={s['pipeline_drains']} "
            f"occ={s['mean_occupancy']:.2f} fill={s['mean_budget_fill']:.2f} "
            f"prefill_toks={s['prefill_tokens']} "
            f"decode_toks={s['decode_tokens']} adm={s['admitted']} "
            f"preempt={s['preemptions']} failed={s['failed']} "
            f"pfx_hit={s['prefix_hits']}/{s['prefix_hits'] + s['prefix_misses']}"
            f" pfx_toks={s['prefix_hit_tokens']} "
            f"pfx_evict={s['prefix_evictions']} pfx_cow={s['prefix_cows']} "
            f"spill={s['spills']} readmit={s['readmits']} "
            f"host_toks={s['host_hit_tokens']} host_B={s['host_bytes']} "
            f"spec={s['spec_accepted']}/{s['spec_drafted']}"
            f"@{s['spec_rounds']}r resize={s['spec_resizes']} "
            f"vskip={s['verify_skipped_rounds']} "
            f"reprobe={s['spec_reprobes']} "
            f"cp={s['cp_shards']} ring={s['ring_steps']} "
            f"bal={s['shard_balance']:.2f} "
            f"dstep_ms={s['decode_step_ms_p50']:.2f}/"
            f"{s['decode_step_ms_p99']:.2f} "
            f"compiles={s['compiles']} retraces={s['retraces']} "
            f"ws_fallback={s['whole_step_fallbacks']}"
        )


@dataclasses.dataclass
class ClusterStats:
    """Cluster-level serving telemetry (serve/cluster/): the front-end
    router's own counters plus an aggregation hook over every replica's
    :class:`SchedulerStats`. The ClusterManager updates the router
    counters at placement/shed/migration time and passes the per-replica
    stats as CALLABLES (the same indirection SchedulerStats uses for the
    prefix cache and retrace guard), so bench-style stat swaps
    (``rm.stats = SchedulerStats()``) keep counting."""

    submitted: int = 0
    # placements by HOW the router decided: "prefix" (longest radix-tree
    # match), "affinity" (session stickiness), "round_robin",
    # "least_loaded" (policy or prefix-miss fallback)
    placements: Dict[str, int] = dataclasses.field(default_factory=dict)
    affinity_hits: int = 0
    sheds: int = 0                 # SLO admission rejections (ERROR, not hangs)
    migrations: int = 0            # prefill→decode page hand-offs
    migrated_pages: int = 0
    migrated_bytes: int = 0
    # Fault tolerance (serve/cluster/health.py + manager failover):
    # step exceptions observed, replica state transitions (DOWN trips /
    # half-open probes / closed circuits), requests re-admitted off a
    # dead replica through recompute, total re-admission attempts
    # (failovers + migration-drain recomputes), and requests that ended
    # in a terminal error because retries exhausted or no healthy
    # replica remained (the bounded alternative to a hang).
    step_faults: int = 0
    replica_down: int = 0
    replica_suspect: int = 0
    probes: int = 0
    replica_recoveries: int = 0
    failovers: int = 0
    retries: int = 0
    failover_errors: int = 0
    # Migration back-pressure (ServingConfig.migration_queue_budget):
    # failed migrate attempts (exceptions, retried with backoff), the
    # bounded queue's current depth (gauge) and high-water mark, and
    # held prefills that overflowed the budget and drained through
    # recompute re-admission instead of parking with their pages.
    migration_failures: int = 0
    migration_queue_depth: int = 0
    migration_queue_peak: int = 0
    migration_queue_overflows: int = 0
    # Replica RPC transport (serve/cluster/transport.py + remote.py):
    # RPCs that exhausted their retries (each one is also a health
    # observation), retry attempts the deadline/backoff machinery
    # spent (absorbed losses — no health impact), cluster steps on
    # which a remote replica had had no successful exchange for
    # heartbeat_gap_steps (each one a SUSPECT observation), transport
    # reconnects after a disconnect, standby replicas that adopted a
    # DOWN replica's routing position (+ prefix families), and raw
    # frame bytes both ways (requests+responses; migrated page bytes
    # and shipped radix trees dominate).
    rpc_errors: int = 0
    rpc_retries: int = 0
    heartbeat_gaps: int = 0
    reconnects: int = 0
    standby_adoptions: int = 0
    wire_bytes_sent: int = 0
    wire_bytes_received: int = 0
    # Concurrent cluster stepping (serve/cluster/manager.py): the
    # high-water mark of RPCs in flight inside one step's fan-out
    # (gauge — 0 under the serial reference loop), plus bounded
    # reservoirs of whole-cluster-step wall time and per-replica
    # step-RPC round-trip time in milliseconds. The raw sample lists
    # stay out of Prometheus; the derived ``cluster_step_ms_p50/p99``
    # and ``rpc_rtt_ms_p50/p99`` properties export as gauges, and
    # per-replica RTT percentiles ride the snapshot under
    # ``rpc_rtt_ms_per_replica``.
    rpc_inflight_peak: int = 0
    cluster_step_ms_samples: List[float] = dataclasses.field(
        default_factory=list
    )
    rpc_rtt_ms_samples: Dict[int, List[float]] = dataclasses.field(
        default_factory=dict
    )
    # Elastic control plane (serve/cluster/{journal,reconfigure}.py):
    # committed reconfigurations by kind (replicas added live, replicas
    # drained + retired, prefill/decode pool flips), journal traffic
    # (records + raw frame bytes appended; compactions that rewrote the
    # log to the live set), manager restarts recovered from the journal,
    # and unfinished requests a recovery re-admitted through recompute.
    scale_outs: int = 0
    scale_ins: int = 0
    pool_flips: int = 0
    journal_records: int = 0
    journal_bytes: int = 0
    journal_compactions: int = 0
    manager_recoveries: int = 0
    journal_replayed: int = 0
    # Self-driving serving (serve/autotune): policy decisions taken
    # (applied or advisory), speculation-bucket retunes advised, and
    # the predicted-vs-measured throughput gauges the autoscaler
    # refreshes every evaluation (tokens/sec — how far off the cost
    # model is on this box). Per-replica arrival/completion counters
    # and the bounded admission-time queue-delay reservoir feed the
    # TrafficEstimator; the dict fields stay out of Prometheus (the
    # derived ``queue_delay_s_p50/p99`` and the per-replica snapshot
    # maps ride along instead).
    autoscale_decisions: int = 0
    retunes: int = 0
    autoscale_predicted_tps: float = 0.0
    autoscale_measured_tps: float = 0.0
    arrivals_per_replica: Dict[int, int] = dataclasses.field(
        default_factory=dict
    )
    completions_per_replica: Dict[int, int] = dataclasses.field(
        default_factory=dict
    )
    queue_delay_s_samples: List[float] = dataclasses.field(
        default_factory=list
    )

    def record_placement(self, how: str) -> None:
        self.placements[how] = self.placements.get(how, 0) + 1
        if how == "affinity":
            self.affinity_hits += 1

    def note_cluster_step_ms(self, ms: float) -> None:
        """Record one whole-cluster-step wall sample (bounded
        reservoir, same trim discipline as decode_step_ms)."""
        s = self.cluster_step_ms_samples
        s.append(float(ms))
        if len(s) > _DECODE_MS_CAP:
            del s[: len(s) - _DECODE_MS_CAP]

    def note_arrival(self, replica: int) -> None:
        """Count one first-time placement onto ``replica`` (failover
        re-admissions are NOT arrivals — the request already counted)."""
        r = int(replica)
        self.arrivals_per_replica[r] = self.arrivals_per_replica.get(r, 0) + 1

    def note_completion(self, replica: int) -> None:
        """Count one successfully finished request against the replica
        that first homed it (profile.replica_id — stable across
        failovers, so arrivals and completions reconcile per home)."""
        r = int(replica)
        self.completions_per_replica[r] = (
            self.completions_per_replica.get(r, 0) + 1
        )

    def note_queue_delay_s(self, delay_s: float) -> None:
        """Record one admission-time queue-delay estimate (bounded
        reservoir). Pre-envelope/cold-replica placements report 0.0 —
        a real sample ("no estimated wait"), kept, not dropped: the
        percentiles must reflect what admission actually saw."""
        s = self.queue_delay_s_samples
        s.append(max(0.0, float(delay_s)))
        if len(s) > _DECODE_MS_CAP:
            del s[: len(s) - _DECODE_MS_CAP]

    def note_rpc_rtt_ms(self, replica: int, ms: float) -> None:
        """Record one step-RPC round-trip sample for ``replica``
        (bounded per-replica reservoir)."""
        s = self.rpc_rtt_ms_samples.setdefault(int(replica), [])
        s.append(float(ms))
        if len(s) > _DECODE_MS_CAP:
            del s[: len(s) - _DECODE_MS_CAP]

    @staticmethod
    def _pct(samples: Sequence[float], q: float) -> float:
        if not samples:
            return 0.0
        ordered = sorted(samples)
        idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
        return ordered[idx]

    @property
    def cluster_step_ms_p50(self) -> float:
        return self._pct(self.cluster_step_ms_samples, 0.50)

    @property
    def cluster_step_ms_p99(self) -> float:
        return self._pct(self.cluster_step_ms_samples, 0.99)

    @property
    def queue_delay_s_p50(self) -> float:
        return self._pct(self.queue_delay_s_samples, 0.50)

    @property
    def queue_delay_s_p99(self) -> float:
        return self._pct(self.queue_delay_s_samples, 0.99)

    def arrivals_completions_per_replica(self) -> Dict[int, Dict[str, int]]:
        """Per-replica arrival/completion reconciliation map — the
        difference is the replica's live (or lost-to-error) load."""
        out: Dict[int, Dict[str, int]] = {}
        for idx in sorted(
            set(self.arrivals_per_replica) | set(self.completions_per_replica)
        ):
            out[idx] = {
                "arrivals": self.arrivals_per_replica.get(idx, 0),
                "completions": self.completions_per_replica.get(idx, 0),
            }
        return out

    def _all_rtt(self) -> List[float]:
        return [
            ms for s in self.rpc_rtt_ms_samples.values() for ms in s
        ]

    @property
    def rpc_rtt_ms_p50(self) -> float:
        return self._pct(self._all_rtt(), 0.50)

    @property
    def rpc_rtt_ms_p99(self) -> float:
        return self._pct(self._all_rtt(), 0.99)

    def rpc_rtt_ms_per_replica(self) -> Dict[int, Dict[str, float]]:
        """Per-replica RTT p50/p99 over the bounded reservoirs."""
        return {
            idx: {
                "p50": round(self._pct(s, 0.50), 3),
                "p99": round(self._pct(s, 0.99), 3),
            }
            for idx, s in sorted(self.rpc_rtt_ms_samples.items())
        }

    def snapshot(
        self, replicas: Sequence["SchedulerStats"] = ()
    ) -> Dict[str, object]:
        """Router counters + the SUM over every replica's scheduler
        counters (numeric fields only; per-replica snapshots ride along
        under ``per_replica`` so nothing is averaged away)."""
        per = [r.snapshot() for r in replicas]
        agg: Dict[str, float] = {}
        for snap in per:
            for k, v in snap.items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        # rates do not sum — recompute them over the summed counters
        if per:
            hits = agg.get("prefix_hits", 0)
            misses = agg.get("prefix_misses", 0)
            agg["prefix_hit_rate"] = round(
                hits / (hits + misses), 4
            ) if hits + misses else 0.0
            hit_toks = agg.get("prefix_hit_tokens", 0)
            agg["host_hit_rate"] = round(
                agg.get("host_hit_tokens", 0) / hit_toks, 4
            ) if hit_toks else 0.0
            drafted = agg.get("spec_drafted", 0)
            agg["spec_accept_rate"] = round(
                agg.get("spec_accepted", 0) / drafted, 4
            ) if drafted else 0.0
            # remote replicas mirror their stats from heartbeats — a
            # snapshot taken before the first envelope is empty
            agg["mean_occupancy"] = round(
                sum(s.get("mean_occupancy", 0.0) for s in per) / len(per), 4
            )
            agg["mean_budget_fill"] = round(
                sum(s.get("mean_budget_fill", 0.0) for s in per) / len(per),
                4,
            )
            # percentiles do not sum either — report the replica mean
            for k in ("decode_step_ms_p50", "decode_step_ms_p99"):
                agg[k] = round(
                    sum(s.get(k, 0.0) for s in per) / len(per), 3
                )
        return {
            "submitted": self.submitted,
            "placements": dict(self.placements),
            "affinity_hits": self.affinity_hits,
            "sheds": self.sheds,
            "migrations": self.migrations,
            "migrated_pages": self.migrated_pages,
            "migrated_bytes": self.migrated_bytes,
            "step_faults": self.step_faults,
            "replica_down": self.replica_down,
            "replica_suspect": self.replica_suspect,
            "probes": self.probes,
            "replica_recoveries": self.replica_recoveries,
            "failovers": self.failovers,
            "retries": self.retries,
            "failover_errors": self.failover_errors,
            "migration_failures": self.migration_failures,
            "migration_queue_depth": self.migration_queue_depth,
            "migration_queue_peak": self.migration_queue_peak,
            "migration_queue_overflows": self.migration_queue_overflows,
            "rpc_errors": self.rpc_errors,
            "rpc_retries": self.rpc_retries,
            "heartbeat_gaps": self.heartbeat_gaps,
            "reconnects": self.reconnects,
            "standby_adoptions": self.standby_adoptions,
            "wire_bytes_sent": self.wire_bytes_sent,
            "wire_bytes_received": self.wire_bytes_received,
            "rpc_inflight_peak": self.rpc_inflight_peak,
            "cluster_step_ms_p50": round(self.cluster_step_ms_p50, 3),
            "cluster_step_ms_p99": round(self.cluster_step_ms_p99, 3),
            "rpc_rtt_ms_p50": round(self.rpc_rtt_ms_p50, 3),
            "rpc_rtt_ms_p99": round(self.rpc_rtt_ms_p99, 3),
            "rpc_rtt_ms_per_replica": self.rpc_rtt_ms_per_replica(),
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "pool_flips": self.pool_flips,
            "journal_records": self.journal_records,
            "journal_bytes": self.journal_bytes,
            "journal_compactions": self.journal_compactions,
            "manager_recoveries": self.manager_recoveries,
            "journal_replayed": self.journal_replayed,
            "autoscale_decisions": self.autoscale_decisions,
            "retunes": self.retunes,
            "autoscale_predicted_tps": self.autoscale_predicted_tps,
            "autoscale_measured_tps": self.autoscale_measured_tps,
            "queue_delay_s_p50": round(self.queue_delay_s_p50, 6),
            "queue_delay_s_p99": round(self.queue_delay_s_p99, 6),
            "arrivals_completions_per_replica": (
                self.arrivals_completions_per_replica()
            ),
            "replicas": agg,
            "per_replica": per,
        }

    def report(self, replicas: Sequence["SchedulerStats"] = ()) -> str:
        s = self.snapshot(replicas)
        place = " ".join(
            f"{k}={v}" for k, v in sorted(s["placements"].items())
        ) or "none"
        agg = s["replicas"]
        return (
            f"[cluster {len(replicas)} replicas] sub={s['submitted']} "
            f"place[{place}] affinity={s['affinity_hits']} "
            f"shed={s['sheds']} migr={s['migrations']} "
            f"migrB={s['migrated_bytes']} "
            f"faults={s['step_faults']} down={s['replica_down']} "
            f"failover={s['failovers']} migq={s['migration_queue_depth']} "
            f"rpc_err={s['rpc_errors']} rpc_retry={s['rpc_retries']} "
            f"hb_gaps={s['heartbeat_gaps']} reconn={s['reconnects']} "
            f"inflight^={s['rpc_inflight_peak']} "
            f"cstep_ms={s['cluster_step_ms_p50']:.2f}/"
            f"{s['cluster_step_ms_p99']:.2f} "
            f"rtt_ms={s['rpc_rtt_ms_p50']:.2f}/{s['rpc_rtt_ms_p99']:.2f} "
            f"standby={s['standby_adoptions']} "
            f"scale+{s['scale_outs']}/-{s['scale_ins']} "
            f"flip={s['pool_flips']} jrnl={s['journal_records']}r/"
            f"{s['journal_bytes']}B recov={s['manager_recoveries']} "
            f"autoscale={s['autoscale_decisions']}d/{s['retunes']}rt "
            f"qdelay_s={s['queue_delay_s_p50']:.3f}/"
            f"{s['queue_delay_s_p99']:.3f} "
            f"wireB={s['wire_bytes_sent']}/{s['wire_bytes_received']} "
            f"pfx_hit_rate={agg.get('prefix_hit_rate', 0.0)} "
            f"adm={agg.get('admitted', 0)} "
            f"preempt={agg.get('preemptions', 0)} "
            f"retraces={agg.get('retraces', 0)}"
        )


@dataclasses.dataclass
class PerfMetrics:
    """Host-side running aggregate — reference ``PerfMetrics`` future chain
    (``FFModel::update_metrics_task``, reference ``model.cc:3911``)."""

    iterations: int = 0
    totals: Dict[str, float] = dataclasses.field(default_factory=dict)
    loss_total: float = 0.0

    def update(self, loss: float, batch_metrics: Dict[str, float]):
        self.iterations += 1
        self.loss_total += float(loss)
        for k, v in batch_metrics.items():
            self.totals[k] = self.totals.get(k, 0.0) + float(v)

    def averages(self) -> Dict[str, float]:
        if self.iterations == 0:
            return {}
        out = {k: v / self.iterations for k, v in self.totals.items()}
        out["loss"] = self.loss_total / self.iterations
        return out

    def report(self) -> str:
        avg = self.averages()
        parts = [f"{k}={v:.6f}" for k, v in sorted(avg.items())]
        return f"[{self.iterations} iters] " + " ".join(parts)
