"""Mesh-degree planner for stacked-decoder (LLaMA-style) training.

The graph-level Unity search (unity.py) assigns per-op sharding states
over a (data, model[, expert]) grid; pipeline and sequence degrees live
at a different altitude — they restructure the *program* (GPipe
schedule, ring attention), not one op. This planner covers that axis:
it enumerates every (dp, tp, pp, sp) factorization of the device count
for a decoder config and scores it with the scaling-book cost model —
MXU compute, Megatron all-reduces per layer, GPipe bubble + stage
hand-offs, ring-attention K/V rotation, DP gradient all-reduce — under
an HBM-fit constraint (params + optimizer moments + rematerialized
activations). The winner plugs straight into
``llama.make_train_step``'s MachineSpec.

The reference explores its analogous dims inside one search because
Legion tasks make pipelining just another placement; under XLA the
split mirrors how the programs are actually built (reference fixes
inference PP outside the search too, inference_manager.cc:91).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..core.mesh import MachineSpec
from .machine_model import TPUChip, TPUTopology
from .unity import _divisors


@dataclasses.dataclass
class PlanReport:
    spec: MachineSpec
    step_time_s: float
    breakdown: Dict[str, float]
    feasible: bool
    hbm_bytes: float
    candidates: int


def plan_decoder_mesh(
    num_devices: int,
    *,
    num_layers: int,
    hidden: int,
    intermediate: int,
    vocab: int,
    num_heads: int,
    num_kv_heads: Optional[int] = None,
    batch: int,
    seq: int,
    topo: Optional[TPUTopology] = None,
    dtype_bytes: int = 2,
    optimizer_bytes_per_param: int = 12,  # bf16 param + f32 grad+m+v (Adam)
    max_microbatches: int = 32,
) -> PlanReport:
    """Pick (dp, tp, pp, sp) for a decoder train step. Returns the best
    feasible plan (or the least-infeasible one, flagged)."""
    topo = topo or TPUTopology(chip=TPUChip.v5e(), num_chips=num_devices)
    chip = topo.chip
    kv = num_kv_heads or num_heads
    head_dim = hidden // num_heads

    # per-layer parameter count and per-token matmul flops
    layer_params = (
        hidden * num_heads * head_dim        # wq
        + 2 * hidden * kv * head_dim         # wk, wv
        + num_heads * head_dim * hidden      # wo
        + 3 * hidden * intermediate          # w1, w2, w3
    )
    total_params = num_layers * layer_params + 2 * vocab * hidden
    flops_per_token_layer = 2 * layer_params + 4 * hidden * seq  # + attn
    tokens = batch * seq

    ici = chip.ici_bandwidth
    eff_flops = chip.bf16_flops * chip.mxu_efficiency

    best: Optional[PlanReport] = None
    best_any: Optional[PlanReport] = None
    n_cand = 0
    for tp in _divisors(num_devices):
        if num_heads % tp or kv % tp:
            continue
        for pp in _divisors(num_devices // tp):
            if num_layers % pp:
                continue
            for sp in _divisors(num_devices // (tp * pp)):
                dp = num_devices // (tp * pp * sp)
                if batch % dp or (sp > 1 and seq % sp):
                    continue
                if sp > 1 and pp > 1:
                    # make_train_step doesn't compose ring attention
                    # with the GPipe path yet — don't plan what the
                    # executor can't run
                    continue
                n_cand += 1
                mb = max(pp, min(max_microbatches, batch // dp))
                # --- compute (divides over every axis) ---
                t_comp = (
                    3.0 * flops_per_token_layer * num_layers * tokens
                    / num_devices / eff_flops
                )
                # --- Megatron TP all-reduces: ~4/layer (fwd+bwd) ---
                act = batch * seq * hidden * dtype_bytes / (dp * sp)
                t_tp = 0.0
                if tp > 1:
                    ar = 2.0 * act * (tp - 1) / tp / ici
                    t_tp = 4.0 * (num_layers / pp) * ar
                # --- GPipe bubble + stage hand-offs ---
                t_pp = 0.0
                if pp > 1:
                    t_pp = (t_comp + t_tp) * (pp - 1) / mb
                    t_pp += 2.0 * (pp - 1) * (act / mb) / ici
                # --- ring-attention K/V rotation ---
                t_sp = 0.0
                if sp > 1:
                    kv_bytes = (
                        2 * batch * seq * kv * head_dim * dtype_bytes
                        / (dp * sp)
                    )
                    t_sp = (
                        3.0 * (num_layers / pp) * kv_bytes * (sp - 1) / sp / ici
                    )
                # --- DP gradient all-reduce ---
                t_dp = 0.0
                if dp > 1:
                    grad = total_params * dtype_bytes / (tp * pp)
                    t_dp = 2.0 * grad * (dp - 1) / dp / ici
                t = t_comp + t_tp + t_pp + t_sp + t_dp

                # --- HBM fit: params + optimizer + remat activations ---
                hbm = (
                    total_params * optimizer_bytes_per_param / (tp * pp)
                    + 2.0 * batch * seq * hidden * dtype_bytes
                    * (num_layers / pp) / (dp * sp)
                )
                feasible = hbm <= 0.9 * chip.hbm_capacity
                rep = PlanReport(
                    spec=MachineSpec(data=dp, pipe=pp, seq=sp, model=tp),
                    step_time_s=t,
                    breakdown={
                        "compute": t_comp, "tp_comm": t_tp,
                        "pp_bubble": t_pp, "sp_comm": t_sp, "dp_sync": t_dp,
                    },
                    feasible=feasible,
                    hbm_bytes=hbm,
                    candidates=0,
                )
                if feasible and (best is None or t < best.step_time_s):
                    best = rep
                if best_any is None or hbm < best_any.hbm_bytes:
                    best_any = rep
    winner = best or best_any
    if winner is None:
        raise ValueError(
            f"no (dp, tp, pp, sp) factorization of {num_devices} devices "
            f"satisfies the divisibility constraints (layers={num_layers}, "
            f"heads={num_heads}, batch={batch}, seq={seq})"
        )
    winner.candidates = n_cand
    return winner
