"""Parallelization strategy — the TPU-native MachineView assignment.

The reference's search output is a ``MachineView`` per PCG operator
(device list + strides) plus inserted parallel ops (reference
``machine_view.h:18-39``, ``graph.cc:2225`` serialization). Under GSPMD
the equivalent is (a) mesh axis degrees and (b) a per-operator *sharding
state* describing how that op's computation is laid out; the XLA
partitioner materialises the communication the reference represented as
explicit Repartition/Combine/Replicate/Reduction/AllReduce nodes.

Sharding states (per op):

  * ``REP``     — fully replicated (reference: MachineView on 1 device /
                  replicated weights).
  * ``DP``      — batch dim sharded over the ``data`` axis (reference:
                  Repartition on the sample dim).
  * ``TP_COL``  — weights column-parallel on ``model``; output features
                  sharded (reference: Replicate input + partition weight
                  out-channels).
  * ``TP_ROW``  — weights row-parallel on ``model``; consumes
                  feature-sharded input, output needs a psum (reference:
                  partition in-channels + Reduction after).

States compose with DP: ``DP`` shards only batch; ``TP_*`` states also
shard batch when ``data`` degree > 1 (the hybrid the Unity search
explores via its extra parallel dims).

Strategies serialize to JSON — the analog of ``--export-strategy`` /
``--import-strategy`` (reference ``config.h:171-172``, TRAIN.md:58-60).
"""
from __future__ import annotations

import base64
import dataclasses
import json
import pickle
from typing import Dict, List, Optional

from jax.sharding import PartitionSpec as P

from ..core.graph import Graph
from ..core.mesh import DATA_AXIS, MODEL_AXIS, MachineSpec

# The per-op sharding state space. SAMPLE/ATTR are the reference's
# extra search dims beyond DP/TP (enable_sample_parallel /
# enable_attribute_parallel, reference config.h:160-162): SAMPLE splits
# the batch over BOTH mesh axes (weights replicated), ATTR splits a
# non-batch activation dim (spatial/sequence) over the model axis.
# PARAM is the reference's parameter-parallel dim
# (enable_parameter_parallel) realised the GSPMD way: weights (and grads
# + optimizer state) shard over the DATA axis and are all-gathered per
# step — the ZeRO-style memory/time tradeoff the memory search can pick
# when replicated weights blow HBM.
STATES = ("REP", "DP", "TP_COL", "TP_ROW", "TP_MEGATRON", "PARAM",
          "SAMPLE", "ATTR")


class _GraphUnpickler(pickle.Unpickler):
    """Unpickler restricted to the EXACT types a serialized
    :class:`Graph` can legitimately contain — a strategy file is an
    interchange format (``--import-strategy``), so a crafted
    ``graph_pkl`` must not be able to execute arbitrary code via
    pickle's class resolution. Prefix allowlists are not enough (any
    admitted *callable* is invocable through pickle REDUCE), so only a
    closed set of data classes resolves, plus Initializer subclasses
    (constructing one is inert)."""

    _SAFE = {
        ("flexflow_tpu.core.graph", "Graph"),
        ("flexflow_tpu.core.graph", "OpNode"),
        ("flexflow_tpu.core.graph", "TensorRef"),
        ("flexflow_tpu.core.tensor", "TensorSpec"),
        ("flexflow_tpu.core.tensor", "DimSharding"),
        ("flexflow_tpu.core.dtypes", "DataType"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("builtins", "set"),
        ("builtins", "frozenset"),
        ("builtins", "slice"),
        ("builtins", "complex"),
        ("builtins", "bytearray"),
    }

    # Closed list of initializer class names Graph attrs can actually
    # contain — NOT issubclass(Initializer): pickle REDUCE invokes the
    # resolved class's constructor with attacker-controlled args, so a
    # future initializer subclass with side effects (file/RNG/device
    # access) must not silently join the attack surface.
    _SAFE_INITIALIZERS = {
        "Initializer", "GlorotUniform", "Zero", "Constant", "Uniform",
        "Normal",
    }

    def find_class(self, module, name):
        if (module, name) in self._SAFE:
            return super().find_class(module, name)
        if (module == "flexflow_tpu.initializers"
                and name in self._SAFE_INITIALIZERS):
            from .. import initializers as ffinit

            obj = getattr(ffinit, name, None)
            if isinstance(obj, type) and issubclass(obj, ffinit.Initializer):
                return obj
        raise pickle.UnpicklingError(
            f"strategy graph_pkl references forbidden type {module}.{name}"
        )


def _restricted_graph_loads(data: bytes):
    import io

    return _GraphUnpickler(io.BytesIO(data)).load()


@dataclasses.dataclass(frozen=True)
class OpShardingChoice:
    node_id: int
    state: str  # one of STATES

    def __post_init__(self):
        assert self.state in STATES, self.state


@dataclasses.dataclass
class ParallelStrategy:
    machine: MachineSpec
    choices: Dict[int, str]  # node_id -> state
    estimated_step_time: float = 0.0
    # The (possibly substitution-rewritten) graph the choices refer to.
    # Persisted with the strategy so an exported strategy from a search
    # that REWROTE the graph re-applies against the right node ids on
    # import — the reference ships the optimized graph + views together
    # the same way (GraphOptimalViewSerialized, graph.cc:2225, graph.h:92).
    graph: Optional[Graph] = None

    # ------------------------------------------------------------------
    # lowering to GSPMD annotations

    def weight_pspecs(self, graph: Graph) -> Dict[str, object]:
        """Per-op weight PartitionSpec trees keyed by node name — plugs
        into FFModel._param_shardings (the compile-pipeline hook the
        reference fills from deserialized optimal MachineViews)."""
        import jax

        from ..ops.registry import get_op

        out: Dict[str, object] = {}
        for node in graph.nodes:
            if node.op_type == "input":
                continue
            op = get_op(node.op_type)
            in_specs = [graph.out_spec(r) for r in node.inputs]
            w = op.weight_shapes(in_specs, node.attrs_dict)
            if not w:
                continue
            state = self.choices.get(node.id, "DP")
            if state in ("TP_COL", "TP_ROW", "TP_MEGATRON", "PARAM"):
                attrs = node.attrs_dict
                attrs["tp_shard"] = self._tp_kind(node.op_type, state)
                out[node.name] = op.weight_pspecs(in_specs, attrs, MODEL_AXIS)
            else:
                out[node.name] = jax.tree.map(lambda _: P(), w)
        return out

    @staticmethod
    def _tp_kind(op_type: str, state: str) -> str:
        if state == "TP_MEGATRON":
            return "megatron"
        if state == "PARAM":
            return "param"
        if op_type == "multihead_attention":
            return "heads"
        return "col" if state == "TP_COL" else "row"

    def stamp(self, graph: Graph) -> None:
        """Stamp ``tp_shard`` attrs onto the graph in place so the
        compile pipeline's weight-sharding hook (FFModel._param_shardings)
        and GSPMD see the found strategy — the analog of the reference's
        convert_graph_to_operators materialising searched MachineViews
        (model.cc:3347-3349)."""
        for node in graph.nodes:
            state = self.choices.get(node.id)
            if state in ("TP_COL", "TP_ROW", "TP_MEGATRON", "PARAM"):
                d = dict(node.attrs)
                d["tp_shard"] = self._tp_kind(node.op_type, state)
                node.attrs = tuple(sorted(d.items()))

    def activation_pspec(self, node_id: int, rank: int = 2) -> P:
        state = self.choices.get(node_id, "DP")
        data = DATA_AXIS if self.machine.data > 1 else None
        pad = (None,) * max(0, rank - 2)
        if state == "TP_COL":  # features (last dim) sharded
            return P(data, *pad, MODEL_AXIS)
        if state in ("DP", "TP_ROW", "TP_MEGATRON", "PARAM"):
            # TP_MEGATRON/PARAM keep boundary activations batch-sharded
            # full-feature; the weight sharding lives inside the op
            # (GSPMD inserts the Megatron psums / the ZeRO all-gather)
            return P(data)
        if state == "SAMPLE":  # batch over both axes
            both = tuple(a for a in (data, MODEL_AXIS) if a)
            return P(both if len(both) > 1 else MODEL_AXIS)
        if state == "ATTR":  # first attribute dim (dim 1) over model
            return P(data, MODEL_AXIS, *((None,) * max(0, rank - 2)))
        return P()

    def activation_constraints(self, graph: Graph) -> Dict[str, P]:
        """Per-node-name output constraints for states GSPMD cannot
        infer from weight shardings alone (SAMPLE/ATTR) — applied by
        FFModel.run_graph (the executable form of the reference's
        sample/attribute-parallel MachineViews)."""
        out: Dict[str, P] = {}
        for node in graph.nodes:
            state = self.choices.get(node.id)
            if state in ("SAMPLE", "ATTR") and node.out_specs:
                rank = len(node.out_specs[0].shape)
                out[node.name] = self.activation_pspec(node.id, rank)
        return out

    def to_dot(self, graph: Graph) -> str:
        """Strategy-colored PCG dot export (reference
        ``--export-strategy-computation-graph-file``, config.h:173-175 +
        tools/substitutions_to_dot)."""
        colors = {
            "REP": "gray80", "DP": "lightblue", "TP_COL": "salmon",
            "TP_ROW": "orange", "TP_MEGATRON": "gold",
            "SAMPLE": "palegreen", "ATTR": "plum", "PARAM": "khaki",
        }
        lines = ["digraph strategy {", "  node [style=filled];"]
        for n in graph.nodes:
            # same default every execution path uses (weight_pspecs /
            # activation_pspec): an unassigned node runs data-parallel
            state = self.choices.get(n.id, "DP")
            c = colors.get(state, "white")
            lines.append(
                f'  n{n.id} [label="{n.name}\\n{n.op_type} [{state}]" '
                f'fillcolor="{c}"];'
            )
            for r in n.inputs:
                lines.append(f"  n{r.node_id} -> n{n.id};")
        lines.append("}")
        return "\n".join(lines)

    # ------------------------------------------------------------------
    # (de)serialization — reference --export-strategy/--import-strategy

    def to_json(self) -> str:
        d = {
            "machine": dataclasses.asdict(self.machine),
            "choices": {str(k): v for k, v in self.choices.items()},
            "estimated_step_time": self.estimated_step_time,
        }
        if self.graph is not None:
            # The graph holds arbitrary attr values (initializer
            # objects, dtypes) — a pickled blob inside the JSON is the
            # moral equivalent of the reference's binary
            # GraphOptimalViewSerialized payload.
            d["graph_pkl"] = base64.b64encode(
                pickle.dumps(self.graph)
            ).decode("ascii")
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ParallelStrategy":
        d = json.loads(text)
        graph = None
        if "graph_pkl" in d:
            graph = _restricted_graph_loads(base64.b64decode(d["graph_pkl"]))
            if not isinstance(graph, Graph):
                raise ValueError(
                    "strategy file graph_pkl did not decode to a Graph"
                )
        return cls(
            machine=MachineSpec(**d["machine"]),
            choices={int(k): v for k, v in d["choices"].items()},
            estimated_step_time=d.get("estimated_step_time", 0.0),
            graph=graph,
        )

    def save(self, path: str, graph: Optional[Graph] = None):
        if graph is not None:
            self.graph = graph
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "ParallelStrategy":
        with open(path) as f:
            return cls.from_json(f.read())
