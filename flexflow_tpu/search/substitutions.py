"""Algebraic graph substitutions + backtracking search.

The reference's substitution engine pattern-matches OpX/TensorX template
graphs and runs a cost-pruned best-first search over rewrite sequences
(reference ``src/runtime/substitution.cc:1675-2445``; rules generated
per parallel degree at :1742-1810, plus JSON rules in
``substitutions/graph_subst_3_v2.json``). Two TPU-design deltas:

  * Parallel-op rewrites (replicate_linear_combine, partition_*_combine…)
    don't exist here — GSPMD owns resharding, so the *placement* search
    (:mod:`.placement`) covers that axis of Unity's space.
  * What remains valuable at graph level is computation algebra that XLA
    cannot see across our op boundaries: activation fusion into matmuls,
    sibling-GEMM merging (one bigger MXU matmul), and shape-op
    elimination. Rules are small Python match/apply pairs over the PCG
    IR instead of template graphs.

Every rule is semantics-preserving; tests check numerical equivalence of
``run_graph`` before/after each rewrite.
"""
from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Callable, Dict, List, Optional, Tuple

from ..core.graph import Graph, OpNode, TensorRef


# ---------------------------------------------------------------------------
# Graph surgery helper: rebuild a Graph with some nodes dropped/replaced.


def rebuild(
    graph: Graph,
    drop: set,
    replace_node: Dict[int, Tuple[str, Dict, Tuple[TensorRef, ...]]],
    redirect: Dict[TensorRef, TensorRef],
) -> Graph:
    """Produce a new Graph: nodes in ``drop`` removed, nodes in
    ``replace_node`` rebuilt with (op_type, attrs, inputs), and every
    edge passed through ``redirect`` (old ref -> new ref). Node ids are
    re-assigned densely in topological order; names are preserved so
    weight pytrees keyed by name survive rewrites."""
    id_map: Dict[int, int] = {}
    out = Graph()

    def map_ref(ref: TensorRef, follow_redirect: bool) -> TensorRef:
        if follow_redirect and ref in redirect:
            ref = redirect[ref]  # single-step: rules never chain redirects
        return TensorRef(id_map[ref.node_id], ref.out_idx)

    for node in graph.nodes:
        if node.id in drop:
            continue
        if node.id in replace_node:
            op_type, attrs, inputs = replace_node[node.id]
            follow = False  # explicit inputs already state the new wiring
        else:
            op_type, attrs, inputs = node.op_type, node.attrs_dict, node.inputs
            follow = True
        new_inputs = tuple(map_ref(r, follow) for r in inputs)
        if op_type == "input":
            out_specs = node.out_specs
        else:
            from ..ops.registry import get_op

            in_specs = [out.out_spec(r) for r in new_inputs]
            out_specs = get_op(op_type).infer(in_specs, attrs)
        new = out.add_node(op_type, attrs, new_inputs, out_specs, name=node.name)
        id_map[node.id] = new.id
    # Every redirected output leaves a (name, out_idx) alias — dropped
    # nodes (fused-away relu) AND replaced survivors whose outputs
    # changed meaning (sibling-dense merge re-points a.0 to the split) —
    # so a compile output declared before the rewrite still resolves.
    # Appended as a NEW generation: this rewrite's redirects are
    # simultaneous, later rewrites compose (Graph.resolve_name).
    prior = getattr(graph, "name_aliases", None) or []
    if isinstance(prior, dict):  # pre-generations format (bare-str keys)
        prior = [
            {
                (k if isinstance(k, tuple) else (k, 0)): v
                for k, v in prior.items()
            }
        ]
    out.name_aliases = list(prior)
    gen = {}
    for ref, target in redirect.items():
        if target.node_id in id_map:
            src = graph.nodes[ref.node_id]
            tgt = out.nodes[id_map[target.node_id]]
            gen[(src.name, ref.out_idx)] = (tgt.name, target.out_idx)
    if gen:
        out.name_aliases.append(gen)
    return out


# ---------------------------------------------------------------------------
# Rules


@dataclasses.dataclass(frozen=True)
class Substitution:
    name: str
    apply_fn: Callable[[Graph], Optional[Graph]]

    def apply(self, graph: Graph) -> Optional[Graph]:
        """Return a rewritten graph, or None when the rule doesn't match
        anywhere. Applies at the *first* match site; the search loop
        re-applies for further sites."""
        return self.apply_fn(graph)


_ACT_OPS = {"relu", "sigmoid", "tanh", "gelu", "elu"}


def _consumers(graph: Graph, node_id: int) -> List[OpNode]:
    return graph.consumers(node_id)


def _fuse_dense_activation(graph: Graph) -> Optional[Graph]:
    """dense(act=None) → elementwise-activation ⇒ dense(act) (reference
    rule linear_relu_merge, substitution.cc:1779)."""
    for node in graph.nodes:
        if node.op_type != "element_unary":
            continue
        a = node.attrs_dict
        if a.get("op") not in _ACT_OPS or a.get("scalar") is not None:
            continue
        (src,) = node.inputs
        prod = graph.node(src.node_id)
        if prod.op_type != "dense" or prod.attrs_dict.get("activation"):
            continue
        if len(_consumers(graph, prod.id)) != 1:
            continue
        attrs = prod.attrs_dict
        attrs["activation"] = node.attrs_dict["op"]
        return rebuild(
            graph,
            drop={node.id},
            replace_node={prod.id: ("dense", attrs, prod.inputs)},
            redirect={TensorRef(node.id, 0): TensorRef(prod.id, 0)},
        )
    return None


def _merge_sibling_dense(graph: Graph) -> Optional[Graph]:
    """Two dense ops on the same input with identical activation/bias ⇒
    one wider GEMM + split (the fuse_head pattern; bigger MXU tiles). The
    merged node keeps the first sibling's name so only the second's
    weights re-key."""
    for node in graph.nodes:
        dense_consumers = [
            c
            for c in _consumers(graph, node.id)
            if c.op_type == "dense"
            and len(c.inputs) == 1
            and c.inputs[0] == TensorRef(node.id, 0)
        ]
        for a, b in itertools.combinations(dense_consumers, 2):
            aa, ba = a.attrs_dict, b.attrs_dict
            if aa.get("activation") != ba.get("activation"):
                continue
            if aa.get("use_bias", True) != ba.get("use_bias", True):
                continue
            # redirected consumers will read from the split (b's slot):
            # they must all sit after b in topo order
            if any(
                c.id <= b.id
                for nid in (a.id, b.id)
                for c in _consumers(graph, nid)
                if c.id != b.id
            ):
                continue
            oa, ob = aa["out_dim"], ba["out_dim"]
            merged_attrs = dict(aa)
            merged_attrs["out_dim"] = oa + ob
            split_attrs = {"sizes": (oa, ob), "axis": -1}
            # merged dense replaces `a`; split node replaces `b`
            return rebuild(
                graph,
                drop=set(),
                replace_node={
                    a.id: ("dense", merged_attrs, a.inputs),
                    b.id: ("split", split_attrs, (TensorRef(a.id, 0),)),
                },
                redirect={
                    # consumers of a read split output 0; of b, output 1
                    TensorRef(a.id, 0): TensorRef(b.id, 0),
                    TensorRef(b.id, 0): TensorRef(b.id, 1),
                },
            )
    return None


def _drop_identity_reshape(graph: Graph) -> Optional[Graph]:
    """reshape to the same shape ⇒ eliminate."""
    for node in graph.nodes:
        if node.op_type != "reshape":
            continue
        (src,) = node.inputs
        if graph.out_spec(src).shape == node.out_specs[0].shape:
            return rebuild(
                graph,
                drop={node.id},
                replace_node={},
                redirect={TensorRef(node.id, 0): src},
            )
    return None


def _drop_inverse_transpose(graph: Graph) -> Optional[Graph]:
    """transpose(p) ∘ transpose(q) with p∘q = id ⇒ eliminate both."""
    for node in graph.nodes:
        if node.op_type != "transpose":
            continue
        (src,) = node.inputs
        prod = graph.node(src.node_id)
        if prod.op_type != "transpose":
            continue
        p = prod.attrs_dict["perm"]
        q = node.attrs_dict["perm"]
        if tuple(q[i] for i in p) != tuple(range(len(p))):
            continue
        if len(_consumers(graph, prod.id)) != 1:
            continue
        return rebuild(
            graph,
            drop={node.id, prod.id},
            replace_node={},
            redirect={TensorRef(node.id, 0): prod.inputs[0]},
        )
    return None


def _merge_cast_chain(graph: Graph) -> Optional[Graph]:
    """cast ∘ cast ⇒ single cast to the final dtype."""
    for node in graph.nodes:
        if node.op_type != "cast":
            continue
        (src,) = node.inputs
        prod = graph.node(src.node_id)
        if prod.op_type != "cast" or len(_consumers(graph, prod.id)) != 1:
            continue
        return rebuild(
            graph,
            drop={prod.id},
            replace_node={node.id: ("cast", node.attrs_dict, prod.inputs)},
            redirect={},
        )
    return None


# ---------------------------------------------------------------------------
# Declarative JSON rules (reference --substitution-json +
# substitution_loader.cc + substitutions/graph_subst_3_v2.json). A rule
# matches a single-consumer producer→consumer CHAIN of ops by op_type +
# attr conditions and either drops the chain (redirecting to its input)
# or replaces it with one op whose attrs may copy matched values
# ("$i.key" = element i's attr `key`). Conditions support constants and
# {"$eq": "i.key"} cross-element equality.


def _chain_matches(graph: Graph, last: OpNode, pattern: List[Dict]):
    """Walk input[0] edges upward from ``last`` matching the pattern
    (ordered producer..consumer). Returns the matched node chain or
    None; intermediate nodes must have exactly one consumer."""
    chain: List[OpNode] = [last]
    node = last
    for _ in range(len(pattern) - 1):
        if len(node.inputs) != 1:
            return None
        node = graph.node(node.inputs[0].node_id)
        if len(_consumers(graph, node.id)) != 1:
            return None
        chain.append(node)
    chain.reverse()  # producer first, like the pattern
    for spec, node in zip(pattern, chain):
        if node.op_type != spec["op"]:
            return None
    # attr conditions once the ops line up
    for i, spec in enumerate(pattern):
        attrs = chain[i].attrs_dict
        for key, cond in (spec.get("attrs") or {}).items():
            if isinstance(cond, dict) and "$eq" in cond:
                j, _, other = cond["$eq"].partition(".")
                if attrs.get(key) != chain[int(j)].attrs_dict.get(other):
                    return None
            else:
                val = attrs.get(key)
                if isinstance(val, tuple):
                    val = list(val)
                if val != cond:
                    return None
    return chain


def _resolve_attrs(template: Dict, chain: List[OpNode]) -> Dict:
    out = {}
    for key, val in template.items():
        if isinstance(val, str) and val.startswith("$"):
            i, _, name = val[1:].partition(".")
            if name not in chain[int(i)].attrs_dict:
                raise ValueError(
                    f"substitution attr reference {val!r} names no attr "
                    f"on matched op {chain[int(i)].op_type!r}"
                )
            val = chain[int(i)].attrs_dict[name]
        if isinstance(val, list):
            val = tuple(val)
        out[key] = val
    return out


def make_json_rule(spec: Dict) -> Substitution:
    pattern = spec["pattern"]
    action = spec["action"]
    # reject malformed rules at load time — a typo'd kind must not sit
    # silently inert (or abort the search mid-run) after a match
    if not pattern:
        raise ValueError(f"rule {spec.get('name')!r}: empty pattern")
    if action.get("kind") not in ("drop", "replace"):
        raise ValueError(
            f"rule {spec.get('name')!r}: unknown action kind "
            f"{action.get('kind')!r} (expected 'drop' or 'replace')"
        )
    if action["kind"] == "replace":
        if "op" not in action:
            raise ValueError(
                f"rule {spec.get('name')!r}: replace action needs an 'op'"
            )
        from ..ops.registry import get_op

        get_op(action["op"])  # unknown target op fails at load, not apply
        # attr references must parse and stay in pattern bounds at LOAD
        # time — a typo'd '$5.k' or '$x.k' must not abort an
        # auto_parallel compile mid-search
        for key, val in (action.get("attrs") or {}).items():
            if isinstance(val, str) and val.startswith("$"):
                i, _, name = val[1:].partition(".")
                if not i.isdigit() or int(i) >= len(pattern) or not name:
                    raise ValueError(
                        f"rule {spec.get('name')!r}: malformed attr "
                        f"reference {val!r} for {key!r} (expected "
                        f"'$<pattern-index>.<attr>' with index < "
                        f"{len(pattern)})"
                    )
    # $eq cross-references in pattern attrs get the same load-time check
    for i, pspec in enumerate(pattern):
        for key, cond in (pspec.get("attrs") or {}).items():
            if isinstance(cond, dict) and "$eq" in cond:
                j, _, other = cond["$eq"].partition(".")
                if not j.isdigit() or int(j) >= len(pattern) or not other:
                    raise ValueError(
                        f"rule {spec.get('name')!r}: malformed $eq "
                        f"reference {cond['$eq']!r} in pattern[{i}].{key}"
                    )

    def apply_fn(graph: Graph) -> Optional[Graph]:
        for node in graph.nodes:
            if node.op_type != pattern[-1]["op"]:
                continue
            chain = _chain_matches(graph, node, pattern)
            if chain is None:
                continue
            head_input = chain[0].inputs[0] if chain[0].inputs else None
            if action["kind"] == "drop":
                if head_input is None:
                    continue
                # a dropped chain must be an identity: single-input head
                # whose source spec equals the chain's output spec —
                # otherwise consumers would silently re-infer from a
                # different shape (the reference's substitution loader
                # validates rule legality the same way)
                if len(chain[0].inputs) != 1:
                    continue
                src = graph.out_spec(head_input)
                if src.shape != node.out_specs[0].shape or (
                    src.dtype != node.out_specs[0].dtype
                ):
                    continue
                return rebuild(
                    graph,
                    drop={n.id for n in chain},
                    replace_node={},
                    redirect={TensorRef(chain[-1].id, 0): head_input},
                )
            else:  # "replace" (kinds validated at load time)
                try:
                    attrs = _resolve_attrs(action.get("attrs", {}), chain)
                except ValueError:
                    # a well-formed reference can still name an attr the
                    # matched op doesn't carry — skip the match rather
                    # than abort the whole search
                    continue
                # same legality guard as drop: the replacement op must
                # reproduce the matched chain's output spec, or downstream
                # consumers would silently re-infer from a different shape
                from ..ops.registry import get_op

                in_specs = [graph.out_spec(r) for r in chain[0].inputs]
                try:
                    new_specs = get_op(action["op"]).infer(in_specs, attrs)
                except Exception:
                    continue
                if tuple((s.shape, s.dtype) for s in new_specs) != tuple(
                    (s.shape, s.dtype) for s in node.out_specs
                ):
                    continue
                return rebuild(
                    graph,
                    drop={n.id for n in chain[:-1]},
                    replace_node={
                        chain[-1].id: (
                            action["op"], attrs, chain[0].inputs
                        )
                    },
                    redirect={},
                )
        return None

    return Substitution(spec["name"], apply_fn)


def load_substitutions_json(path: str) -> List[Substitution]:
    """Load declarative rules (the reference's ``--substitution-json``
    import, substitution_loader.cc)."""
    import json

    with open(path) as f:
        doc = json.load(f)
    return [make_json_rule(spec) for spec in doc["rules"]]


def default_json_rules() -> List[Substitution]:
    import os

    path = os.path.join(os.path.dirname(__file__), "substitutions.json")
    if not os.path.exists(path):
        return []
    try:
        return load_substitutions_json(path)
    except Exception as e:  # pragma: no cover - corrupt install
        # this runs at package import: a corrupt bundled rules file must
        # degrade the search to built-in rules, not break every
        # ``import flexflow_tpu`` (serving users never touch the search)
        import warnings

        warnings.warn(f"ignoring bundled substitution rules ({e})")
        return []


SUBSTITUTIONS: List[Substitution] = [
    Substitution("fuse_dense_activation", _fuse_dense_activation),
    Substitution("merge_sibling_dense", _merge_sibling_dense),
    Substitution("drop_identity_reshape", _drop_identity_reshape),
    Substitution("drop_inverse_transpose", _drop_inverse_transpose),
    Substitution("merge_cast_chain", _merge_cast_chain),
] + default_json_rules()


# ---------------------------------------------------------------------------
# Best-first rewrite search (reference base_optimize, substitution.cc:2245)


def apply_substitutions(
    graph: Graph,
    cost_fn: Callable[[Graph], float],
    budget: int = 64,
    alpha: float = 1.05,
    rules: Optional[List[Substitution]] = None,
) -> Tuple[Graph, float, List[str]]:
    """Best-first search over rewrite sequences: expand the cheapest
    graph state, prune candidates costing more than ``alpha`` × best
    (the reference's alpha pruning + ``--budget``). Returns (best graph,
    best cost, applied-rule trace)."""
    rules = rules if rules is not None else SUBSTITUTIONS
    start_cost = cost_fn(graph)
    best_graph, best_cost, best_trace = graph, start_cost, []
    seen = {_graph_key(graph)}
    counter = itertools.count()
    heap = [(start_cost, next(counter), graph, [])]
    expansions = 0
    while heap and expansions < budget:
        cost, _, g, trace = heapq.heappop(heap)
        expansions += 1
        for rule in rules:
            g2 = rule.apply(g)
            if g2 is None:
                continue
            key = _graph_key(g2)
            if key in seen:
                continue
            seen.add(key)
            c2 = cost_fn(g2)
            if c2 < best_cost:
                best_graph, best_cost, best_trace = g2, c2, trace + [rule.name]
            if c2 <= alpha * best_cost:
                heapq.heappush(heap, (c2, next(counter), g2, trace + [rule.name]))
    return best_graph, best_cost, best_trace


def _graph_key(graph: Graph) -> Tuple:
    return tuple(
        (n.op_type, n.attrs, n.inputs) for n in graph.nodes
    )
