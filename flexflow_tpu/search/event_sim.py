"""Event-driven strategy simulation with comm/compute overlap.

The reference prices a candidate strategy by event-simulating the task
graph — per-device compute queues plus communication tasks that overlap
with compute (reference ``Simulator::simulate_runtime``,
``src/runtime/simulator.cc:797``, and the taskgraph variant at
``:1233``). The straight-sum estimator (:func:`.simulator
.estimate_graph_cost`) systematically overestimates strategies whose
collectives hide behind compute — pipelined/bucketed DP grad sync being
the canonical case — and can therefore mis-rank them.

This module is the TPU-native equivalent: a list-scheduling simulation
over two resources —

* ``compute``: one MXU stream per device (SPMD: every device runs the
  same program, so one stream models all of them);
* ``comm``: the ICI collective channel (XLA overlaps collectives with
  compute via async start/done pairs; a single channel models the
  serialization of collectives against each other).

Training runs a forward sweep (topological order), then a backward
sweep (reverse order, 2× the forward time per op — the reference times
fwd and bwd separately), and releases each op's DP gradient all-reduce
onto the comm channel the moment its backward completes — exactly the
bucketed overlap XLA/GSPMD produces, leaving only the tail exposed.
Resharding collectives occupy the comm channel between producer finish
and consumer start on the forward sweep only — matching the additive
estimator's once-per-edge pricing so the two stay byte-comparable (the
backward's mirrored collectives are deliberately not double-priced by
either model).
"""
from __future__ import annotations

from typing import Dict

from ..core.graph import Graph
from ..core.mesh import DATA_AXIS
from .simulator import CostModel, weight_bytes
from .strategy import ParallelStrategy


def event_sim_cost(
    graph: Graph,
    strategy: ParallelStrategy,
    cm: CostModel,
) -> float:
    """Makespan of one training/inference step under ``strategy`` with
    comm/compute overlap. Always ≤ the straight-sum estimate on the
    same inputs (overlap can only hide time)."""
    training = cm.training
    states = {n.id: strategy.choices.get(n.id, "DP") for n in graph.nodes}

    # Per-op compute durations. op_cost folds fwd+bwd (×3) and the op's
    # internal collectives when training; split 1/3 fwd, 2/3 bwd — the
    # internal collectives scale the same way (bwd re-runs them).
    fwd: Dict[int, float] = {}
    bwd: Dict[int, float] = {}
    for node in graph.nodes:
        c = cm.op_cost(graph, node, states[node.id])
        if training:
            fwd[node.id] = c / 3.0
            bwd[node.id] = 2.0 * c / 3.0
        else:
            fwd[node.id] = c
            bwd[node.id] = 0.0

    compute_free = 0.0
    comm_free = 0.0
    done: Dict[int, float] = {}

    # ---- forward sweep ------------------------------------------------
    for node in graph.nodes:
        ready = 0.0
        for ref in node.inputs:
            r = cm.reshard_cost(
                graph,
                graph.out_spec(ref),
                states[ref.node_id],
                states[node.id],
            )
            src = done[ref.node_id]
            if r > 0.0:
                start = max(src, comm_free)
                comm_free = start + r
                ready = max(ready, comm_free)
            else:
                ready = max(ready, src)
        start = max(ready, compute_free)
        compute_free = start + fwd[node.id]
        done[node.id] = compute_free

    if not training:
        return max(compute_free, comm_free)

    # ---- backward sweep + overlapped DP grad sync ---------------------
    # Backward visits ops in reverse topological order on the compute
    # stream. Each op's DP gradient all-reduce is released onto the comm
    # channel the moment its backward finishes — the bucketed overlap
    # XLA/GSPMD produces. To stay byte-for-byte comparable with the
    # additive estimator's single fused grad all-reduce (and keep the
    # invariant event_sim ≤ straight-sum), buckets pay ring *bandwidth*
    # per op but the ring latency only once: XLA coalesces the async
    # starts, it does not pay (degree-1) hops per parameter tensor.
    d = cm.machine.data
    any_grads = False
    for node in reversed(graph.nodes):
        compute_free += bwd[node.id]
        if d > 1:
            nbytes = weight_bytes(graph, node)
            if nbytes > 0.0:
                if states[node.id] in ("TP_COL", "TP_ROW", "TP_MEGATRON"):
                    nbytes /= cm.machine.model
                elif states[node.id] == "PARAM":
                    # ZeRO grads reduce-scatter: half an all-reduce
                    # (mirrors grad_sync_cost's accounting)
                    nbytes /= 2.0
                bw = cm.topo.axis_bandwidth(DATA_AXIS)
                r = 2.0 * (d - 1) / d * nbytes / bw  # bandwidth-only term
                start = max(compute_free, comm_free)
                comm_free = start + r
                any_grads = True
    if any_grads:
        comm_free += cm.topo.axis_latency(DATA_AXIS) * (d - 1)

    return max(compute_free, comm_free)
