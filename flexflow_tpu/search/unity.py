"""Top-level Unity-style joint optimization + MCMC fallback.

Mirrors the reference's two searches:

  * :func:`optimize` — the Unity path (reference
    ``GraphSearchHelper::graph_optimize``, substitution.cc:1914): for
    each candidate mesh shape (axis-degree factorization of the device
    count — the analog of enumerating MachineResource splits), run the
    substitution best-first search with the placement DP as the cost
    oracle, keep the (graph, strategy) with the lowest simulated step
    time.
  * :func:`mcmc_optimize` — the legacy simulated-annealing fallback
    (reference ``FFModel::mcmc_optimize``, model.cc:3808): random
    single-op state flips accepted by the Metropolis rule.
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Iterable, List, Optional, Tuple

from ..core.graph import Graph
from ..core.mesh import MachineSpec
from .machine_model import TPUChip, TPUTopology
from .event_sim import event_sim_cost
from .placement import placement_dp
from .simulator import CostModel, candidate_states
from .strategy import ParallelStrategy
from .substitutions import SUBSTITUTIONS, apply_substitutions


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def mesh_candidates(
    num_devices: int, max_model: Optional[int] = None, *, expert: bool = False
) -> List[MachineSpec]:
    """Factor the device count over (data, model[, expert]) axis degrees
    — the search's machine-grid enumeration (all factorizations, not
    just powers of two; a device count's divisor set is small, so the
    grid stays cheap even at pod scale). Expert degrees join the grid
    when the graph contains MoE ops; pipeline/seq degrees are planned by
    :mod:`.planner` for stacked-layer models (the reference likewise
    fixes inference PP outside its search). ``max_model`` optionally
    bounds the TP degree (e.g. to one ICI torus axis)."""
    out = []
    for model in _divisors(num_devices):
        if max_model is not None and model > max_model:
            continue
        rest = num_devices // model
        if expert:
            for e in _divisors(rest):
                out.append(
                    MachineSpec(data=rest // e, model=model, expert=e)
                )
        else:
            out.append(MachineSpec(data=rest, model=model))
    return out


@dataclasses.dataclass
class SearchReport:
    best_cost: float
    machine: MachineSpec
    substitutions_applied: List[str]
    candidates_evaluated: int
    # memory-aware search results (reference perform_memory_search,
    # graph.cc:2132-2190)
    memory_bytes: float = 0.0
    memory_budget: Optional[float] = None
    memory_lambda: float = 0.0
    memory_feasible: bool = True


def refine_strategy(
    graph: Graph,
    strategy: ParallelStrategy,
    cm: CostModel,
    *,
    budget_bytes: float = float("inf"),
    passes: int = 2,
) -> ParallelStrategy:
    """Coordinate-descent polish of a placement under the TRUE objective
    (the overlap-aware event simulation): per node, try every candidate
    state and keep the argmin, skipping states that break the memory
    budget. Closes the gap left by the DP's additive objective and its
    fan-out amortisation heuristic (placement_dp docstring) — the
    reference similarly refines DP placements against its full
    simulator (graph.cc:1600 graph_cost memoisation + simulate).
    Monotone in time once feasible: never returns a worse event-sim
    cost than it was given — except that an over-budget input first
    gets a dedicated memory-descent pass (which may trade time for
    footprint) so the budget can be met by multiple flips, not just
    one."""
    best_cost = event_sim_cost(graph, strategy, cm)
    # per-node memory is independent (strategy_memory_bytes is a plain
    # sum), so a state flip updates the total in O(1) instead of a full
    # O(nodes) resum per candidate
    mem_terms = {
        n.id: cm.op_memory_bytes(graph, n, strategy.choices.get(n.id, "DP"))
        for n in graph.nodes
    }
    mem_total = sum(mem_terms.values())
    if mem_total > budget_bytes:
        # An over-budget winner cannot be rescued by the time-descent
        # gate below (it would need a SINGLE flip to clear the whole
        # overage): walk memory down first — take each node's
        # smallest-footprint state until the budget is met, then let
        # the time passes improve within budget.
        for node in graph.nodes:
            if mem_total <= budget_bytes:
                break
            cur = strategy.choices.get(node.id, "DP")
            best_s, best_term = cur, mem_terms[node.id]
            for s in candidate_states(
                node,
                cm.machine,
                enable_sample=cm.enable_sample,
                enable_attribute=cm.enable_attribute,
                enable_parameter=cm.enable_parameter,
            ):
                t = cm.op_memory_bytes(graph, node, s)
                if t < best_term:
                    best_s, best_term = s, t
            if best_s != cur:
                strategy.choices[node.id] = best_s
                mem_total += best_term - mem_terms[node.id]
                mem_terms[node.id] = best_term
        best_cost = event_sim_cost(graph, strategy, cm)
    for _ in range(passes):
        improved = False
        for node in graph.nodes:
            cur = strategy.choices.get(node.id, "DP")
            for s in candidate_states(
                node,
                cm.machine,
                enable_sample=cm.enable_sample,
                enable_attribute=cm.enable_attribute,
                enable_parameter=cm.enable_parameter,
            ):
                if s == cur:
                    continue
                new_term = cm.op_memory_bytes(graph, node, s)
                if (
                    mem_total - mem_terms[node.id] + new_term
                    > budget_bytes
                ):
                    continue
                strategy.choices[node.id] = s
                c = event_sim_cost(graph, strategy, cm)
                if c < best_cost * (1 - 1e-9):
                    mem_total += new_term - mem_terms[node.id]
                    mem_terms[node.id] = new_term
                    best_cost, cur, improved = c, s, True
                else:
                    strategy.choices[node.id] = cur
        if not improved:
            break
    strategy.estimated_step_time = best_cost
    return strategy


def memory_search(
    graph: Graph,
    cm: CostModel,
    budget_bytes: float,
    *,
    iters: int = 8,
) -> Tuple[ParallelStrategy, float]:
    """Binary-search the memory/runtime tradeoff λ (reference
    ``try_one_lambda`` / ``perform_memory_search``): find the smallest λ
    whose placement fits ``budget_bytes`` per device — i.e. give up only
    as much runtime as HBM requires. Returns (strategy, λ); the caller
    checks feasibility via ``cm.strategy_memory_bytes``."""
    strat0 = placement_dp(graph, cm)
    if cm.strategy_memory_bytes(graph, strat0) <= budget_bytes:
        return strat0, 0.0
    strat1 = placement_dp(graph, cm, mem_lambda=1.0)
    if cm.strategy_memory_bytes(graph, strat1) > budget_bytes:
        return strat1, 1.0  # even pure memory-minimisation doesn't fit
    lo, hi = 0.0, 1.0
    best, best_lambda = strat1, 1.0
    for _ in range(iters):
        mid = 0.5 * (lo + hi)
        s = placement_dp(graph, cm, mem_lambda=mid)
        if cm.strategy_memory_bytes(graph, s) <= budget_bytes:
            best, best_lambda = s, mid
            hi = mid
        else:
            lo = mid
    return best, best_lambda


def optimize(
    graph: Graph,
    num_devices: int,
    topo: Optional[TPUTopology] = None,
    *,
    training: bool = True,
    budget: int = 32,
    alpha: float = 1.05,
    machines: Optional[Iterable[MachineSpec]] = None,
    measured: bool = False,
    measured_cache: Optional[str] = None,
    enable_sample: bool = True,
    enable_attribute: bool = True,
    enable_parameter: bool = True,
    allow_expert: bool = True,
    extra_rules: Optional[List] = None,
    memory_budget: Optional[float] = None,
) -> Tuple[Graph, ParallelStrategy, SearchReport]:
    """Joint substitution + sharding search. Returns the rewritten graph,
    the winning strategy, and a report. With ``measured`` the cost model
    calibrates per-op times on the current device first (the reference's
    on-device ``inner_measure_operator_cost``, model.cu:38).
    ``allow_expert=False`` keeps MoE expert degrees out of the grid
    (when the config fixed the expert degree outside the search).

    ``memory_budget`` (bytes per device; defaults to the chip's HBM
    capacity) makes the search memory-aware: a machine/strategy whose
    per-device footprint exceeds the budget is re-searched with the λ
    tradeoff (:func:`memory_search`) and discarded as infeasible if even
    pure memory-minimisation doesn't fit — so the search can no longer
    return a strategy that OOMs the chip (reference
    ``perform_memory_search``, graph.cc:2132-2190). Pass ``float('inf')``
    to disable."""
    topo = topo or TPUTopology(chip=TPUChip.v5e(), num_chips=num_devices)
    has_moe = any(
        n.op_type in ("moe", "experts", "group_by") for n in graph.nodes
    )
    machines = (
        list(machines)
        if machines is not None
        else mesh_candidates(num_devices, expert=has_moe and allow_expert)
    )

    # calibrate ONCE — on-device timings are machine-spec independent
    shared_measured = None
    if measured:
        cm0 = CostModel(topo=topo, machine=MachineSpec(), training=training)
        cm0.calibrate(graph, cache_path=measured_cache)
        shared_measured = cm0.measured

    if memory_budget is None:
        memory_budget = topo.chip.hbm_capacity

    # (feasible?, time, graph, strategy, trace, mem, λ) — feasible
    # strategies always beat infeasible ones; within a class, min time
    # (infeasible fallback: min memory, so we never return silently-OOM
    # when a fitting machine exists).
    best = None
    evaluated = 0
    for machine in machines:
        cm = CostModel(
            topo=topo, machine=machine, training=training,
            enable_sample=enable_sample, enable_attribute=enable_attribute,
            enable_parameter=enable_parameter,
            measured=shared_measured,
        )

        def cost_fn(g: Graph) -> float:
            return placement_dp(g, cm).estimated_step_time

        rules = SUBSTITUTIONS + list(extra_rules or [])
        g2, cost2, trace = apply_substitutions(
            graph, cost_fn, budget=budget, alpha=alpha, rules=rules
        )
        strat, lam = memory_search(g2, cm, memory_budget)
        mem = cm.strategy_memory_bytes(g2, strat)
        feasible = mem <= memory_budget
        evaluated += 1
        key = (
            not feasible,
            strat.estimated_step_time if feasible else mem,
        )
        if best is None or key < best[0]:
            best = (key, g2, strat, trace, mem, lam, feasible, cm)
    _, g_best, s_best, trace, mem, lam, feasible, cm_best = best
    # Polish only the WINNER under the true (event-sim) objective —
    # refining every mesh candidate would multiply the O(passes × nodes
    # × states) sweep by the divisor count at pod scale.
    s_best = refine_strategy(
        g_best, s_best, cm_best, budget_bytes=memory_budget
    )
    # refinement can shrink memory (or the winner was infeasible and a
    # cheaper-AND-smaller flip landed it in budget): recompute BOTH the
    # footprint and the feasibility verdict together
    mem = cm_best.strategy_memory_bytes(g_best, s_best)
    feasible = mem <= memory_budget
    report = SearchReport(
        best_cost=s_best.estimated_step_time,
        machine=s_best.machine,
        substitutions_applied=trace,
        candidates_evaluated=evaluated,
        memory_bytes=mem,
        memory_budget=memory_budget,
        memory_lambda=lam,
        memory_feasible=feasible,
    )
    return g_best, s_best, report


def mcmc_optimize(
    graph: Graph,
    cost_model: CostModel,
    *,
    iters: int = 500,
    temperature: float = 0.25,
    seed: int = 0,
    init: Optional[ParallelStrategy] = None,
) -> ParallelStrategy:
    """Metropolis search over per-op sharding states (reference
    ``FFModel::mcmc_optimize``: random op gets a random machine view,
    accept if exp(-Δ/T) beats a coin flip)."""
    rng = random.Random(seed)
    machine = cost_model.machine
    nodes = [n for n in graph.nodes if n.op_type != "input"]
    if init is not None:
        choices = dict(init.choices)
    else:
        choices = {n.id: "DP" for n in graph.nodes}
    strat = ParallelStrategy(machine=machine, choices=choices)
    cur = event_sim_cost(graph, strat, cost_model)
    best_choices, best_cost = dict(choices), cur
    for _ in range(iters):
        node = rng.choice(nodes)
        states = candidate_states(
            node,
            machine,
            enable_sample=cost_model.enable_sample,
            enable_attribute=cost_model.enable_attribute,
            enable_parameter=cost_model.enable_parameter,
        )
        new_state = rng.choice(states)
        old_state = choices.get(node.id, "DP")
        if new_state == old_state:
            continue
        choices[node.id] = new_state
        cand = event_sim_cost(
            graph, ParallelStrategy(machine=machine, choices=choices), cost_model
        )
        delta = cand - cur
        if delta <= 0 or rng.random() < math.exp(-delta / (temperature * max(cur, 1e-12))):
            cur = cand
            if cur < best_cost:
                best_cost, best_choices = cur, dict(choices)
        else:
            choices[node.id] = old_state
    out = ParallelStrategy(machine=machine, choices=best_choices)
    out.estimated_step_time = best_cost
    return out
