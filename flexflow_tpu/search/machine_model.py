"""Analytic TPU machine model for the auto-parallel search.

The reference's simulator is parameterised by a machine model hierarchy —
``SimpleMachineModel`` (intra/inter-node bandwidths) up to
``NetworkedMachineModel`` with explicit topology + routing (reference
``src/runtime/machine_model.cc:1-1287``, ``network.cc:47``,
``machine_config_example``). A TPU pod is far more regular: identical
chips on a 2-D/3-D ICI torus, slices joined over DCN. So the TPU model
is a chip roofline (MXU peak, HBM bandwidth) + per-hop ICI link
bandwidth + DCN bandwidth, and collective costs follow the standard
ring/band formulas instead of weighted-shortest-path routing.

All times in seconds, sizes in bytes, rates in bytes/s or FLOP/s.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from ..core.mesh import AXIS_ORDER


@dataclasses.dataclass(frozen=True)
class TPUChip:
    """Single-chip roofline parameters."""

    name: str
    bf16_flops: float          # peak MXU FLOP/s at bf16
    hbm_bandwidth: float       # bytes/s
    hbm_capacity: float        # bytes
    ici_bandwidth: float       # bytes/s per ICI link direction
    mxu_efficiency: float = 0.55   # achievable fraction of peak on big GEMMs
    hbm_efficiency: float = 0.80

    # -- presets ------------------------------------------------------

    @classmethod
    def v5e(cls):
        return cls(
            name="v5e",
            bf16_flops=197e12,
            hbm_bandwidth=819e9,
            hbm_capacity=16e9,
            ici_bandwidth=45e9,
        )

    @classmethod
    def v5p(cls):
        return cls(
            name="v5p",
            bf16_flops=459e12,
            hbm_bandwidth=2765e9,
            hbm_capacity=95e9,
            ici_bandwidth=90e9,
        )

    @classmethod
    def v4(cls):
        return cls(
            name="v4",
            bf16_flops=275e12,
            hbm_bandwidth=1228e9,
            hbm_capacity=32e9,
            ici_bandwidth=45e9,
        )


@dataclasses.dataclass(frozen=True)
class TPUTopology:
    """A slice (ICI-connected mesh of chips) optionally multiplied over
    DCN (multi-slice). Mesh axes map onto ICI first (innermost axes) —
    matching ``core.mesh.AXIS_ORDER``'s convention that ``model`` rides
    the fastest links — and any axis marked in ``dcn_axes`` pays DCN
    bandwidth instead."""

    chip: TPUChip
    num_chips: int = 1
    dcn_bandwidth: float = 25e9     # bytes/s per host pair
    dcn_axes: tuple = ()            # mesh axes that cross slice boundaries
    per_hop_latency: float = 1e-6   # ICI hop latency (s)
    dcn_latency: float = 10e-6

    def axis_bandwidth(self, axis: str) -> float:
        return self.dcn_bandwidth if axis in self.dcn_axes else self.chip.ici_bandwidth

    def axis_latency(self, axis: str) -> float:
        return self.dcn_latency if axis in self.dcn_axes else self.per_hop_latency


class CollectiveModel:
    """Ring-algorithm collective cost estimates over one mesh axis.

    The reference prices its parallel ops (AllReduce/Combine/Replicate/
    Repartition/Reduction, SURVEY.md §2.1) through per-pair transfer
    routing; on TPU the GSPMD-inserted collectives follow closed-form
    ring costs over the axis's ICI links.
    """

    def __init__(self, topo: TPUTopology):
        self.topo = topo

    def _ring(self, bytes_total: float, degree: int, axis: str, factor: float) -> float:
        if degree <= 1 or bytes_total <= 0:
            return 0.0
        bw = self.topo.axis_bandwidth(axis)
        lat = self.topo.axis_latency(axis) * (degree - 1)
        return factor * (degree - 1) / degree * bytes_total / bw + lat

    def all_reduce(self, bytes_total: float, degree: int, axis: str) -> float:
        # reduce-scatter + all-gather
        return self._ring(bytes_total, degree, axis, 2.0)

    def all_gather(self, bytes_total: float, degree: int, axis: str) -> float:
        return self._ring(bytes_total, degree, axis, 1.0)

    def reduce_scatter(self, bytes_total: float, degree: int, axis: str) -> float:
        return self._ring(bytes_total, degree, axis, 1.0)

    def all_to_all(self, bytes_total: float, degree: int, axis: str) -> float:
        # each chip keeps 1/degree locally; bisection-limited on a ring
        return self._ring(bytes_total, degree, axis, 0.5)

    def ppermute(self, bytes_per_chip: float, axis: str) -> float:
        if bytes_per_chip <= 0:
            return 0.0
        return bytes_per_chip / self.topo.axis_bandwidth(axis) + self.topo.axis_latency(axis)


def compute_time(chip: TPUChip, flops: float, bytes_moved: float) -> float:
    """Roofline: compute-bound on the MXU or bandwidth-bound on HBM."""
    t_flops = flops / (chip.bf16_flops * chip.mxu_efficiency)
    t_mem = bytes_moved / (chip.hbm_bandwidth * chip.hbm_efficiency)
    return max(t_flops, t_mem)
