"""Analytic TPU machine model for the auto-parallel search.

The reference's simulator is parameterised by a machine model hierarchy —
``SimpleMachineModel`` (intra/inter-node bandwidths) up to
``NetworkedMachineModel`` with explicit topology + routing (reference
``src/runtime/machine_model.cc:1-1287``, ``network.cc:47``,
``machine_config_example``). A TPU pod is far more regular: identical
chips on a 2-D/3-D ICI torus, slices joined over DCN. So the TPU model
is a chip roofline (MXU peak, HBM bandwidth) + per-hop ICI link
bandwidth + DCN bandwidth, and collective costs follow the standard
ring/band formulas instead of weighted-shortest-path routing.

All times in seconds, sizes in bytes, rates in bytes/s or FLOP/s.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from ..core.mesh import AXIS_ORDER


@dataclasses.dataclass(frozen=True)
class TPUChip:
    """Single-chip roofline parameters."""

    name: str
    bf16_flops: float          # peak MXU FLOP/s at bf16
    hbm_bandwidth: float       # bytes/s
    hbm_capacity: float        # bytes
    ici_bandwidth: float       # bytes/s per ICI link direction
    mxu_efficiency: float = 0.55   # achievable fraction of peak on big GEMMs
    hbm_efficiency: float = 0.80

    # -- presets ------------------------------------------------------

    @classmethod
    def v5e(cls):
        return cls(
            name="v5e",
            bf16_flops=197e12,
            hbm_bandwidth=819e9,
            hbm_capacity=16e9,
            ici_bandwidth=45e9,
        )

    @classmethod
    def v5p(cls):
        return cls(
            name="v5p",
            bf16_flops=459e12,
            hbm_bandwidth=2765e9,
            hbm_capacity=95e9,
            ici_bandwidth=90e9,
        )

    @classmethod
    def v4(cls):
        return cls(
            name="v4",
            bf16_flops=275e12,
            hbm_bandwidth=1228e9,
            hbm_capacity=32e9,
            ici_bandwidth=45e9,
        )


@dataclasses.dataclass(frozen=True)
class TPUTopology:
    """A slice (ICI-connected mesh of chips) optionally multiplied over
    DCN (multi-slice). Mesh axes map onto ICI first (innermost axes) —
    matching ``core.mesh.AXIS_ORDER``'s convention that ``model`` rides
    the fastest links — and any axis marked in ``dcn_axes`` pays DCN
    bandwidth instead.

    ``torus`` is the slice's physical ICI torus shape (e.g. ``(4, 4)``
    for v5e-16): a real slice is a 2-D/3-D torus with two links per
    dimension (one per direction), not a single 1-D ring, so a
    collective over an axis laid out on the torus stripes over several
    links at once — the analog of the reference's multi-link
    ``nic_persocket``/routing model (machine_config_example:22,
    machine_model.cc). When ``torus`` is unset the model stays the
    conservative single-ring formula. ``axis_links`` pins an explicit
    per-axis link multiplicity, overriding the torus derivation."""

    chip: TPUChip
    num_chips: int = 1
    dcn_bandwidth: float = 25e9     # bytes/s per host pair
    dcn_axes: tuple = ()            # mesh axes that cross slice boundaries
    per_hop_latency: float = 1e-6   # ICI hop latency (s)
    dcn_latency: float = 10e-6
    torus: tuple = ()               # physical ICI torus shape, innermost first
    axis_links: Optional[Dict[str, int]] = None

    def axis_bandwidth(self, axis: str) -> float:
        return self.dcn_bandwidth if axis in self.dcn_axes else self.chip.ici_bandwidth

    def axis_latency(self, axis: str) -> float:
        return self.dcn_latency if axis in self.dcn_axes else self.per_hop_latency

    def axis_link_multiplicity(
        self,
        axis: str,
        degree: int = 0,
        axis_degrees: Optional[Dict[str, int]] = None,
    ) -> int:
        """How many ICI links a ring collective over ``axis`` can stripe
        across. DCN axes get 1 (one NIC path). On a physical torus, an
        axis covering k torus dimensions rides 2k links (bidirectional
        ring per dimension): a model-axis all-reduce on a v5e 4x4 slice
        is ~2x the single-ring estimate, and a whole-slice axis ~4x.

        ``axis_degrees`` (full mesh axis → degree map) places ``axis``
        on the torus correctly: mesh axes map onto ICI innermost-first
        (``core.mesh.AXIS_ORDER`` — ``model`` rides the fastest links),
        so an outer axis starts at the torus dim where the inner ICI
        axes left off. Without it every axis was assumed to start at
        torus dim 0, over-crediting outer axes on asymmetric tori (a
        data axis of 8 on a 2x8 torus rides the single size-8 dim → 2
        links, not the 4 the dim-0 walk claimed)."""
        if axis in self.dcn_axes:
            return 1
        if self.axis_links and axis in self.axis_links:
            return max(1, int(self.axis_links[axis]))
        if self.torus and degree > 1:
            start = 0
            if axis_degrees:
                # consume torus dims claimed by ICI axes INSIDE this one
                for inner in reversed(AXIS_ORDER):
                    if inner == axis:
                        break
                    d = int(axis_degrees.get(inner, 1))
                    if d <= 1 or inner in self.dcn_axes:
                        continue
                    covered = 1
                    while start < len(self.torus) and covered < d:
                        covered *= self.torus[start]
                        start += 1
            covered, dims = 1, 0
            for d in self.torus[start:]:
                if covered >= degree:
                    break
                covered *= d
                dims += 1
            return 2 * dims if dims else 1
        return 1

    @classmethod
    def from_file(cls, path: str) -> "TPUTopology":
        """Parse a user-editable machine config (the TPU analog of the
        reference's ``machine_config_example`` + ``--machine-model-file``,
        machine_model.cc:1-1287). ``key = value`` lines, ``#`` comments:

            chip = v5e            # preset: v5e | v5p | v4 | custom
            num_chips = 16
            torus = 4x4           # physical ICI torus shape
            dcn_axes = data       # comma-separated mesh axes over DCN
            # optional overrides of the chip preset / topology numbers:
            ici_bandwidth = 45e9
            mxu_efficiency = 0.55
            ...
        """
        kv: Dict[str, str] = {}
        with open(path) as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                if "=" not in line:
                    raise ValueError(f"bad machine-config line: {raw!r}")
                k, v = (s.strip() for s in line.split("=", 1))
                kv[k.lower()] = v

        presets = {"v5e": TPUChip.v5e, "v5p": TPUChip.v5p, "v4": TPUChip.v4}
        chip_name = kv.pop("chip", "v5e").lower()
        if chip_name in presets:
            chip = presets[chip_name]()
        elif chip_name == "custom":
            chip = TPUChip(
                name="custom", bf16_flops=0.0, hbm_bandwidth=0.0,
                hbm_capacity=0.0, ici_bandwidth=0.0,
            )
        else:
            raise ValueError(f"unknown chip preset {chip_name!r}")
        chip_fields = {f.name for f in dataclasses.fields(TPUChip)} - {"name"}
        chip_over = {
            k: float(kv.pop(k)) for k in list(kv) if k in chip_fields
        }
        if chip_over:
            chip = dataclasses.replace(chip, **chip_over)
        if chip_name == "custom":
            # fail at parse time, next to the file — not with a
            # ZeroDivisionError deep inside the search roofline
            missing = [
                k for k in ("bf16_flops", "hbm_bandwidth", "hbm_capacity",
                            "ici_bandwidth")
                if getattr(chip, k) <= 0
            ]
            if missing:
                raise ValueError(
                    f"chip = custom requires positive values for {missing}"
                )

        topo_kw: Dict[str, object] = {"chip": chip}
        if "num_chips" in kv:
            topo_kw["num_chips"] = int(float(kv.pop("num_chips")))
        if "torus" in kv:
            topo_kw["torus"] = tuple(
                int(x) for x in kv.pop("torus").lower().split("x")
            )
        if "dcn_axes" in kv:
            topo_kw["dcn_axes"] = tuple(
                a.strip() for a in kv.pop("dcn_axes").split(",") if a.strip()
            )
        for k in ("dcn_bandwidth", "per_hop_latency", "dcn_latency"):
            if k in kv:
                topo_kw[k] = float(kv.pop(k))
        if kv:
            raise ValueError(f"unknown machine-config keys: {sorted(kv)}")
        topo = cls(**topo_kw)
        if topo.torus and math.prod(topo.torus) != topo.num_chips:
            raise ValueError(
                f"torus {topo.torus} does not cover num_chips={topo.num_chips}"
            )
        return topo


class CollectiveModel:
    """Ring-algorithm collective cost estimates over one mesh axis.

    The reference prices its parallel ops (AllReduce/Combine/Replicate/
    Repartition/Reduction, SURVEY.md §2.1) through per-pair transfer
    routing; on TPU the GSPMD-inserted collectives follow closed-form
    ring costs over the axis's ICI links.
    """

    def __init__(self, topo: TPUTopology,
                 axis_degrees: Optional[Dict[str, int]] = None):
        self.topo = topo
        # full mesh axis → degree map (MachineSpec.axis_sizes()): places
        # each axis on the physical torus so outer axes aren't credited
        # with the inner axes' links on asymmetric tori
        self.axis_degrees = axis_degrees

    def _ring(self, bytes_total: float, degree: int, axis: str, factor: float) -> float:
        if degree <= 1 or bytes_total <= 0:
            return 0.0
        # stripe over every ICI link the axis's torus layout provides
        # (2 per covered torus dim); 1 when no torus info is available
        bw = self.topo.axis_bandwidth(axis) * self.topo.axis_link_multiplicity(
            axis, degree, self.axis_degrees
        )
        lat = self.topo.axis_latency(axis) * (degree - 1)
        return factor * (degree - 1) / degree * bytes_total / bw + lat

    def all_reduce(self, bytes_total: float, degree: int, axis: str) -> float:
        # reduce-scatter + all-gather
        return self._ring(bytes_total, degree, axis, 2.0)

    def all_gather(self, bytes_total: float, degree: int, axis: str) -> float:
        return self._ring(bytes_total, degree, axis, 1.0)

    def reduce_scatter(self, bytes_total: float, degree: int, axis: str) -> float:
        return self._ring(bytes_total, degree, axis, 1.0)

    def all_to_all(self, bytes_total: float, degree: int, axis: str) -> float:
        # each chip keeps 1/degree locally; bisection-limited on a ring
        return self._ring(bytes_total, degree, axis, 0.5)

    def ppermute(self, bytes_per_chip: float, axis: str) -> float:
        if bytes_per_chip <= 0:
            return 0.0
        return bytes_per_chip / self.topo.axis_bandwidth(axis) + self.topo.axis_latency(axis)


def compute_time(chip: TPUChip, flops: float, bytes_moved: float) -> float:
    """Roofline: compute-bound on the MXU or bandwidth-bound on HBM."""
    t_flops = flops / (chip.bf16_flops * chip.mxu_efficiency)
    t_mem = bytes_moved / (chip.hbm_bandwidth * chip.hbm_efficiency)
    return max(t_flops, t_mem)


def calibrate_chip(chip: TPUChip, *, iters: int = 5, n: int = 4096,
                   stream_mb: int = 256) -> TPUChip:
    """Replace the preset ``mxu_efficiency``/``hbm_efficiency`` guesses
    with MEASURED achieved fractions on the current default device — the
    closing of the cost-model fidelity loop the reference gets from
    ``inner_measure_operator_cost`` re-measurement (model.cu:38,
    graph.cc:2108). Two microbenchmarks:

    * MXU: a big square bf16 matmul (``n``=4096 default; ~137 GFLOP) —
      achieved FLOP/s over ``bf16_flops``;
    * HBM: an elementwise stream over ``stream_mb`` (~256 MB default,
      read + write) — achieved bytes/s over ``hbm_bandwidth``.

    The defaults saturate a real chip; smaller sizes are for smoke
    tests that only need the measurement to RUN (a CPU host measures
    the CPU — meaningless vs the TPU peaks — so callers gate on
    platform and tests only assert the clamp).

    Results clamp to [0.05, 8.0]."""
    import time

    import jax
    import jax.numpy as jnp
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n, n), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    mm(a, b).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = mm(a, b)
    out.block_until_ready()
    t_mm = (time.perf_counter() - t0) / iters
    mxu = (2.0 * n**3 / t_mm) / chip.bf16_flops

    m = stream_mb * 1024 * 1024 // 2  # bf16 elements, stream_mb bytes
    x = jax.random.normal(jax.random.fold_in(key, 2), (m,), jnp.bfloat16)
    stream = jax.jit(lambda x: x * 1.0009765625 + 1.0)
    stream(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        y = stream(x)
    y.block_until_ready()
    t_st = (time.perf_counter() - t0) / iters
    hbm = (2.0 * x.nbytes / t_st) / chip.hbm_bandwidth  # read + write

    # No 1.0 ceiling: on hardware faster than the preset (a v5p chip
    # calibrated against the v5e preset) the measured ratio legitimately
    # exceeds 1 — peak × efficiency is then the TRUE achieved rate, so
    # compute_time stays correct whatever preset was assumed. The upper
    # bound only guards timer glitches.
    clamp = lambda v: float(min(8.0, max(0.05, v)))  # noqa: E731
    return dataclasses.replace(
        chip, mxu_efficiency=clamp(mxu), hbm_efficiency=clamp(hbm)
    )
