"""Per-operator sharding-state assignment via dynamic programming.

The reference solves "optimal MachineView per op" with a DP that splits
the PCG at 2-terminal nodes into sequence/nonsequence subproblems and
memoizes (graph, sink-view) costs (reference ``SearchHelper::graph_cost``
``graph.cc:1600``, ``find_optimal_{sequence,nonsequence}_graph_time``
``graph.cc:129,281``). The TPU state space is much smaller — a handful
of sharding states per op instead of every device sub-grid — so a
forward Viterbi pass over the topological order suffices:

    dp[n][s] = op_cost(n, s) + Σ_{e=(p→n)} min_sp dp-edge(p, sp, s)

For ops with a single consumer the per-edge min is exact (chain DP =
the reference's sequence split); at fan-out nodes each consumer chooses
its preferred producer state independently, which can under-count a
producer forced to serve two states — the same approximation the
reference accepts inside its nonsequence enumeration fallback. Fan-in
re-synchronises states exactly.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..core.graph import Graph
from ..core.mesh import MachineSpec
from .simulator import CostModel, candidate_states
from .strategy import ParallelStrategy


def placement_dp(
    graph: Graph,
    cost_model: CostModel,
    mem_lambda: float = 0.0,
) -> ParallelStrategy:
    """Assign a sharding state to every op, minimising estimated step
    time (op roofline + resharding collectives). Returns the strategy
    with per-node choices and its estimated cost (before grad-sync,
    which is state-independent enough to add afterwards).

    ``mem_lambda`` ∈ [0, 1] mixes per-op memory into the objective —
    (1-λ)·time + λ·mem_time — the reference's generalized cost for its
    memory/runtime tradeoff search (memory_optimization.h MemorySearch-
    Result, graph.cc try_one_lambda). λ=0 is the pure-time DP; the
    reported ``estimated_step_time`` is always pure time."""
    machine = cost_model.machine
    # dp[node_id][state] = (best cumulative cost along the best
    # predecessor states, best predecessor-state pick per input edge)
    dp: Dict[int, Dict[str, float]] = {}
    back: Dict[int, Dict[str, Dict[int, str]]] = {}

    for node in graph.nodes:
        states = candidate_states(
            node,
            machine,
            enable_sample=cost_model.enable_sample,
            enable_attribute=cost_model.enable_attribute,
            enable_parameter=cost_model.enable_parameter,
        )
        dp[node.id] = {}
        back[node.id] = {}
        for s in states:
            cost = cost_model.op_cost(graph, node, s)
            if mem_lambda > 0.0:
                cost = (1.0 - mem_lambda) * cost + mem_lambda * (
                    cost_model.memory_time_equiv(
                        cost_model.op_memory_bytes(graph, node, s)
                    )
                )
            picks: Dict[int, str] = {}
            for ref in node.inputs:
                spec = graph.out_spec(ref)
                best_c, best_p = float("inf"), None
                for p_state, p_cost in dp[ref.node_id].items():
                    # amortise a shared producer's cost over its fan-out
                    fan = max(1, len(graph.consumers(ref.node_id)))
                    reshard = cost_model.reshard_cost(
                        graph, spec, p_state, s
                    )
                    # the edge term is pure time — weight it like the
                    # time component so λ=1 really is pure memory
                    # minimisation (else zero-reshard replicated states
                    # beat memory-minimal TP states at every λ)
                    c = p_cost / fan + (1.0 - mem_lambda) * reshard
                    if c < best_c:
                        best_c, best_p = c, p_state
                cost += best_c if best_p is not None else 0.0
                if best_p is not None:
                    picks[ref.node_id] = best_p
            dp[node.id][s] = cost
            back[node.id][s] = picks

    # Backtrack from every sink (ops with no consumers), voting on shared
    # producers; ties resolve to the most-voted state.
    choices: Dict[int, str] = {}
    votes: Dict[int, Dict[str, int]] = {}

    def vote(nid: int, state: str):
        votes.setdefault(nid, {}).setdefault(state, 0)
        votes[nid][state] += 1

    sinks = [n for n in graph.nodes if not graph.consumers(n.id)]
    total = 0.0
    stack: List[Tuple[int, str]] = []
    for sink in sinks:
        s = min(dp[sink.id], key=dp[sink.id].get)
        total += dp[sink.id][s]
        stack.append((sink.id, s))
    while stack:
        nid, s = stack.pop()
        vote(nid, s)
        for pid, p_state in back[nid][s].items():
            stack.append((pid, p_state))
    for nid, v in votes.items():
        choices[nid] = max(v, key=v.get)

    strategy = ParallelStrategy(machine=machine, choices=choices)
    # Re-price the VOTED choices with the one shared estimator (the
    # overlap-aware event simulation), whatever λ the DP optimised: the
    # DP objective is additive and optimistic at fan-outs, and λ>0
    # mixes memory in — either would make costs incomparable across
    # machine/λ candidates in unity.optimize.
    from .event_sim import event_sim_cost

    strategy.estimated_step_time = event_sim_cost(
        graph, strategy, cost_model
    )
    return strategy
