"""Unity-style auto-parallelization search, re-designed for TPU.

The reference's Unity subsystem (reference ``src/runtime/graph.cc``,
``substitution.cc``, ``simulator.cc``, ``machine_model.cc``; SURVEY.md
§2.1/L5) jointly searches algebraic graph substitutions and per-operator
MachineView placements, guided by an execution simulator. The TPU-native
re-design keeps the same three pillars but changes their meaning:

  * **machine model** → analytic TPU chip + ICI/DCN topology roofline
    (:mod:`.machine_model`) instead of measured CUDA kernels + NIC/PCIe
    graphs; optional on-device measured timings refine it.
  * **placement** → a *sharding strategy* (mesh axis degrees + per-op
    sharding choices, :mod:`.strategy`) instead of per-task device lists:
    GSPMD generates the collectives the reference inserted as parallel
    ops (Repartition/Combine/Replicate/Reduction/AllReduce).
  * **search** → substitution rewrites over the PCG IR
    (:mod:`.substitutions`) + a DP over per-op sharding states with
    resharding edge costs (:mod:`.placement`), orchestrated by
    :func:`~.unity.optimize` with an MCMC fallback — mirroring
    ``GraphSearchHelper::graph_optimize`` + ``FFModel::mcmc_optimize``.
"""
from .machine_model import TPUChip, TPUTopology, CollectiveModel
from .strategy import OpShardingChoice, ParallelStrategy
from .simulator import CostModel, estimate_graph_cost
from .event_sim import event_sim_cost
from .substitutions import SUBSTITUTIONS, apply_substitutions, Substitution
from .placement import placement_dp
from .planner import PlanReport, plan_decoder_mesh
from .unity import optimize, mcmc_optimize

__all__ = [
    "PlanReport",
    "plan_decoder_mesh",
    "TPUChip",
    "TPUTopology",
    "CollectiveModel",
    "OpShardingChoice",
    "ParallelStrategy",
    "CostModel",
    "estimate_graph_cost",
    "event_sim_cost",
    "SUBSTITUTIONS",
    "Substitution",
    "apply_substitutions",
    "placement_dp",
    "optimize",
    "mcmc_optimize",
]
