"""Execution cost simulator for the auto-parallel search.

The reference simulates a candidate strategy by timing each operator's
real CUDA kernels on device (memoized) and pricing communication through
the machine model, then event-simulating the task graph (reference
``src/runtime/simulator.cc:797``, ``Op::inner_measure_operator_cost``
``model.cu:38``). The TPU version inverts the default: the *analytic*
roofline (MXU/HBM per op + ring-collective formulas) is primary because
XLA fuses away op boundaries anyway, and an optional *measured* mode
jit-compiles a per-(op, shape, state) micro-benchmark on the real chip
to calibrate — cached aggressively, as the survey prescribes
(SURVEY.md §7 "hard parts").
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, List, Optional, Tuple

from ..core.graph import Graph, OpNode
from ..core.mesh import DATA_AXIS, MODEL_AXIS, MachineSpec
from ..ops.registry import get_op
from .machine_model import CollectiveModel, TPUChip, TPUTopology, compute_time
from .strategy import ParallelStrategy, STATES

_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int32": 4, "int8": 1}


def _nbytes(spec) -> float:
    return spec.num_elements * _BYTES.get(str(spec.dtype), 4)


def weight_bytes(graph: Graph, node: OpNode) -> float:
    """Total parameter bytes of one op (memoized via OpDef.weight_shapes)."""
    import jax

    op = get_op(node.op_type)
    in_specs = [graph.out_spec(r) for r in node.inputs]
    w = op.weight_shapes(in_specs, node.attrs_dict)
    return float(
        sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(w))
    )


# Resharding table: producer state -> consumer state -> (collective, operand)
# operand: "act" = activation bytes move over the model axis; "none" = free.
# Mirrors the parallel-op insertion the reference search performs between
# differently-viewed operators (SURVEY.md §2.1 parallel operators).
_RESHARD = {
    ("DP", "DP"): None,
    ("DP", "TP_COL"): None,            # replicated-in, col weights: free
    ("DP", "TP_ROW"): None,  # row-parallel wants feature-sharded input, and
    # every model-rank of a DP activation holds full features: a local
    # slice, no collective.
    ("TP_COL", "DP"): ("all_gather",),  # gather features back
    ("TP_COL", "TP_ROW"): None,         # Megatron pair: col feeds row directly
    ("TP_COL", "TP_COL"): ("all_gather",),
    ("TP_ROW", "DP"): ("all_reduce",),  # unreduced partial sums
    ("TP_ROW", "TP_COL"): ("all_reduce",),
    ("TP_ROW", "TP_ROW"): ("all_reduce",),
    ("REP", "DP"): None,
    ("DP", "REP"): ("all_gather_batch",),
    ("REP", "REP"): None,
    ("REP", "TP_COL"): None,
    ("REP", "TP_ROW"): None,
    ("TP_COL", "REP"): ("all_gather",),
    ("TP_ROW", "REP"): ("all_reduce",),
}

# TP states each op type actually implements in its weight_pspecs (only
# states the strategy can materialise may be priced — otherwise the search
# picks shardings that silently never happen).
_TP_STATES = {
    "dense": ("TP_COL", "TP_ROW"),
    "embedding": ("TP_COL",),
    "multihead_attention": ("TP_COL", "TP_ROW"),  # both stamp tp_shard=heads
    # fused decoder stack: full Megatron layout inside the op (col QKV/up,
    # row O/down, GSPMD all-reduces priced via internal_collectives)
    "transformer_decoder_stack": ("TP_MEGATRON",),
}
_ANY = ("REP", "DP")

# Ops that implement the PARAM (ZeRO-style weight-sharding) state in
# their weight_pspecs (tp_shard="param").
_PARAM_OK = {"dense", "embedding"}

# Ops whose batch dim can split past the data axis (sample parallelism)
# and whose dim-1 attribute can split over model (attribute parallelism)
# — weight-free / elementwise-ish ops where replicated weights make the
# extra split free (reference enable_sample/attribute_parallel,
# config.h:160-162).
_SAMPLE_OK = {
    "element_unary", "element_binary", "dropout", "softmax", "flat",
    "reshape", "concat", "split", "pool2d", "batch_norm", "layer_norm",
    "rms_norm", "cast", "transpose", "reduce",
}


def _tp_state_valid(node: OpNode, state: str, model: int) -> bool:
    """A TP state may only be offered when the op's sharded dims divide
    the model degree — otherwise the searched strategy crashes at param
    init (same gate the explicit-TP pass applies, parallel/tp.py)."""
    attrs = node.attrs_dict
    if state == "TP_MEGATRON":
        kv = attrs.get("num_kv_heads") or attrs["num_heads"]
        return kv % model == 0 and attrs["intermediate_size"] % model == 0
    if node.op_type == "dense":
        # TP_COL shards out_dim; TP_ROW shards in_dim (not visible from
        # the node alone — out_dim divisibility is the usable proxy;
        # GSPMD tolerates a ragged in_dim split, unlike a ragged named
        # sharding of the weight's out axis)
        return attrs["out_dim"] % model == 0
    if node.op_type == "multihead_attention":
        return attrs["num_heads"] % model == 0
    if node.op_type == "embedding":
        return attrs["out_dim"] % model == 0
    return True


def candidate_states(
    node: OpNode,
    machine: MachineSpec,
    *,
    enable_sample: bool = True,
    enable_attribute: bool = True,
    enable_parameter: bool = True,
) -> Tuple[str, ...]:
    if node.op_type == "input":
        return ("DP",) if machine.data > 1 else ("REP",)
    states = _ANY
    if (
        enable_parameter
        and machine.data > 1
        and node.op_type in _PARAM_OK
    ):
        states = states + ("PARAM",)
    if machine.model > 1:
        if node.op_type in _TP_STATES:
            states = states + tuple(
                s
                for s in _TP_STATES[node.op_type]
                if _tp_state_valid(node, s, machine.model)
            )
        if node.op_type in _SAMPLE_OK:
            if enable_sample:
                states = states + ("SAMPLE",)
            rank = len(node.out_specs[0].shape) if node.out_specs else 2
            if enable_attribute and rank >= 3:
                states = states + ("ATTR",)
    return states


@dataclasses.dataclass
class CostModel:
    topo: TPUTopology
    machine: MachineSpec
    training: bool = True
    # measured-mode memo: (op_type, attrs, shapes, state) -> seconds
    measured: Optional[Dict] = None
    # reference --enable-sample/attribute/parameter-parallel
    # (config.h:160-162)
    enable_sample: bool = True
    enable_attribute: bool = True
    enable_parameter: bool = True

    def __post_init__(self):
        # the machine's axis degrees place each mesh axis on the torus
        # (outer axes start where inner ICI axes left off)
        self.coll = CollectiveModel(self.topo, self.machine.axis_sizes())

    # ------------------------------------------------------------------

    def op_cost(self, graph: Graph, node: OpNode, state: str) -> float:
        """Time for one execution of ``node`` under ``state`` on this
        machine (fwd, or fwd+bwd when training — the reference times both,
        simulator.cc forward_time+backward_time)."""
        if node.op_type == "input":
            return 0.0
        op = get_op(node.op_type)
        in_specs = [graph.out_spec(r) for r in node.inputs]
        flops = float(op.flops(in_specs, node.attrs_dict))
        bytes_moved = sum(_nbytes(s) for s in in_specs) + sum(
            _nbytes(s) for s in node.out_specs
        )
        if self.training:
            flops *= 3.0  # fwd + ~2x bwd
            bytes_moved *= 2.0
        # work divides over the axes this state shards
        div = 1
        if state in ("DP", "TP_COL", "TP_ROW", "TP_MEGATRON", "PARAM",
                     "SAMPLE", "ATTR"):
            div *= self.machine.data
        if state in ("TP_COL", "TP_ROW", "TP_MEGATRON", "SAMPLE", "ATTR"):
            div *= self.machine.model
        # expert parallelism: MoE expert compute splits over the expert
        # axis (reference experts_start_idx/num_experts range sharding)
        if self.machine.expert > 1 and node.op_type in (
            "moe", "experts", "group_by", "aggregate"
        ):
            div *= self.machine.expert
        t = None
        measured_state = False
        if self.measured:
            mult = 3.0 if self.training else 1.0
            shapes = tuple(s.shape for s in in_specs)
            # exact state measurement wins; else scale the measured
            # unsharded forward (reference inner_measure_operator_cost
            # memo) by the shard division and fwd+bwd multiplier
            state_key = (node.op_type, node.attrs, shapes, state)
            base_key = (node.op_type, node.attrs, shapes, "REP")
            if state_key in self.measured:
                t = self.measured[state_key] * mult
                # a per-state measurement taken on real multi-device
                # hardware already includes the state's internal
                # collectives — adding _internal_comm_cost on top would
                # double-count them (calibrate() only writes REP keys,
                # but externally supplied measured dicts carry state keys)
                measured_state = True
            elif base_key in self.measured:
                t = self.measured[base_key] * mult / div
        if t is None:
            t = compute_time(self.topo.chip, flops / div, bytes_moved / div)
        if measured_state:
            # a state-keyed end-to-end measurement already paid ALL of
            # its state's collectives — internal comm AND PARAM gathers
            return t
        # single-device estimates never include the multi-device
        # collectives a sharded state implies — price them on top
        t += self._internal_comm_cost(node, in_specs, state)
        if state == "PARAM" and self.machine.data > 1:
            # ZeRO-style weight all-gathers: one per forward and — since
            # params are never persisted gathered — one more for the
            # backward. (The grad reduce-scatter replaces the DP grad
            # all-reduce and is priced in grad_sync_cost.) Without the
            # backward gather PARAM would price exactly like DP and the
            # search would be time-indifferent between them.
            gathers = 2.0 if self.training else 1.0
            t += gathers * self.coll.all_gather(
                weight_bytes(graph, node), self.machine.data, DATA_AXIS
            )
        return t

    def _internal_comm_cost(self, node: OpNode, in_specs, state: str) -> float:
        """Collectives GSPMD inserts *inside* one op under this state
        (fused ops declare them via OpDef.internal_collectives) — e.g.
        the per-layer Megatron all-reduces of a fused decoder stack."""
        op = get_op(node.op_type)
        fn = getattr(op, "internal_collectives", None)
        if fn is None or self.machine.model <= 1:
            return 0.0
        total = 0.0
        for kind, nbytes in fn(in_specs, node.attrs_dict, state, self.training):
            if self.machine.data > 1:
                nbytes /= self.machine.data
            total += getattr(self.coll, kind)(
                nbytes, self.machine.model, MODEL_AXIS
            )
        return total

    def calibrate(
        self, graph: Graph, iters: int = 3, cache_path: Optional[str] = None
    ) -> int:
        """Measure every op's unsharded forward on the current device
        (memoized across calls) so op_cost scales real times instead of
        roofline estimates — the reference's measured simulator mode.
        Returns the number of ops calibrated.

        ``cache_path``: persist measurements to a JSON file and reuse
        them across processes — on TPU each per-(op, shape) timing
        costs a compile (SURVEY §7 hard parts: "cache aggressively"),
        so recompiles and repeated searches must not re-pay it. The
        file holds a nested {device_kind: {mode: {key: secs}}} map, so
        heterogeneous environments sharing one path coexist instead of
        evicting each other, and training-mode forwards (dropout,
        batch-stats) never masquerade as inference timings. Any corrupt
        or wrong-shaped file is treated as empty."""
        import json
        import os

        if self.measured is None:
            self.measured = {}
        disk: Dict[str, float] = {}
        full: Dict = {}
        dev_kind = ""
        mode = "training" if self.training else "inference"
        if cache_path:
            import jax

            dev_kind = jax.devices()[0].device_kind
            try:
                with open(cache_path) as f:
                    raw = json.load(f)
                full = raw if isinstance(raw, dict) else {}
            except Exception:
                full = {}
            try:
                disk = {
                    k: float(v)
                    for k, v in full.get(dev_kind, {}).get(mode, {}).items()
                    if isinstance(v, (int, float))
                }
            except Exception:
                # malformed inner shape: re-measure this (kind, mode)
                # but keep the rest of the file intact on write
                disk = {}
        n = 0
        dirty = False
        for node in graph.nodes:
            if node.op_type == "input":
                continue
            in_specs = [graph.out_spec(r) for r in node.inputs]
            key = (
                node.op_type, node.attrs,
                tuple(s.shape for s in in_specs), "REP",
            )
            rkey = repr(key)
            if key not in self.measured and rkey in disk:
                self.measured[key] = disk[rkey]
                n += 1
                continue
            try:
                t = self.measure_op(graph, node, "REP", iters=iters)
                n += 1
            except Exception:
                continue
            if cache_path and disk.get(rkey) != t:
                disk[rkey] = float(t)
                dirty = True
        if cache_path and dirty:
            if not isinstance(full.get(dev_kind), dict):
                full[dev_kind] = {}
            full[dev_kind][mode] = disk
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(full, f)
            os.replace(tmp, cache_path)
        return n

    def reshard_cost(
        self, graph: Graph, edge_spec, producer_state: str, consumer_state: str
    ) -> float:
        """Collective cost of moving one activation between two op
        sharding states (the priced equivalents of the reference's
        Repartition/Combine/Replicate/Reduction/AllReduce nodes)."""
        # TP_MEGATRON's and PARAM's boundary activations are
        # batch-sharded full-feature tensors — exactly a DP edge
        if producer_state in ("TP_MEGATRON", "PARAM"):
            producer_state = "DP"
        if consumer_state in ("TP_MEGATRON", "PARAM"):
            consumer_state = "DP"
        if producer_state == consumer_state:
            rule = _RESHARD.get((producer_state, consumer_state))
        elif (producer_state, consumer_state) in _RESHARD:
            rule = _RESHARD[(producer_state, consumer_state)]
        else:
            # SAMPLE/ATTR transitions: a batch/attribute repartition over
            # the model axis — priced as an all-to-all-sized gather of
            # the per-shard activation (GSPMD materialises a collective
            # whenever the model-axis layout changes).
            moves = {"SAMPLE", "ATTR", "TP_COL"}
            if producer_state in moves or consumer_state in moves:
                rule = ("model_resplit",)
            else:
                rule = None
        if rule is None:
            return 0.0
        act_bytes = _nbytes(edge_spec)
        if self.machine.data > 1:
            act_bytes /= self.machine.data  # per-data-shard activation
        kind = rule[0]
        if kind == "all_reduce":
            return self.coll.all_reduce(act_bytes, self.machine.model, MODEL_AXIS)
        if kind == "all_gather":
            return self.coll.all_gather(act_bytes, self.machine.model, MODEL_AXIS)
        if kind == "all_gather_batch":
            return self.coll.all_gather(
                act_bytes * self.machine.data, self.machine.data, DATA_AXIS
            )
        if kind == "model_resplit":
            # per-shard slice exchanged across the model axis
            return self.coll.all_gather(
                act_bytes / max(1, self.machine.model),
                self.machine.model,
                MODEL_AXIS,
            )
        return 0.0

    # ------------------------------------------------------------------
    # memory model (reference memory_optimization.cc MemoryUsage +
    # graph.cc:2132-2190 try_one_lambda / perform_memory_search)

    # Bytes of optimizer + gradient state per parameter byte: grads (1x)
    # + Adam m/v in f32 (2 leaves x fp32/param-dtype ratio ~2 for bf16
    # params). Conservative for SGD; the search only needs an upper
    # bound that scales with the right sharding.
    opt_state_mult: float = 3.0

    def op_memory_bytes(self, graph: Graph, node: OpNode, state: str) -> float:
        """Per-device HBM bytes attributable to one op under ``state``:
        parameters (+grads+optimizer state when training) + activations
        saved for the backward pass. Weights shard over ``model`` in TP
        states and over ``data`` in PARAM (DP replicates them);
        activations shard over whatever the state shards."""
        if node.op_type == "input":
            return 0.0
        w = weight_bytes(graph, node)
        if state in ("TP_COL", "TP_ROW", "TP_MEGATRON"):
            w /= self.machine.model
        elif state == "PARAM":
            w /= self.machine.data  # ZeRO: params+grads+opt all shard
        if self.training:
            w *= 1.0 + self.opt_state_mult
        op = get_op(node.op_type)
        in_specs = [graph.out_spec(r) for r in node.inputs]
        act_fn = getattr(op, "activation_bytes", None)
        if act_fn is not None:
            act = float(act_fn(in_specs, node.attrs_dict, self.training))
        else:
            act = float(sum(_nbytes(s) for s in node.out_specs))
        div = 1
        if state in ("DP", "TP_COL", "TP_ROW", "TP_MEGATRON", "PARAM",
                     "SAMPLE", "ATTR"):
            div *= self.machine.data
        if state in ("SAMPLE", "ATTR", "TP_COL"):
            div *= self.machine.model
        return w + act / div

    def strategy_memory_bytes(
        self, graph: Graph, strategy: ParallelStrategy
    ) -> float:
        """Per-device byte estimate for a whole strategy. Activations are
        summed (the interpreted training graph keeps every intermediate
        live for backward; fused ops report their remat footprint via
        OpDef.activation_bytes)."""
        return sum(
            self.op_memory_bytes(
                graph, node, strategy.choices.get(node.id, "DP")
            )
            for node in graph.nodes
        )

    def memory_time_equiv(self, nbytes: float) -> float:
        """Convert bytes to a time-dimensioned quantity so the memory
        term can mix with step time in a (1-λ)·time + λ·mem objective
        (the reference's generalized cost, memory_optimization.h)."""
        return nbytes / (self.topo.chip.hbm_bandwidth * self.topo.chip.hbm_efficiency)

    def grad_sync_cost(self, graph: Graph, strategy: ParallelStrategy) -> float:
        """Per-step DP gradient all-reduce over replicated weights
        (reference: NCCL optimizer path, optimizer_kernel.cu:88)."""
        if not self.training or self.machine.data <= 1:
            return 0.0
        total = 0.0
        for node in graph.nodes:
            if node.op_type == "input":
                continue
            nbytes = weight_bytes(graph, node)
            state = strategy.choices.get(node.id, "DP")
            if state in ("TP_COL", "TP_ROW", "TP_MEGATRON"):
                nbytes /= self.machine.model  # sharded grads all-reduce less
            elif state == "PARAM":
                # ZeRO grads reduce-scatter (half an all-reduce): fold
                # the factor into the byte count of the shared ring
                nbytes /= 2.0
            total += nbytes
        return self.coll.all_reduce(total, self.machine.data, DATA_AXIS)

    # ------------------------------------------------------------------
    # measured mode (reference inner_measure_operator_cost, model.cu:38)

    def measure_op(self, graph: Graph, node: OpNode, state: str, iters: int = 5):
        """Time the op's jitted forward on the current default device and
        memoize. Used to calibrate the analytic model on real hardware.

        Only ``state="REP"`` may be measured here: this times the
        UNSHARDED forward on one device, and op_cost scales REP entries
        by the shard division and prices collectives analytically on
        top. Non-REP keys in ``measured`` are reserved for externally
        supplied END-TO-END per-device times (real multi-device runs,
        collectives included) — op_cost uses those verbatim."""
        assert state == "REP", (
            "measure_op times an unsharded single-device forward; "
            f"storing it under state {state!r} would be misread as an "
            "end-to-end sharded measurement (see op_cost)"
        )
        import jax
        import jax.numpy as jnp

        from ..ops.registry import OpContext, get_op

        if self.measured is None:
            self.measured = {}
        op = get_op(node.op_type)
        in_specs = [graph.out_spec(r) for r in node.inputs]
        key = (node.op_type, node.attrs, tuple(s.shape for s in in_specs), state)
        if key in self.measured:
            return self.measured[key]
        kk = jax.random.PRNGKey(0)
        weights = op.init(kk, in_specs, node.attrs_dict)
        inputs = [
            jax.random.normal(jax.random.fold_in(kk, i), s.shape, jnp.float32)
            for i, s in enumerate(in_specs)
        ]
        ctx = OpContext(training=self.training)
        fn = jax.jit(
            lambda w, xs: op.forward(w, xs, node.attrs_dict, ctx)
        )
        out = fn(weights, inputs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(weights, inputs)
        jax.block_until_ready(out)
        t = (time.perf_counter() - t0) / iters
        self.measured[key] = t
        return t


def estimate_graph_cost(
    graph: Graph,
    strategy: ParallelStrategy,
    cost_model: CostModel,
) -> float:
    """Total estimated step time of ``graph`` under ``strategy`` — the
    analog of ``Simulator::simulate_runtime`` (simulator.cc:797), with
    XLA overlap approximated by straight summation (conservative)."""
    total = 0.0
    for node in graph.nodes:
        state = strategy.choices.get(node.id, "DP")
        total += cost_model.op_cost(graph, node, state)
        for ref in node.inputs:
            pstate = strategy.choices.get(ref.node_id, "DP")
            total += cost_model.reshard_cost(
                graph, graph.out_spec(ref), pstate, state
            )
    total += cost_model.grad_sync_cost(graph, strategy)
    return total
