"""RequestManager — request queue + continuous batching + decoding loops.

TPU-native counterpart of the reference ``RequestManager`` (reference
``src/runtime/request_manager.cc:1-2435``): tokenize + queue incoming
requests, admit them into free batch slots, build per-step BatchConfigs
(``prepare_next_batch``, :350), run the incremental-decoding loop
(``generate_incr_decoding``, :2292), track per-request profiling, and
free slots on completion.

Scheduling is **iteration-level continuous batching**: prompt processing
is *chunked prefill* (a prompt enters the batch in fixed-size chunks so
prefill and decode share one program shape), and — with
``ServingConfig.continuous_batching`` (the default) — prefill chunks
ride in the SAME dispatch-ahead pipelined step as decode rows. One
jitted *mixed step* carries every decode row's single token plus up to
``max_tokens_per_step`` new prompt tokens, samples on device for decode
rows AND prefill-final rows, and feeds the sampled tokens to the next
dispatch without a host round-trip. Admissions, chunk progression and
completions therefore never drain the pipeline; host-side token append
is deferred to drain (flush) time, ``dispatch_ahead`` steps behind the
device. ``continuous_batching=False`` restores the flush-on-admit
scheduler (any PREFILLING request forces the blocking sync path) — the
bench baseline.
"""
from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..logging_utils import get_logger
from ..metrics import SchedulerStats
from ..obs.tracer import NULL_TRACER
from .batch_config import (
    BatchConfig,
    GenerationConfig,
    GenerationResult,
    ProfileInfo,
    StreamEvent,
)
from .engine import InferenceEngine
from .sampling import choose_sample_mode, sample_tokens


class RequestStatus(enum.Enum):
    PENDING = "pending"
    PREFILLING = "prefilling"
    DECODING = "decoding"
    COMPLETED = "completed"
    # Terminal failure: the request can never be served under the
    # configured limits (e.g. its prompt alone exceeds the KV budget).
    # Surfaced via GenerationResult.error instead of live-locking the
    # scheduler or crashing unrelated requests.
    ERROR = "error"


TERMINAL_STATUSES = (RequestStatus.COMPLETED, RequestStatus.ERROR)


@dataclasses.dataclass
class Request:
    """reference ``Request`` (request_manager.h:92-278)."""

    request_id: int
    prompt: str
    tokens: List[int]                 # prompt + generated so far
    prompt_len: int
    gen: GenerationConfig
    status: RequestStatus = RequestStatus.PENDING
    slot: int = -1
    n_cached: int = 0                 # tokens whose K/V commit was flushed
    n_sched: int = 0                  # prompt tokens dispatched (may run
    # ahead of n_cached while prefill chunks are in flight)
    inflight: int = 0                 # dispatched sampling steps not yet
    # fetched (decode rows + the prefill-final chunk)
    pipeline_refs: int = 0            # in-flight dispatches touching this
    # request's slot — the slot (and its pages) may only be released once
    # this drains to 0, or later garbage writes from already-dispatched
    # steps would scribble on a reassigned slot/page
    admit_seq: int = -1               # admission order (preemption victims
    # are chosen newest-first, vLLM-style recompute preemption)
    error: Optional[str] = None
    profile: ProfileInfo = dataclasses.field(default_factory=ProfileInfo)

    @property
    def output_tokens(self) -> List[int]:
        return self.tokens[self.prompt_len :]


class RequestManager:
    # Subclasses that keep a second engine's cache in sync (SpecInfer)
    # must not use the LLM-only fast decode pipeline.
    supports_fast_decode = True
    # Automatic prefix caching (serve/prefix_cache.py). Managers that
    # mirror slot state across engines (SpecInfer) maintain ONE radix
    # tree per page pool and keep the matched lengths aligned through
    # the _cache_attach/_cache_insert hooks — the SSM pools page
    # independently but share the token offset math.
    supports_prefix_cache = True
    # The "sampling" decode fusion's sync path (engine.run_sampled)
    # bypasses the _run_batch hook; managers that override _run_batch
    # to keep a second engine in sync (SpecInfer) opt out and keep the
    # two-dispatch step + host sample.
    supports_fused_sampling = True

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        output_file: Optional[str] = None,
    ):
        self.engine = engine
        if engine.serving.inference_debugging and getattr(
            engine.model, "serve_debug_activations", None
        ) is not None:
            # the dump hook lives in engine.run(): the dispatch-ahead
            # fused decode pipeline bypasses it, so debugging forces
            # every step through the sync path (triage mode is allowed
            # to be slow — the reference's inference_debugging is too).
            # A model without the hook keeps fast decode: nothing could
            # be dumped anyway (the engine logs a loud warning instead
            # of silently paying the slowdown, ADVICE.md round 5).
            self.supports_fast_decode = False
        self.tokenizer = tokenizer
        self.eos_token_id = eos_token_id
        # Per-request telemetry sink (reference -output-file,
        # request_manager.cc:417-440: e2e latency, decoding steps and
        # token ids appended per finished request).
        self.output_file = output_file
        if eos_token_id is None and tokenizer is not None:
            self.eos_token_id = getattr(tokenizer, "eos_token_id", None)
        self.requests: Dict[int, Request] = {}
        self.pending: List[int] = []
        self.slots: List[Optional[int]] = [None] * engine.num_slots
        # Request ids whose slot + pages must SURVIVE completion: the
        # cluster's prefill→decode migration (serve/cluster/) reads the
        # finished prefill's pages out of the pool after the request
        # completes — releasing them at _finish would hand the pages to
        # the next admission before they were shipped. The holder calls
        # :meth:`release_held` once the pages have migrated.
        self.hold_finished: set = set()
        self._next_id = 1000000  # reference starts guids at 1000000
        self._admit_counter = 0
        self._key = jax.random.PRNGKey(seed)
        self._step_counter = 0
        # Dispatch-ahead pipeline (reference's 4-deep batch-future
        # queue, request_manager.cc:2310-2325): entries are
        # (device_tokens, [(rid, slot, ntoks, samples), ...])
        # oldest-first; ``ntoks`` is the row's cache lines this dispatch
        # wrote, ``samples`` whether its sampled token is meaningful
        # (decode rows and prefill-final rows).
        self._inflight: List[tuple] = []
        # Slots whose sampled token in the NEWEST dispatch is their next
        # input (device feedback instead of a host token).
        self._prev_dispatch_slots: set = set()
        self.stats = SchedulerStats()
        self._log = get_logger("serve")
        # Observability (flexflow_tpu/obs): request-lifecycle tracing +
        # failure flight recorder. Disabled by default — every emission
        # site below guards on ``tracer.enabled`` (one attribute read)
        # before building any event, so a no-obs run does no extra
        # per-step host work (tests/test_observability.py proves it).
        # obs.attach_observability wires a live tracer in; the engine
        # shares it so dispatch events land on the same lane.
        self.tracer = NULL_TRACER
        self.flight_recorder = None
        # rid -> cluster-wide trace id (bound at submission; local runs
        # fall back to the rid itself — see trace_of)
        self._trace_ids: Dict[int, int] = {}
        # Retrace sentinel telemetry (analysis/retrace.py): compile
        # events recorded at the engine's jit chokepoint surface in the
        # scheduler stats (FF_LOG=serve=debug + bench reports). The
        # callable indirection survives bench-style stat swaps
        # (rm.stats = SchedulerStats()) the same way the prefix cache's
        # stats hook does.
        guard = getattr(engine, "retrace_guard", None)
        if guard is not None:
            guard.stats_cb = lambda: self.stats
        # Automatic prefix caching (paged layout only — on dense,
        # prefix_caching=True is a documented passthrough: there are no
        # pages to share). The radix tree owns one reference per cached
        # page; the allocator's reclaim hook evicts idle cached pages
        # before any allocation fails.
        self.prefix_cache = None
        sc = engine.serving
        if (
            self.supports_prefix_cache
            and sc.prefix_caching
            and getattr(engine, "paged", False)
        ):
            from .prefix_cache import PrefixCache

            # Hierarchical KV cache: with a host_cache_bytes budget the
            # cache SPILLS cold pages to host RAM instead of evicting
            # (async D2H via engine.fetch_page; re-admitted with an
            # async H2D upload on a later match). The spill handles are
            # harvested to numpy at flush time — the scheduler's
            # existing sync point — so the decode loop never blocks on
            # a transfer.
            host_kw = {}
            if sc.host_cache_bytes:
                host_kw = dict(
                    fetch_page=engine.fetch_page,
                    upload_page=engine.upload_page,
                    host_cache_bytes=sc.host_cache_bytes,
                    page_bytes=engine.page_host_bytes(),
                )
            self.prefix_cache = PrefixCache(
                engine.pager,
                copy_page=engine.copy_page,
                policy=sc.cache_policy,
                stats=lambda: self.stats,
                **host_kw,
            )
            engine.pager.reclaim_cb = self.prefix_cache.reclaim

    # ------------------------------------------------------------------
    # registration (reference register_new_request, request_manager.cc:137)

    def register_request(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
    ) -> int:
        gen = gen or GenerationConfig()
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt requires a tokenizer")
            tokens = list(self.tokenizer.encode(prompt))
            text = prompt
        else:
            tokens = [int(t) for t in prompt]
            text = ""
        if not tokens:
            raise ValueError("empty prompt")
        max_len = self.engine.serving.max_sequence_length
        if len(tokens) >= max_len:
            tokens = tokens[: max_len - 1]
        rid = self._next_id
        self._next_id += 1
        req = Request(
            request_id=rid,
            prompt=text,
            tokens=list(tokens),
            prompt_len=len(tokens),
            gen=gen,
        )
        req.profile.start_time = time.perf_counter()
        self.requests[rid] = req
        self.pending.append(rid)
        return rid

    def submit(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        trace_id: Optional[int] = None,
    ) -> int:
        """Non-blocking submission: queue one request and return its id
        immediately. Drive the scheduler with :meth:`step` (or a
        concurrent :meth:`generate_stream`/:meth:`generate` call) and
        read tokens from ``requests[rid]`` / :meth:`result` as they
        drain. ``trace_id`` binds a cluster-wide trace id so this
        request's spans stitch with its router/migration/other-replica
        spans (obs/tracer.py); local rids are their own trace ids."""
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        rid = self.register_request(prompt, gen)
        if trace_id is not None:
            self._trace_ids[rid] = int(trace_id)
        return rid

    def bind_trace(self, rid: int, trace_id: int) -> None:
        """Bind ``rid``'s spans to a cluster-wide trace id (submission
        and migration adoption call this — see obs/__init__.py)."""
        self._trace_ids[int(rid)] = int(trace_id)

    def trace_of(self, rid: int) -> int:
        """The trace id this request's spans carry: the bound
        cluster-wide id, else the rid itself (single-engine runs)."""
        return self._trace_ids.get(rid, rid)

    # ------------------------------------------------------------------
    # cluster hooks (serve/cluster/): hold-for-migration + adoption of
    # an externally prefilled request

    def hold_on_finish(self, rid: int) -> None:
        """Mark ``rid`` so completion does NOT release its slot/pages —
        the prefill→decode migration reads them from the pool after the
        request finishes. Pair with :meth:`release_held`."""
        self.hold_finished.add(rid)

    def release_held(self, rid: int) -> None:
        """Release the slot + pages of a finished held request (the
        migration shipped its pages, or the hold is abandoned)."""
        self.hold_finished.discard(rid)
        req = self.requests.get(rid)
        if (
            req is not None
            and req.status in TERMINAL_STATUSES
            and req.slot >= 0
            and req.pipeline_refs == 0
        ):
            self._release_slot(req)

    def adopt_prefilled(
        self,
        tokens: Sequence[int],
        prompt_len: int,
        gen: GenerationConfig,
        *,
        profile: Optional[ProfileInfo] = None,
        prompt_text: str = "",
        trace_id: Optional[int] = None,
    ) -> Optional[int]:
        """Admit an EXTERNALLY prefilled request straight into DECODING
        (cluster prefill→decode migration, serve/cluster/migration.py):
        ``tokens`` is prompt + the first sampled output token, and cache
        lines [0, prompt_len) are about to be filled by page uploads
        into the slot this method allocates. Returns the new request id,
        or None when no slot (or no pages) can be had right now — the
        caller keeps the request on its source replica and retries.
        All-or-nothing: a page-allocation failure rolls the slot back."""
        assert len(tokens) > prompt_len, "adopt needs the first output token"
        slot = next(
            (i for i, occ in enumerate(self.slots) if occ is None), None
        )
        if slot is None:
            return None
        if self._paged:
            for eng in self._engines():
                if not eng.pager.ensure(slot, prompt_len):
                    self._release_pages(slot)
                    return None
        rid = self._next_id
        self._next_id += 1
        req = Request(
            request_id=rid,
            prompt=prompt_text,
            tokens=[int(t) for t in tokens],
            prompt_len=int(prompt_len),
            gen=gen,
        )
        req.slot = slot
        req.status = RequestStatus.DECODING
        req.n_cached = int(prompt_len)
        req.n_sched = int(prompt_len)
        req.admit_seq = self._admit_counter
        self._admit_counter += 1
        if profile is not None:
            req.profile = profile
        req.profile.context_shards = getattr(self.engine, "cp_shards", 1)
        self.requests[rid] = req
        self.slots[slot] = rid
        self.stats.admitted += 1
        if trace_id is not None:
            self._trace_ids[rid] = int(trace_id)
        tr = self.tracer
        if tr.enabled:
            tr.event(
                "adopt", trace_id=self.trace_of(rid), rid=rid, slot=slot,
                prompt_len=int(prompt_len),
            )
        return rid

    def rollback_adopt(self, rid: int) -> None:
        """Undo :meth:`adopt_prefilled` before any step ran — the
        migration failed AFTER adoption (a page gather/upload raised),
        so the destination must release the slot + pages it granted and
        forget the request entirely: the source still holds the
        original, and a half-adopted ghost would leak its pages and
        double-count the admission."""
        req = self.requests.pop(rid)
        assert req.status is RequestStatus.DECODING, (
            f"rollback_adopt of request {rid} in state {req.status}"
        )
        assert req.pipeline_refs == 0 and req.n_cached == req.prompt_len, (
            "rollback_adopt after the adopted request already stepped"
        )
        if req.slot >= 0:
            if self._paged:
                self._release_pages(req.slot)
            self.slots[req.slot] = None
        self.stats.admitted -= 1

    # ------------------------------------------------------------------
    # paged-KV page management (serve/paging.py PageAllocator; one
    # allocator per engine — a SpecInfer LLM/SSM pair allocates
    # independently but the tables evolve in lockstep because slot
    # assignment and serving limits are shared)

    @property
    def _paged(self) -> bool:
        return getattr(self.engine, "paged", False)

    def _engines(self):
        """Every engine whose cache this manager keeps in sync
        (SpecInferManager adds its SSMs)."""
        return [self.engine]

    def _prefix_caches(self):
        """Every prefix cache this manager maintains (SpecInferManager
        adds one radix tree per SSM pool)."""
        return [] if self.prefix_cache is None else [self.prefix_cache]

    def _cache_attach(self, slot: int, tokens: Sequence[int]) -> int:
        """Hook: admission-time prefix-cache attach. SpecInferManager
        overrides it to attach the SAME matched length on the LLM pool
        and every SSM pool (or none at all) — a prefix the engines do
        not jump past together would desync verification."""
        return self.prefix_cache.attach(slot, tokens)

    def _cache_insert(self, slot: int, tokens: Sequence[int],
                      valid: int) -> None:
        """Hook: publish a slot's blocks into every maintained radix
        tree (SpecInferManager inserts into the SSM trees too — their
        pools hold the same tokens' K/V at the same lines, paged
        independently)."""
        for cache in self._prefix_caches():
            cache.insert(slot, tokens, valid)

    def _mirror_dispatch(self, last, host_tokens, use_last, positions,
                         logits_idx, key, greedy, temperature, topp,
                         topk) -> None:
        """Hook: managers that keep secondary engines' caches in sync
        (SpecInfer SSM mirrors) dispatch the SAME pipelined mixed step
        there — identical token selection (the LLM's previous sampled
        tokens feed ``use_last`` rows), identical positions, so every
        cache advances in lockstep without a host round-trip. The base
        manager has no secondary engines: no-op."""

    def _ensure_pages(self, req: Request, num_lines: int) -> bool:
        """Cover cache lines [0, num_lines) for ``req`` on every engine.
        All-or-nothing per engine; a partial cross-engine success is
        resolved by the caller's preemption retry (``ensure`` is
        idempotent on the engines that already granted)."""
        for eng in self._engines():
            if not eng.pager.ensure(req.slot, num_lines):
                return False
        return True

    def _release_pages(self, slot: int):
        for eng in self._engines():
            eng.pager.release(slot)

    def _preempt(self, req: Request):
        """Evict an admitted request back to the front of the pending
        queue, reclaiming its pages everywhere. Its prefix is recomputed
        on re-admission (prompt + tokens generated so far re-prefill —
        vLLM-style recompute preemption), so generation continues
        exactly where it stopped. Only called with the pipeline drained
        (pipeline_refs == 0), so no in-flight dispatch can scribble on
        the reclaimed pages."""
        assert req.pipeline_refs == 0, "preempting a request with work in flight"
        self._release_pages(req.slot)
        self.slots[req.slot] = None
        req.slot = -1
        req.status = RequestStatus.PENDING
        req.n_cached = 0
        req.n_sched = 0
        req.inflight = 0
        self.pending.insert(0, req.request_id)
        self.stats.preemptions += 1
        tr = self.tracer
        if tr.enabled:
            tr.event("preempt", trace_id=self.trace_of(req.request_id),
                     rid=req.request_id)

    def _lines_needed(self, req: Request, chunk: Optional[int] = None) -> int:
        """Conservative cache-line bound the next step may touch."""
        if req.status is RequestStatus.PREFILLING:
            chunk = chunk or self.engine.serving.prefill_chunk
            return min(
                len(req.tokens),
                max(req.n_cached, req.n_sched) + chunk,
            )
        # decode: reads lines [0, len-1], writes len-1 (+ dispatch-ahead
        # steps in flight advance the write line without a host sync)
        return len(req.tokens) + req.inflight + 1

    def _reserve_active_pages(self, lines_fn=None):
        """Grow every active slot's page table to cover this step's
        reads/writes; on pool exhaustion, preempt the newest admission
        (reference eviction order) and retry. A single request that
        alone exceeds the pool can never be served — it fails with an
        ERROR status (surfaced in its GenerationResult) instead of
        crashing the scheduler and every healthy request with it."""
        if not self._paged:
            return
        lines_fn = lines_fn or self._lines_needed
        while True:
            active = sorted(
                (
                    self.requests[rid]
                    for rid in self.slots
                    if rid is not None
                    and self.requests[rid].status
                    in (RequestStatus.PREFILLING, RequestStatus.DECODING)
                ),
                key=lambda r: r.admit_seq,
            )
            for req in active:
                if self._ensure_pages(req, lines_fn(req)):
                    continue
                # free in-flight state before touching slot ownership;
                # flushed completions may already release enough pages
                self._flush_all()
                if req.status not in (
                    RequestStatus.PREFILLING, RequestStatus.DECODING
                ) or self._ensure_pages(req, lines_fn(req)):
                    break  # flush resolved it; re-derive the active set
                victims = [
                    r for r in active
                    if r is not req
                    and r.status
                    in (RequestStatus.PREFILLING, RequestStatus.DECODING)
                ]
                if not victims:
                    self._fail_request(
                        req,
                        "KV page pool exhausted by this request alone — "
                        "raise ServingConfig.max_cached_tokens (or lower "
                        "max_sequence_length/page_size)",
                    )
                    break  # active set changed; re-derive
                self._preempt(victims[-1])
                break  # active set changed; re-derive
            else:
                return

    def _attach_paging_metadata(self, bc: BatchConfig):
        """Record the page table + ragged lengths on the batch
        descriptor (the engine dispatches with its own authoritative
        table; this is telemetry/testing metadata)."""
        if not self._paged:
            return
        bc.page_table = self.engine.pager.table.copy()
        seq_lens = np.zeros((self.engine.num_slots,), np.int32)
        for rid in self.slots:
            if rid is None:
                continue
            req = self.requests[rid]
            if req.status is RequestStatus.PREFILLING:
                seq_lens[req.slot] = min(
                    len(req.tokens),
                    req.n_cached + self.engine.serving.prefill_chunk,
                )
            elif req.status is RequestStatus.DECODING:
                seq_lens[req.slot] = len(req.tokens)
        bc.seq_lens = seq_lens

    # ------------------------------------------------------------------
    # slot management

    def _admission_error(self, req: Request) -> Optional[str]:
        """A reason this request can NEVER be admitted under the
        configured limits, or None. Without this check such a request
        either live-locks ``generate()`` (``step()`` keeps returning
        True with the request parked in ``pending``) or eventually
        preempts every healthy request before dying."""
        sc = self.engine.serving
        need = len(req.tokens) + 1  # prompt lines + the first output's line
        if need > sc.cache_len + 1:
            return (
                f"prompt ({len(req.tokens)} tokens) exceeds the cache "
                f"capacity ({sc.cache_len} lines)"
            )
        if self._paged:
            # with kv_quant the max_cached_tokens budget is an HBM
            # budget that buys ~2x the pages, and under kv_shard=
            # "context" it is a PER-SHARD budget the striped layout
            # multiplies — in both cases the allocator's actual
            # capacity (checked below) is the authoritative bound, and
            # the raw token figure would wrongly reject servable prompts
            if (
                sc.max_cached_tokens is not None
                and sc.kv_quant is None
                and sc.kv_shard != "context"
                and need > sc.max_cached_tokens
            ):
                return (
                    f"prompt ({len(req.tokens)} tokens) can never fit the "
                    f"configured KV budget (max_cached_tokens="
                    f"{sc.max_cached_tokens})"
                )
            for eng in self._engines():
                cap = eng.pager.num_pages * eng.pager.page_size
                if need > cap:
                    return (
                        f"prompt ({len(req.tokens)} tokens) exceeds the "
                        f"KV page pool ({cap} tokens)"
                    )
                cp = getattr(eng, "cp_shards", 1)
                if cp > 1:
                    # context parallelism: admission goes PER SHARD —
                    # logical page j lives on shard j % n, so every
                    # shard must cover its striped share of the prompt
                    # out of its own budget (max_cached_tokens prices
                    # ONE shard; the allocator itself is clamped to the
                    # worst case so the budget is enforced here, the
                    # same split as the single-pool raw-token check)
                    budget = getattr(eng, "cp_budget_pages_per_shard",
                                     None)
                    need_per_shard = -(-eng.pager.pages_for(need) // cp)
                    if budget is not None and need_per_shard > budget:
                        return (
                            f"prompt ({len(req.tokens)} tokens) can "
                            f"never fit the per-shard KV budget: its "
                            f"striped share is {need_per_shard} pages/"
                            f"shard vs a budget of {budget} "
                            f"(max_cached_tokens="
                            f"{sc.max_cached_tokens} per shard × "
                            f"{cp} context shards) — raise the budget "
                            "or context_shards"
                        )
                    if not eng.pager.can_ever_fit(need):
                        per = eng.pager.pages_per_shard
                        return (
                            f"prompt ({len(req.tokens)} tokens) "
                            f"exceeds the per-shard page pool ({per} "
                            f"pages/shard × {cp} context shards)"
                        )
        return None

    def _admit_pending(self):
        for i, occupant in enumerate(self.slots):
            if occupant is not None:
                continue
            # fail-fast unservable heads instead of parking them forever
            while self.pending:
                head = self.requests[self.pending[0]]
                err = self._admission_error(head)
                if err is None:
                    break
                self.pending.pop(0)
                self._fail_request(head, err)
            if not self.pending:
                return
            rid = self.pending[0]
            req = self.requests[rid]
            req.slot = i
            # Prefix-cache hit path: splice cached prompt pages into the
            # (empty) slot table and jump prefill past them — the mixed/
            # sync steps then only chunk the uncached suffix. A rolled-
            # back admission releases the spliced references with the
            # slot, so retrying is clean.
            matched = 0
            host_before = self.stats.host_hit_tokens
            if self.prefix_cache is not None:
                matched = self._cache_attach(i, req.tokens)
            if self._paged and not self._ensure_pages(
                req,
                min(
                    len(req.tokens),
                    matched + self.engine.serving.prefill_chunk,
                ),
            ):
                # pool cannot take the first chunk: stop admitting (a
                # flush will free pages; the request stays queued) and
                # roll back any partial cross-engine grant
                self._release_pages(i)
                req.slot = -1
                return
            self.pending.pop(0)
            req.status = RequestStatus.PREFILLING
            req.n_cached = matched
            req.n_sched = matched
            req.inflight = 0
            req.pipeline_refs = 0
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            req.profile.cached_prefix_len = matched
            req.profile.context_shards = getattr(self.engine, "cp_shards", 1)
            # tokens of this prefix that came back from the HOST tier
            # (the stats counter moved inside attach's re-admissions)
            req.profile.host_hit_tokens = (
                self.stats.host_hit_tokens - host_before
            )
            if self.prefix_cache is not None:
                if matched:
                    self.stats.prefix_hits += 1
                    self.stats.prefix_hit_tokens += matched
                else:
                    self.stats.prefix_misses += 1
            self.slots[i] = rid
            self.stats.admitted += 1
            tr = self.tracer
            if tr.enabled:
                tid = self.trace_of(rid)
                if self.prefix_cache is not None:
                    tr.event("prefix_lookup", trace_id=tid, rid=rid,
                             matched=matched)
                tr.event(
                    "admit", trace_id=tid, rid=rid, slot=i,
                    prompt_len=req.prompt_len, cached_prefix=matched,
                )

    def _active(self, status: RequestStatus) -> List[Request]:
        out = []
        for rid in self.slots:
            if rid is None:
                continue
            r = self.requests[rid]
            if r.status is status:
                out.append(r)
        return out

    def _release_slot(self, req: Request):
        """Return the request's slot (and pages) to the free pool.
        Callers must guarantee no in-flight dispatch still references
        the slot (pipeline_refs == 0)."""
        if req.slot < 0:
            return
        if self._paged:
            self._release_pages(req.slot)
        self.slots[req.slot] = None
        req.slot = -1

    def _finish(self, req: Request, error: Optional[str] = None):
        req.status = RequestStatus.ERROR if error else RequestStatus.COMPLETED
        req.error = error
        req.profile.finish_time = time.perf_counter()
        tr = self.tracer
        if tr.enabled:
            tr.event(
                "terminal", trace_id=self.trace_of(req.request_id),
                rid=req.request_id, status=req.status.value,
                error=(error or "")[:200],
            )
        if error and self.flight_recorder is not None:
            # terminal request errors are a flight-recorder trigger
            # (obs/flight_recorder.py): dump this lane's recent ring
            self.flight_recorder.dump(
                self.tracer.lane, "request_error",
                step=self._step_counter,
                extra={"rid": req.request_id, "error": error[:500]},
            )
        if (
            self.prefix_cache is not None
            and error is None
            and req.slot >= 0
            and self.prefix_cache.policy == "complete"
        ):
            # Publish the finished sequence's blocks (prompt + generated
            # — the next conversation turn extends this transcript).
            # Only lines written on device are valid: the final sampled
            # token's K/V never was (it would have been the next step's
            # input), so the insertable prefix ends one short.
            self._cache_insert(req.slot, req.tokens, len(req.tokens) - 1)
        # With dispatches still in flight for this slot, defer the
        # release to the flush that drains the last of them: those
        # dispatches keep writing (garbage) K/V through the page table
        # they were launched with, so reallocating the pages or the slot
        # now would corrupt whoever received them. Held requests
        # (cluster migration sources) keep slot + pages until
        # :meth:`release_held`.
        if (
            req.slot >= 0
            and req.pipeline_refs == 0
            and req.request_id not in self.hold_finished
        ):
            self._release_slot(req)
        if self.output_file and error is None:
            self._write_output_record(req)

    def _fail_request(self, req: Request, reason: str):
        self.stats.failed += 1
        self._log.warning("request %d failed: %s", req.request_id, reason)
        if req.request_id in self.pending:
            self.pending.remove(req.request_id)
        self._finish(req, error=reason)

    def _write_output_record(self, req: Request):
        """Append one finished request's telemetry — the format mirrors
        the reference's output-file writer (request_manager.cc:417-440:
        ``[Profile] guid(%d) llm_decoding_steps(%d) start(%.1lf)
        finish(%.1lf) latency(%.1lf)`` then the token ids)."""
        p = req.profile
        latency_us = (p.finish_time - p.start_time) * 1e6
        text = (
            self.tokenizer.decode(req.output_tokens)
            if self.tokenizer is not None
            else ""
        )
        with open(self.output_file, "a") as f:
            f.write(
                f"[Profile] guid({req.request_id}) "
                f"llm_decoding_steps({p.llm_decoding_steps}) "
                f"latency({latency_us:.1f})\n"
            )
            f.write(
                f"guid({req.request_id}) tokens("
                + " ".join(str(t) for t in req.tokens)
                + f") output({text})\n"
            )

    # ------------------------------------------------------------------
    # batch building (reference prepare_next_batch, request_manager.cc:350)

    def _fill_prefill_row(self, bc: BatchConfig, req: Request, chunk: int):
        off = req.n_cached
        toks = req.tokens[off : off + chunk]
        n = len(toks)
        bc.tokens[req.slot, :n] = toks
        bc.positions[req.slot, :n] = np.arange(off, off + n)
        bc.active[req.slot] = True
        bc.logits_idx[req.slot] = n - 1
        if bc.qlens is not None:
            bc.qlens[req.slot] = n
        if bc.prefill_offsets is not None:
            bc.prefill_offsets[req.slot] = off

    def _prepare_batch(self) -> Optional[BatchConfig]:
        """Build one blocking mixed prefill+decode batch (the sync
        path). Decoding slots always contribute their one pending token,
        so decode never stalls behind a long prompt's prefill (no
        head-of-line blocking); the chunk is 1 when nobody is
        prefilling."""
        prefilling = self._active(RequestStatus.PREFILLING)
        decoding = self._active(RequestStatus.DECODING)
        if not prefilling and not decoding:
            return None
        sc = self.engine.serving
        chunk = sc.prefill_chunk if prefilling else 1
        bc = BatchConfig.empty(self.engine.num_slots, chunk, self.engine.scratch_pos)
        bc.qlens = np.zeros((self.engine.num_slots,), np.int32)
        bc.prefill_offsets = np.zeros((self.engine.num_slots,), np.int32)
        for req in prefilling:
            self._fill_prefill_row(bc, req, chunk)
        for req in decoding:
            bc.tokens[req.slot, 0] = req.tokens[-1]
            bc.positions[req.slot, 0] = len(req.tokens) - 1
            bc.active[req.slot] = True
            bc.logits_idx[req.slot] = 0
            bc.qlens[req.slot] = 1
        self._attach_paging_metadata(bc)
        return bc

    # ------------------------------------------------------------------
    # sampling glue

    def _decode_head_params(self, reqs: Sequence[Request]):
        """Per-slot decode-head arrays for ``reqs`` (greedy/temperature/
        top-k/top-p; top-p >= 1 and top-k <= 0 disable the filters)."""
        R = self.engine.num_slots
        greedy = np.ones((R,), bool)
        temp = np.ones((R,), np.float32)
        topp = np.full((R,), 2.0, np.float32)  # disabled
        topk = np.zeros((R,), np.int32)        # disabled
        for req in reqs:
            greedy[req.slot] = not req.gen.do_sample
            temp[req.slot] = req.gen.temperature
            topp[req.slot] = req.gen.topp if req.gen.do_sample else 2.0
            topk[req.slot] = req.gen.topk if req.gen.do_sample else 0
        return greedy, temp, topp, topk

    def _sample(self, logits) -> np.ndarray:
        """Sample one token per slot from (R, V) logits using each slot's
        GenerationConfig (mixed greedy/sampling in one program). The
        head is mode-specialized host-side (serve/sampling.py): a
        greedy-only batch — the common decode case — skips the (R, V)
        sorts entirely, bitwise-identically."""
        greedy, temp, topp, topk = self._decode_head_params(
            [self.requests[r] for r in self.slots if r is not None]
        )
        mode, cap = choose_sample_mode(
            greedy, topp, topk, self.engine.cfg.vocab_size
        )
        self._key, sub = jax.random.split(self._key)
        toks = sample_tokens(
            logits,
            sub,
            greedy=jnp.asarray(greedy, dtype=jnp.bool_),
            temperature=jnp.asarray(temp, dtype=jnp.float32),
            topp=jnp.asarray(topp, dtype=jnp.float32),
            topk_arr=jnp.asarray(topk, dtype=jnp.int32),
            mode=mode,
            topk_cap=cap,
        )
        # the host-side decode head is its own dispatched program — the
        # figure the fused sampling epilogue's one-program step beats
        self.engine.count_dispatch("host_sample")
        # ffcheck: disable=FF107 -- blocking sync-scheduler decode head: this path trades latency for simplicity by design (the pipelined path samples on device)
        return np.asarray(jax.device_get(toks))

    def _append_token(self, req: Request, token: int):
        if len(req.tokens) == req.prompt_len and not req.profile.first_token_time:
            # the request's first generated token, as the host observes
            # it (TTFT the way a streaming client would measure it)
            req.profile.first_token_time = time.perf_counter()
            tr = self.tracer
            if tr.enabled:
                tr.event("first_token",
                         trace_id=self.trace_of(req.request_id),
                         rid=req.request_id)
        req.tokens.append(int(token))
        gen_len = len(req.tokens) - req.prompt_len
        eos = self.eos_token_id
        max_total = self.engine.serving.max_sequence_length
        stops = set(req.gen.stop_token_ids)
        if eos is not None:
            stops.add(eos)
        if (
            (int(token) in stops)
            or gen_len >= req.gen.max_new_tokens
            or len(req.tokens) >= max_total
        ):
            self._finish(req)

    # ------------------------------------------------------------------
    # incremental decoding loop (reference generate_incr_decoding, :2292)

    def _run_batch(self, bc: BatchConfig):
        """Hook: run one prepared batch through the engine(s).
        SpecInferManager overrides this to keep the SSM cache in sync."""
        return self.engine.run(bc)

    # ------------------------------------------------------------------
    # dispatch-ahead pipeline (reference request_manager.cc:2310)

    def _sched_exhausted(self, req: Request) -> bool:
        """Everything this request will ever need is already dispatched
        — scheduling more rows would only compute garbage (its
        completion lands at a pending flush)."""
        gen_dispatched = len(req.tokens) - req.prompt_len + req.inflight
        return (
            gen_dispatched >= req.gen.max_new_tokens
            or len(req.tokens) + req.inflight
            >= self.engine.serving.max_sequence_length
        )

    def _dispatch_decode(self, decoding: List[Request]):
        """Dispatch one fused decode step WITHOUT waiting for the
        previous one: decode rows that sampled in the previous dispatch
        take their input token from the on-device sampled tokens; rows
        entering the pipeline take it from host state. Positions advance
        deterministically, so no host sync is needed."""
        R = self.engine.num_slots
        scratch = self.engine.scratch_pos
        host_tokens = np.zeros((R, 1), np.int32)
        use_last = np.zeros((R,), bool)
        positions = np.full((R, 1), scratch, np.int32)
        greedy, temp, topp, topk = self._decode_head_params(decoding)
        snapshot = []
        last = self._inflight[-1][0] if self._inflight else None
        for req in decoding:
            s = req.slot
            positions[s, 0] = len(req.tokens) - 1 + req.inflight
            if s in self._prev_dispatch_slots and last is not None:
                use_last[s] = True
            else:
                host_tokens[s, 0] = req.tokens[-1]
            req.inflight += 1
            req.pipeline_refs += 1
            snapshot.append((req.request_id, s, 1, True))
        if last is None:
            last = jnp.zeros((R,), jnp.int32)
        self._key, sub = jax.random.split(self._key)
        t0 = time.perf_counter()
        toks = self.engine.run_decode(
            last, host_tokens, use_last, positions, sub, greedy, temp, topp,
            topk,
        )
        # decode_step_ms (bench serve_megakernel; ROADMAP 5b): the
        # engine call's host wall time — dispatch cost on this
        # pipelined path (the device runs ahead; no sync is added)
        self.stats.note_decode_step_ms((time.perf_counter() - t0) * 1e3)
        self._mirror_dispatch(
            last, host_tokens, use_last, positions,
            np.zeros((R,), np.int32), sub, greedy, temp, topp, topk,
        )
        self._inflight.append((toks, snapshot))
        self._prev_dispatch_slots = {s for _, s, _, _ in snapshot}
        self._step_counter += 1
        self.stats.record_step(
            "decode", active_slots=len(decoding), num_slots=R,
            decode_tokens=len(decoding),
        )
        tr = self.tracer
        if tr.enabled:
            tr.event("decode_step", rows=len(decoding))
        self._maybe_log_stats()

    def _dispatch_mixed(self, prefilling: List[Request],
                        decoding: List[Request]):
        """Dispatch one pipelined MIXED step: every decode row's single
        token plus chunked prefill under the per-step token budget, in
        ONE (R, mixed_chunk) ragged dispatch through the shared step
        (paged layouts go through ``ragged_paged_attention`` via the
        per-row query lengths — padding columns sit at the scratch
        position). Prefill rows whose final chunk is in this dispatch
        transition to DECODING immediately: their sampled token is on
        device, so the next iteration schedules them as decode rows fed
        by device feedback — an admission never costs a pipeline
        drain."""
        eng = self.engine
        sc = eng.serving
        R = eng.num_slots
        C = sc.mixed_chunk
        bc = BatchConfig.empty(R, C, eng.scratch_pos)
        bc.qlens = np.zeros((R,), np.int32)
        bc.prefill_offsets = np.zeros((R,), np.int32)
        use_last = np.zeros((R,), bool)
        snapshot = []
        sampled_slots = set()
        last = self._inflight[-1][0] if self._inflight else None
        greedy, temp, topp, topk = self._decode_head_params(
            list(decoding) + list(prefilling)
        )
        for req in decoding:
            s = req.slot
            bc.positions[s, 0] = len(req.tokens) - 1 + req.inflight
            if s in self._prev_dispatch_slots and last is not None:
                use_last[s] = True
            else:
                bc.tokens[s, 0] = req.tokens[-1]
            bc.logits_idx[s] = 0
            bc.active[s] = True
            bc.qlens[s] = 1
            req.inflight += 1
            req.pipeline_refs += 1
            snapshot.append((req.request_id, s, 1, True))
            sampled_slots.add(s)
        spent = 0
        tr = self.tracer
        for req in sorted(prefilling, key=lambda r: r.admit_seq):
            n = min(C, len(req.tokens) - req.n_sched)
            if n <= 0:
                continue
            s = req.slot
            off = req.n_sched
            bc.tokens[s, :n] = req.tokens[off : off + n]
            bc.positions[s, :n] = np.arange(off, off + n)
            bc.logits_idx[s] = n - 1
            bc.active[s] = True
            bc.qlens[s] = n
            bc.prefill_offsets[s] = off
            final = off + n >= len(req.tokens)
            req.n_sched += n
            req.pipeline_refs += 1
            spent += n
            if final:
                # prompt fully dispatched: this step samples the first
                # output token on device — decode from the next step on
                req.status = RequestStatus.DECODING
                req.inflight += 1
                sampled_slots.add(s)
                if (
                    self.prefix_cache is not None
                    and self.prefix_cache.policy == "prefill"
                ):
                    # every prompt line's write is dispatched — publish
                    # the prompt now so concurrent same-prefix
                    # admissions hit before this request even finishes
                    self._cache_insert(
                        s, req.tokens[: req.prompt_len], req.prompt_len
                    )
            snapshot.append((req.request_id, s, n, final))
            if tr.enabled:
                tr.event(
                    "prefill_chunk",
                    trace_id=self.trace_of(req.request_id),
                    rid=req.request_id, n=n, offset=off, final=final,
                )
        if last is None:
            last = jnp.zeros((R,), jnp.int32)
        self._key, sub = jax.random.split(self._key)
        toks = eng.run_mixed(
            last, bc.tokens, use_last, bc.positions, bc.logits_idx,
            sub, greedy, temp, topp, topk,
        )
        self._mirror_dispatch(
            last, bc.tokens, use_last, bc.positions, bc.logits_idx,
            sub, greedy, temp, topp, topk,
        )
        self._inflight.append((toks, snapshot))
        self._prev_dispatch_slots = sampled_slots
        self._step_counter += 1
        self.stats.record_step(
            "mixed", active_slots=int(bc.active.sum()), num_slots=R,
            prefill_tokens=spent, decode_tokens=len(decoding),
            budget=C * max(1, len(prefilling)),
        )
        if tr.enabled:
            tr.event(
                "mixed_step", prefill_tokens=spent,
                decode_rows=len(decoding),
            )
        self._maybe_log_stats()

    def _flush_one(self):
        """Fetch the oldest in-flight step's tokens and do the deferred
        host bookkeeping: advance each row's committed-line count, and
        for sampling rows append the token (EOS/length checks). A
        request finished by an earlier flush skips the bookkeeping but
        still drains its pipeline refs — its slot/pages are released at
        the flush that drains the last reference."""
        toks, snapshot = self._inflight.pop(0)
        # ffcheck: disable=FF107 -- the pipeline flush IS the designed sync point: it drains steps the device already finished, dispatch_ahead steps behind
        toks = np.asarray(jax.device_get(toks))
        self.stats.flushes += 1
        tr = self.tracer
        if tr.enabled:
            tr.event("flush", entries=len(snapshot))
        for rid, slot, ntoks, samples in snapshot:
            req = self.requests.get(rid)
            if req is None:
                continue
            req.pipeline_refs = max(0, req.pipeline_refs - 1)
            if samples:
                req.inflight = max(0, req.inflight - 1)
            alive = (
                req.status
                in (RequestStatus.PREFILLING, RequestStatus.DECODING)
                and req.slot == slot
            )
            if alive:
                req.n_cached += ntoks
                if samples:
                    req.profile.llm_decoding_steps += 1
                    self._append_token(req, toks[slot])
            if (
                req.status in TERMINAL_STATUSES
                and req.slot == slot
                and req.pipeline_refs == 0
                and req.request_id not in self.hold_finished
            ):
                self._release_slot(req)
        # the flush just blocked on device_get — every async spill
        # copy enqueued before it has landed; convert the handles
        # to host buffers and release their device memory
        for cache in self._prefix_caches():
            cache.harvest()

    def _flush_all(self):
        if self._inflight:
            self.stats.pipeline_drains += 1
        while self._inflight:
            self._flush_one()
        self._prev_dispatch_slots = set()

    def drain(self):
        """Flush every in-flight dispatch: appends all outstanding
        tokens and releases slots/pages held by finished requests whose
        tail dispatches were still in the pipeline."""
        self._flush_all()

    def _trim_pipeline(self):
        depth = max(1, self.engine.serving.dispatch_ahead)
        while len(self._inflight) >= depth:
            self._flush_one()

    def _slots_reclaimable(self) -> bool:
        """Some slot is held by a request that only needs flushes to
        leave: already terminal (zombie refs in flight) or with its
        whole generation budget dispatched."""
        for rid in self.slots:
            if rid is None:
                continue
            req = self.requests[rid]
            if req.status in TERMINAL_STATUSES:
                # held slots (cluster migration sources) only leave via
                # release_held — flushing cannot reclaim them
                if rid not in self.hold_finished:
                    return True
                continue
            if (
                req.status is RequestStatus.DECODING
                and self._sched_exhausted(req)
            ):
                return True
        return False

    def _reclaim_slots_for_admission(self):
        """Under saturation (pending queue non-empty, no free slot),
        flush ahead of the dispatch_ahead cadence to reclaim slots held
        by finished/fully-dispatched requests. Flushing drains steps the
        device has already computed (it runs up to ``dispatch_ahead``
        ahead), so this trades a little pipeline depth for slot
        occupancy — the right trade whenever admissions are waiting;
        without it a completion holds its slot for up to dispatch_ahead
        extra iterations and effective concurrency sags."""
        if not self.pending or any(s is None for s in self.slots):
            return
        while (
            self._inflight
            and self.pending
            and not any(s is None for s in self.slots)
            and self._slots_reclaimable()
        ):
            self._flush_one()
        self._admit_pending()

    def _maybe_log_stats(self):
        # context-parallel telemetry, refreshed per dispatched step so
        # bench-style stat swaps (rm.stats = SchedulerStats()) keep the
        # gauges: shard degree, ring hops a sequence-sharded mesh pays
        # per attention read, and the striping balance of the pool.
        cp = getattr(self.engine, "cp_shards", 1)
        if cp > 1 and self._paged:
            self.stats.cp_shards = cp
            self.stats.ring_steps += cp - 1
            self.stats.shard_balance = self.engine.pager.shard_balance()
        # whole-step VMEM gate telemetry (engine._whole_step_vmem_gate)
        # mirrored the same way, so BENCH_r*.json and the Prometheus
        # scrape track when the walk is actually taken vs fallen back
        self.stats.whole_step_fallbacks = getattr(
            self.engine, "whole_step_fallbacks", 0
        )
        self.stats.whole_step_vmem_est = getattr(
            self.engine, "whole_step_vmem_est", 0
        )
        if self._step_counter % 200 == 0:
            self._log.debug("%s", self.stats.report())

    # ------------------------------------------------------------------

    def step(self) -> bool:
        """One scheduling step. Returns False when no work remains.

        Fast managers run everything through the dispatch-ahead
        pipeline: pure-decode iterations through the fused C==1 step,
        and — with ``continuous_batching`` — iterations with PREFILLING
        slots through the fused mixed step, so admissions and chunk
        progression never drain the pipeline. The blocking sync path
        remains for SpecInfer/triage managers, for the flush-on-admit
        baseline scheduler, and as the idle drain."""
        self._admit_pending()
        sc = self.engine.serving
        if self.supports_fast_decode:
            self._reclaim_slots_for_admission()
            prefilling = self._active(RequestStatus.PREFILLING)
            decoding = self._active(RequestStatus.DECODING)
            if decoding and not prefilling:
                self._reserve_active_pages()
                return self._step_pipelined(mixed=False)
            if sc.continuous_batching and (prefilling or decoding):
                self._reserve_active_pages(
                    lambda r: self._lines_needed(r, sc.mixed_chunk)
                )
                return self._step_pipelined(mixed=True)
        # Sync path (SpecInfer/triage managers; prefill under the
        # flush-on-admit baseline; idle drain): blocking host round trip.
        return self._step_sync()

    def _step_pipelined(self, mixed: bool) -> bool:
        # page reservation may have preempted or failed requests —
        # re-derive the schedulable set
        prefilling = self._active(RequestStatus.PREFILLING) if mixed else []
        decoding = [
            r for r in self._active(RequestStatus.DECODING)
            if not self._sched_exhausted(r)
        ]
        if prefilling:
            self._dispatch_mixed(prefilling, decoding)
        elif decoding:
            self._dispatch_decode(decoding)
        elif self._inflight:
            # every row is fully dispatched: make flush progress so the
            # pending completions land
            self._flush_one()
            return True
        else:
            return bool(self.pending)
        self._trim_pipeline()
        return True

    def _step_sync(self) -> bool:
        self._flush_all()
        self._reserve_active_pages()
        bc = self._prepare_batch()
        if bc is None:
            return bool(self.pending)
        prefilling = self._active(RequestStatus.PREFILLING)
        decoding = self._active(RequestStatus.DECODING)
        decode_only = bool(decoding) and not prefilling
        t0 = time.perf_counter()
        fused = self.engine.serving.fused_decode
        if (
            ("sampling" in fused or "whole_step" in fused)
            and self.supports_fused_sampling
        ):
            # fused sampling epilogue: ONE dispatched program per sync
            # step (step + on-device decode head) instead of two — the
            # (R, V) logits never reach the host. Same single key split
            # per step as the unfused path, so generations are bitwise
            # identical.
            greedy, temp, topp, topk = self._decode_head_params(
                [self.requests[r] for r in self.slots if r is not None]
            )
            self._key, sub = jax.random.split(self._key)
            toks = self.engine.run_sampled(bc, sub, greedy, temp, topp, topk)
            # ffcheck: disable=FF107 -- blocking sync scheduler: one fetch per step by design
            sampled = np.asarray(jax.device_get(toks))
        else:
            logits = self._run_batch(bc)
            sampled = self._sample(logits)
        if decode_only:
            # decode_step_ms, sync path: the full blocking step wall
            # time (dispatch + fetch — this path syncs by design)
            self.stats.note_decode_step_ms(
                (time.perf_counter() - t0) * 1e3
            )
        for req in decoding:
            req.n_cached += 1
            req.n_sched = req.n_cached
            req.profile.llm_decoding_steps += 1
            self._append_token(req, sampled[req.slot])
        for req in prefilling:
            n = int(bc.logits_idx[req.slot]) + 1  # tokens cached this chunk
            req.n_cached += n
            req.n_sched = req.n_cached
            if req.n_cached >= len(req.tokens):
                # prompt fully cached: first output token sampled now
                req.status = RequestStatus.DECODING
                if (
                    self.prefix_cache is not None
                    and self.prefix_cache.policy == "prefill"
                ):
                    self._cache_insert(
                        req.slot, req.tokens[: req.prompt_len],
                        req.prompt_len,
                    )
                req.profile.llm_decoding_steps += 1
                self._append_token(req, sampled[req.slot])
        self._step_counter += 1
        self.stats.record_step(
            "sync",
            active_slots=len(prefilling) + len(decoding),
            num_slots=self.engine.num_slots,
            prefill_tokens=int(
                sum(bc.qlens[r.slot] for r in prefilling)
            ) if prefilling else 0,
            decode_tokens=len(decoding),
        )
        tr = self.tracer
        if tr.enabled:
            tr.event(
                "sync_step", prefill_rows=len(prefilling),
                decode_rows=len(decoding),
            )
        self._maybe_log_stats()
        return True

    # ------------------------------------------------------------------
    # blocking + streaming frontends

    def result(self, rid: int) -> GenerationResult:
        """Build the GenerationResult for a (terminal or in-flight)
        request."""
        req = self.requests[rid]
        out = req.output_tokens
        text = (
            self.tokenizer.decode(out) if self.tokenizer is not None else ""
        )
        return GenerationResult(
            request_id=rid,
            prompt=req.prompt,
            input_tokens=req.tokens[: req.prompt_len],
            output_tokens=list(out),
            output_text=text,
            profile=req.profile,
            error=req.error,
        )

    def generate(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
    ) -> List[GenerationResult]:
        """Blocking generate over a batch of prompts (reference
        ``FFModel::generate`` → ``generate_incr_decoding``)."""
        if isinstance(prompts, str):
            prompts = [prompts]
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        rids = [self.register_request(p, gen) for p in prompts]
        while any(
            self.requests[r].status not in TERMINAL_STATUSES for r in rids
        ):
            if not self.step():
                break
        # the tail of the pipeline may still hold finished requests'
        # dispatches (and their slots/pages)
        self.drain()
        return [self.result(rid) for rid in rids]

    def generate_stream(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
    ) -> Iterator[StreamEvent]:
        """Streaming generate: yields a :class:`StreamEvent` per token
        the moment the pipeline drains it to the host (tokens arrive up
        to ``dispatch_ahead`` steps behind the device), then one
        terminal event per request (``done=True``; ``error`` set if the
        request failed). Interleaves arbitrarily across requests."""
        if isinstance(prompts, str):
            prompts = [prompts]
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        rids = [self.register_request(p, gen) for p in prompts]
        sent = {r: 0 for r in rids}
        finished: set = set()

        def drain_events():
            for r in rids:
                if r in finished:
                    continue
                req = self.requests[r]
                out = req.output_tokens
                while sent[r] < len(out):
                    tok = out[sent[r]]
                    sent[r] += 1
                    yield StreamEvent(r, int(tok))
                if req.status in TERMINAL_STATUSES:
                    finished.add(r)
                    yield StreamEvent(r, None, done=True, error=req.error)

        while len(finished) < len(rids):
            progressed = self.step()
            yield from drain_events()
            if not progressed and len(finished) < len(rids):
                self.drain()
                yield from drain_events()
                if len(finished) < len(rids):
                    break  # nothing schedulable remains — avoid spinning
        self.drain()
        yield from drain_events()
