"""RequestManager — request queue + continuous batching + decoding loops.

TPU-native counterpart of the reference ``RequestManager`` (reference
``src/runtime/request_manager.cc:1-2435``): tokenize + queue incoming
requests, admit them into free batch slots, build per-step BatchConfigs
(``prepare_next_batch``, :350), run the incremental-decoding loop
(``generate_incr_decoding``, :2292), track per-request profiling, and
free slots on completion. Prompt processing is *chunked prefill*: a
prompt enters the batch in fixed-size chunks so prefill and decode share
one program shape per mode and new arrivals join without a full-batch
retrace (the reference's equivalent is padding to MAX_NUM_TOKENS).
"""
from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .batch_config import (
    BatchConfig,
    GenerationConfig,
    GenerationResult,
    ProfileInfo,
)
from .engine import InferenceEngine
from .sampling import sample_tokens


class RequestStatus(enum.Enum):
    PENDING = "pending"
    PREFILLING = "prefilling"
    DECODING = "decoding"
    COMPLETED = "completed"


@dataclasses.dataclass
class Request:
    """reference ``Request`` (request_manager.h:92-278)."""

    request_id: int
    prompt: str
    tokens: List[int]                 # prompt + generated so far
    prompt_len: int
    gen: GenerationConfig
    status: RequestStatus = RequestStatus.PENDING
    slot: int = -1
    n_cached: int = 0                 # tokens whose K/V are in the cache
    inflight: int = 0                 # dispatched decode steps not yet fetched
    admit_seq: int = -1               # admission order (preemption victims
    # are chosen newest-first, vLLM-style recompute preemption)
    profile: ProfileInfo = dataclasses.field(default_factory=ProfileInfo)

    @property
    def output_tokens(self) -> List[int]:
        return self.tokens[self.prompt_len :]


class RequestManager:
    # Subclasses that keep a second engine's cache in sync (SpecInfer)
    # must not use the LLM-only fast decode pipeline.
    supports_fast_decode = True

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        output_file: Optional[str] = None,
    ):
        self.engine = engine
        if engine.serving.inference_debugging and getattr(
            engine.model, "serve_debug_activations", None
        ) is not None:
            # the dump hook lives in engine.run(): the dispatch-ahead
            # fused decode pipeline bypasses it, so debugging forces
            # every step through the sync path (triage mode is allowed
            # to be slow — the reference's inference_debugging is too).
            # A model without the hook keeps fast decode: nothing could
            # be dumped anyway (the engine logs a loud warning instead
            # of silently paying the slowdown, ADVICE.md round 5).
            self.supports_fast_decode = False
        self.tokenizer = tokenizer
        self.eos_token_id = eos_token_id
        # Per-request telemetry sink (reference -output-file,
        # request_manager.cc:417-440: e2e latency, decoding steps and
        # token ids appended per finished request).
        self.output_file = output_file
        if eos_token_id is None and tokenizer is not None:
            self.eos_token_id = getattr(tokenizer, "eos_token_id", None)
        self.requests: Dict[int, Request] = {}
        self.pending: List[int] = []
        self.slots: List[Optional[int]] = [None] * engine.num_slots
        self._next_id = 1000000  # reference starts guids at 1000000
        self._admit_counter = 0
        self._key = jax.random.PRNGKey(seed)
        self._step_counter = 0
        # Dispatch-ahead decode pipeline (reference's 4-deep batch-future
        # queue, request_manager.cc:2310-2325): entries are
        # (device_tokens, [(rid, slot), ...]) oldest-first.
        self._inflight: List[tuple] = []
        self._prev_dispatch_slots: set = set()

    # ------------------------------------------------------------------
    # registration (reference register_new_request, request_manager.cc:137)

    def register_request(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
    ) -> int:
        gen = gen or GenerationConfig()
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt requires a tokenizer")
            tokens = list(self.tokenizer.encode(prompt))
            text = prompt
        else:
            tokens = [int(t) for t in prompt]
            text = ""
        if not tokens:
            raise ValueError("empty prompt")
        max_len = self.engine.serving.max_sequence_length
        if len(tokens) >= max_len:
            tokens = tokens[: max_len - 1]
        rid = self._next_id
        self._next_id += 1
        req = Request(
            request_id=rid,
            prompt=text,
            tokens=list(tokens),
            prompt_len=len(tokens),
            gen=gen,
        )
        req.profile.start_time = time.perf_counter()
        self.requests[rid] = req
        self.pending.append(rid)
        return rid

    # ------------------------------------------------------------------
    # paged-KV page management (serve/paging.py PageAllocator; one
    # allocator per engine — a SpecInfer LLM/SSM pair allocates
    # independently but the tables evolve in lockstep because slot
    # assignment and serving limits are shared)

    @property
    def _paged(self) -> bool:
        return getattr(self.engine, "paged", False)

    def _engines(self):
        """Every engine whose cache this manager keeps in sync
        (SpecInferManager adds its SSMs)."""
        return [self.engine]

    def _ensure_pages(self, req: Request, num_lines: int) -> bool:
        """Cover cache lines [0, num_lines) for ``req`` on every engine.
        All-or-nothing per engine; a partial cross-engine success is
        resolved by the caller's preemption retry (``ensure`` is
        idempotent on the engines that already granted)."""
        for eng in self._engines():
            if not eng.pager.ensure(req.slot, num_lines):
                return False
        return True

    def _release_pages(self, slot: int):
        for eng in self._engines():
            eng.pager.release(slot)

    def _preempt(self, req: Request):
        """Evict an admitted request back to the front of the pending
        queue, reclaiming its pages everywhere. Its prefix is recomputed
        on re-admission (prompt + tokens generated so far re-prefill —
        vLLM-style recompute preemption), so generation continues
        exactly where it stopped."""
        self._release_pages(req.slot)
        self.slots[req.slot] = None
        req.slot = -1
        req.status = RequestStatus.PENDING
        req.n_cached = 0
        req.inflight = 0
        self.pending.insert(0, req.request_id)

    def _lines_needed(self, req: Request) -> int:
        """Conservative cache-line bound the next step may touch."""
        if req.status is RequestStatus.PREFILLING:
            return min(
                len(req.tokens),
                req.n_cached + self.engine.serving.prefill_chunk,
            )
        # decode: reads lines [0, len-1], writes len-1 (+ dispatch-ahead
        # steps in flight advance the write line without a host sync)
        return len(req.tokens) + req.inflight + 1

    def _reserve_active_pages(self, lines_fn=None):
        """Grow every active slot's page table to cover this step's
        reads/writes; on pool exhaustion, preempt the newest admission
        (reference eviction order) and retry. Raises only when a single
        request alone exceeds the pool — a configuration error."""
        if not self._paged:
            return
        lines_fn = lines_fn or self._lines_needed
        while True:
            active = sorted(
                (
                    self.requests[rid]
                    for rid in self.slots
                    if rid is not None
                    and self.requests[rid].status
                    in (RequestStatus.PREFILLING, RequestStatus.DECODING)
                ),
                key=lambda r: r.admit_seq,
            )
            for req in active:
                if self._ensure_pages(req, lines_fn(req)):
                    continue
                # free in-flight state before touching slot ownership;
                # flushed completions may already release enough pages
                self._flush_all()
                if req.status not in (
                    RequestStatus.PREFILLING, RequestStatus.DECODING
                ) or self._ensure_pages(req, lines_fn(req)):
                    break  # flush resolved it; re-derive the active set
                victims = [
                    r for r in active
                    if r is not req
                    and r.status
                    in (RequestStatus.PREFILLING, RequestStatus.DECODING)
                ]
                if not victims:
                    raise RuntimeError(
                        "KV page pool exhausted by a single request — "
                        "raise ServingConfig.max_cached_tokens (or lower "
                        "max_sequence_length/page_size)"
                    )
                self._preempt(victims[-1])
                break  # active set changed; re-derive
            else:
                return

    def _attach_paging_metadata(self, bc: BatchConfig):
        """Record the page table + ragged lengths on the batch
        descriptor (the engine dispatches with its own authoritative
        table; this is telemetry/testing metadata)."""
        if not self._paged:
            return
        bc.page_table = self.engine.pager.table.copy()
        seq_lens = np.zeros((self.engine.num_slots,), np.int32)
        for rid in self.slots:
            if rid is None:
                continue
            req = self.requests[rid]
            if req.status is RequestStatus.PREFILLING:
                seq_lens[req.slot] = min(
                    len(req.tokens),
                    req.n_cached + self.engine.serving.prefill_chunk,
                )
            elif req.status is RequestStatus.DECODING:
                seq_lens[req.slot] = len(req.tokens)
        bc.seq_lens = seq_lens

    # ------------------------------------------------------------------
    # slot management

    def _admit_pending(self):
        for i, occupant in enumerate(self.slots):
            if occupant is not None or not self.pending:
                continue
            rid = self.pending[0]
            req = self.requests[rid]
            req.slot = i
            if self._paged and not self._ensure_pages(
                req, min(len(req.tokens), self.engine.serving.prefill_chunk)
            ):
                # pool cannot take the first chunk: stop admitting (a
                # flush will free pages; the request stays queued) and
                # roll back any partial cross-engine grant
                self._release_pages(i)
                req.slot = -1
                break
            self.pending.pop(0)
            req.status = RequestStatus.PREFILLING
            req.n_cached = 0
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            self.slots[i] = rid

    def _active(self, status: RequestStatus) -> List[Request]:
        out = []
        for rid in self.slots:
            if rid is None:
                continue
            r = self.requests[rid]
            if r.status is status:
                out.append(r)
        return out

    def _finish(self, req: Request):
        req.status = RequestStatus.COMPLETED
        req.profile.finish_time = time.perf_counter()
        if req.slot >= 0:
            if self._paged:
                self._release_pages(req.slot)
            self.slots[req.slot] = None
            req.slot = -1
        if self.output_file:
            self._write_output_record(req)

    def _write_output_record(self, req: Request):
        """Append one finished request's telemetry — the format mirrors
        the reference's output-file writer (request_manager.cc:417-440:
        ``[Profile] guid(%d) llm_decoding_steps(%d) start(%.1lf)
        finish(%.1lf) latency(%.1lf)`` then the token ids)."""
        p = req.profile
        latency_us = (p.finish_time - p.start_time) * 1e6
        text = (
            self.tokenizer.decode(req.output_tokens)
            if self.tokenizer is not None
            else ""
        )
        with open(self.output_file, "a") as f:
            f.write(
                f"[Profile] guid({req.request_id}) "
                f"llm_decoding_steps({p.llm_decoding_steps}) "
                f"latency({latency_us:.1f})\n"
            )
            f.write(
                f"guid({req.request_id}) tokens("
                + " ".join(str(t) for t in req.tokens)
                + f") output({text})\n"
            )

    # ------------------------------------------------------------------
    # batch building (reference prepare_next_batch, request_manager.cc:350)

    def _fill_prefill_row(self, bc: BatchConfig, req: Request, chunk: int):
        off = req.n_cached
        toks = req.tokens[off : off + chunk]
        n = len(toks)
        bc.tokens[req.slot, :n] = toks
        bc.positions[req.slot, :n] = np.arange(off, off + n)
        bc.active[req.slot] = True
        bc.logits_idx[req.slot] = n - 1

    def _prepare_batch(self) -> Optional[BatchConfig]:
        """Build one mixed prefill+decode batch. Decoding slots always
        contribute their one pending token, so decode never stalls behind
        a long prompt's prefill (no head-of-line blocking); the chunk is
        1 when nobody is prefilling."""
        prefilling = self._active(RequestStatus.PREFILLING)
        decoding = self._active(RequestStatus.DECODING)
        if not prefilling and not decoding:
            return None
        sc = self.engine.serving
        chunk = sc.prefill_chunk if prefilling else 1
        bc = BatchConfig.empty(self.engine.num_slots, chunk, self.engine.scratch_pos)
        for req in prefilling:
            self._fill_prefill_row(bc, req, chunk)
        for req in decoding:
            bc.tokens[req.slot, 0] = req.tokens[-1]
            bc.positions[req.slot, 0] = len(req.tokens) - 1
            bc.active[req.slot] = True
            bc.logits_idx[req.slot] = 0
        self._attach_paging_metadata(bc)
        return bc

    # ------------------------------------------------------------------
    # sampling glue

    def _decode_head_params(self, reqs: Sequence[Request]):
        """Per-slot decode-head arrays for ``reqs`` (greedy/temperature/
        top-p; top-p >= 1 disables the nucleus filter)."""
        R = self.engine.num_slots
        greedy = np.ones((R,), bool)
        temp = np.ones((R,), np.float32)
        topp = np.full((R,), 2.0, np.float32)  # disabled
        for req in reqs:
            greedy[req.slot] = not req.gen.do_sample
            temp[req.slot] = req.gen.temperature
            topp[req.slot] = req.gen.topp if req.gen.do_sample else 2.0
        return greedy, temp, topp

    def _sample(self, logits) -> np.ndarray:
        """Sample one token per slot from (R, V) logits using each slot's
        GenerationConfig (mixed greedy/sampling in one program)."""
        greedy, temp, topp = self._decode_head_params(
            [self.requests[r] for r in self.slots if r is not None]
        )
        self._key, sub = jax.random.split(self._key)
        toks = sample_tokens(
            logits,
            sub,
            greedy=jnp.asarray(greedy),
            temperature=jnp.asarray(temp),
            topp=jnp.asarray(topp),
        )
        return np.asarray(jax.device_get(toks))

    def _append_token(self, req: Request, token: int):
        req.tokens.append(int(token))
        gen_len = len(req.tokens) - req.prompt_len
        eos = self.eos_token_id
        max_total = self.engine.serving.max_sequence_length
        stops = set(req.gen.stop_token_ids)
        if eos is not None:
            stops.add(eos)
        if (
            (int(token) in stops)
            or gen_len >= req.gen.max_new_tokens
            or len(req.tokens) >= max_total
        ):
            self._finish(req)

    # ------------------------------------------------------------------
    # incremental decoding loop (reference generate_incr_decoding, :2292)

    def _run_batch(self, bc: BatchConfig):
        """Hook: run one prepared batch through the engine(s).
        SpecInferManager overrides this to keep the SSM cache in sync."""
        return self.engine.run(bc)

    # ------------------------------------------------------------------
    # dispatch-ahead decode pipeline (reference request_manager.cc:2310)

    def _dispatch_decode(self, decoding: List[Request]):
        """Dispatch one fused decode step WITHOUT waiting for the
        previous one: decode rows that were in the previous dispatch
        take their input token from the on-device sampled tokens; rows
        entering the pipeline take it from host state. Positions advance
        deterministically, so no host sync is needed."""
        R = self.engine.num_slots
        scratch = self.engine.scratch_pos
        host_tokens = np.zeros((R, 1), np.int32)
        use_last = np.zeros((R,), bool)
        positions = np.full((R, 1), scratch, np.int32)
        greedy, temp, topp = self._decode_head_params(decoding)
        snapshot = []
        last = self._inflight[-1][0] if self._inflight else None
        for req in decoding:
            s = req.slot
            positions[s, 0] = len(req.tokens) - 1 + req.inflight
            if s in self._prev_dispatch_slots and last is not None:
                use_last[s] = True
            else:
                host_tokens[s, 0] = req.tokens[-1]
            req.inflight += 1
            snapshot.append((req.request_id, s))
        if last is None:
            last = jnp.zeros((R,), jnp.int32)
        self._key, sub = jax.random.split(self._key)
        toks = self.engine.run_decode(
            last, host_tokens, use_last, positions, sub, greedy, temp, topp
        )
        self._inflight.append((toks, snapshot))
        self._prev_dispatch_slots = {s for _, s in snapshot}
        self._step_counter += 1

    def _flush_one(self):
        """Fetch the oldest in-flight step's tokens and do the host
        bookkeeping (append, EOS/max-length checks, slot release)."""
        toks, snapshot = self._inflight.pop(0)
        toks = np.asarray(jax.device_get(toks))
        for rid, slot in snapshot:
            req = self.requests.get(rid)
            if req is None:
                continue
            req.inflight = max(0, req.inflight - 1)
            if req.status is not RequestStatus.DECODING:
                continue  # finished by an earlier flush; row is garbage
            req.n_cached += 1
            req.profile.llm_decoding_steps += 1
            self._append_token(req, toks[slot])

    def _flush_all(self):
        while self._inflight:
            self._flush_one()
        self._prev_dispatch_slots = set()

    # ------------------------------------------------------------------

    def step(self) -> bool:
        """One scheduling step. Returns False when no work remains."""
        self._admit_pending()
        # paged KV: grow page tables to cover this step's writes BEFORE
        # any dispatch (may preempt the newest admission on exhaustion)
        self._reserve_active_pages()
        prefilling = self._active(RequestStatus.PREFILLING)
        decoding = self._active(RequestStatus.DECODING)
        if self.supports_fast_decode and decoding and not prefilling:
            # (a queued request waiting for a slot doesn't force the
            # sync path: it only becomes schedulable once a flush frees
            # a slot, and the resulting PREFILLING admission is itself
            # the sync point)
            self._dispatch_decode(decoding)
            depth = max(1, self.engine.serving.dispatch_ahead)
            while len(self._inflight) >= depth:
                self._flush_one()
            return True
        # Mode change (prefill joining, admissions, drain): sync point.
        self._flush_all()
        bc = self._prepare_batch()
        if bc is None:
            return bool(self.pending)
        prefilling = self._active(RequestStatus.PREFILLING)
        decoding = self._active(RequestStatus.DECODING)
        logits = self._run_batch(bc)
        sampled = self._sample(logits)
        for req in decoding:
            req.n_cached += 1
            req.profile.llm_decoding_steps += 1
            self._append_token(req, sampled[req.slot])
        for req in prefilling:
            n = int(bc.logits_idx[req.slot]) + 1  # tokens cached this chunk
            req.n_cached += n
            if req.n_cached >= len(req.tokens):
                # prompt fully cached: first output token sampled now
                req.status = RequestStatus.DECODING
                req.profile.llm_decoding_steps += 1
                self._append_token(req, sampled[req.slot])
        self._step_counter += 1
        return True

    def generate(
        self,
        prompts: Union[str, Sequence[Union[str, Sequence[int]]]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
    ) -> List[GenerationResult]:
        """Blocking generate over a batch of prompts (reference
        ``FFModel::generate`` → ``generate_incr_decoding``)."""
        if isinstance(prompts, str):
            prompts = [prompts]
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        rids = [self.register_request(p, gen) for p in prompts]
        while any(
            self.requests[r].status is not RequestStatus.COMPLETED for r in rids
        ):
            if not self.step():
                break
        results = []
        for rid in rids:
            req = self.requests[rid]
            out = req.output_tokens
            text = (
                self.tokenizer.decode(out) if self.tokenizer is not None else ""
            )
            results.append(
                GenerationResult(
                    request_id=rid,
                    prompt=req.prompt,
                    input_tokens=req.tokens[: req.prompt_len],
                    output_tokens=list(out),
                    output_text=text,
                    profile=req.profile,
                )
            )
        return results
