"""Decode heads: greedy argmax, temperature, top-k, top-p sampling.

TPU-native equivalents of the reference decode operators ``argmax``,
``sampling`` (top-p via sorted cumsum, reference ``src/ops/sampling.cc``),
``arg_topk``/``beam_topk`` (reference ``src/ops/arg_topk.cc``,
``beam_topk.cc``). One jitted function handles a whole batch with
per-request parameters as arrays, so mixed greedy/sampling batches run in
a single program (the reference dispatches per-model decode-head ops).

Mode-specialized heads (the megakernel decode step's sampling
epilogue): the general path pays one full ``(R, V)`` descending sort —
shared by the top-k and top-p filters — every step, even when every
row is greedy (today's common decode batch). ``mode`` specializes the
compiled head to what the batch actually needs, chosen host-side by
:func:`choose_sample_mode` from the step's decode-head arrays:

``"greedy"``
    every row argmaxes — no scaling, no filters, no sort, no RNG.
``"sample"``
    temperature-only sampling (top-k/top-p both disabled) — no sort.
``"topk"``
    per-row top-k (no top-p): the k-th-value threshold comes from one
    ``lax.top_k`` over a static ``topk_cap`` bucket (power-of-two ≥
    the batch max k, so steady workloads reuse one compile) — O(V·log
    cap) instead of the full sort.
``"full"``
    the reference path: ONE shared sort feeds both filters (the
    top-k-filtered sorted tensor is derived analytically from the
    unfiltered sort, so top-p never re-sorts).

Every mode is bitwise-identical to the ``"full"`` reference head on
the rows it serves: same threshold values (a top-k prefix of a
descending sort IS the sort's prefix), same filtered logits, same
categorical draw from the same key.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

#: sampling-epilogue modes a compiled head can specialize to; also the
#: vocabulary of ``ServingConfig.fused_decode``-tagged step keys
SAMPLE_MODES = ("full", "greedy", "sample", "topk")

#: largest per-row top-k the bucketed "topk" mode serves; bigger ks
#: fall back to the full-sort head (one compile per power-of-two
#: bucket keeps the step-key set small and steady)
TOPK_CAP_LIMIT = 128


def choose_sample_mode(
    greedy: np.ndarray,   # (R,) bool
    topp: np.ndarray,     # (R,) float; >= 1 disables
    topk: np.ndarray,     # (R,) int; <= 0 disables
    vocab_size: int,
) -> Tuple[str, int]:
    """Pick the cheapest head mode serving this batch's decode-head
    arrays (host-side — the scheduler knows every row's
    GenerationConfig). Returns ``(mode, topk_cap)``; ``topk_cap`` is 0
    except for the bucketed "topk" mode."""
    greedy = np.asarray(greedy, bool)
    if bool(greedy.all()):
        return "greedy", 0
    sampling = ~greedy
    if bool((np.asarray(topp, np.float32)[sampling] < 1.0).any()):
        return "full", 0
    mk = int(np.asarray(topk, np.int64)[sampling].max(initial=0))
    if mk <= 0:
        return "sample", 0
    if mk >= min(TOPK_CAP_LIMIT, vocab_size):
        return "full", 0
    cap = 1 << (mk - 1).bit_length()  # smallest power of two >= mk
    return "topk", min(cap, vocab_size)


def _apply_topk(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Static-k top-k filter: keep the k largest logits per row."""
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _sorted_desc(logits: jnp.ndarray) -> jnp.ndarray:
    """One full descending sort — the shared tensor both filters cut."""
    return jnp.sort(logits, axis=-1)[..., ::-1]


def _topk_filter(
    logits: jnp.ndarray,
    topk: jnp.ndarray,
    sorted_desc: Optional[jnp.ndarray] = None,
):
    """Per-row top-k filter (``topk`` (R,) int32; <=0 disables for that
    row) — the dynamic-k counterpart of :func:`_apply_topk` so mixed
    batches honor each request's ``GenerationConfig.topk`` in ONE
    program (the reference dispatches a per-model arg_topk op,
    ``src/ops/arg_topk.cc``). Uses a sorted threshold instead of
    ``lax.top_k`` because k is a traced per-row value.

    Returns ``(filtered, filtered_sorted)``: the filter drops a SUFFIX
    of the descending sort, so the filtered tensor's sort is the shared
    sort with that suffix set to NEG_INF — derived, never re-sorted
    (the top-p filter consumes it)."""
    V = logits.shape[-1]
    if sorted_desc is None:
        sorted_desc = _sorted_desc(logits)
    kk = jnp.clip(topk, 1, V)
    kth = jnp.take_along_axis(sorted_desc, (kk - 1)[..., None], axis=-1)
    keep_all = (topk <= 0)[..., None]
    filtered = jnp.where(keep_all | (logits >= kth), logits, NEG_INF)
    filtered_sorted = jnp.where(
        keep_all | (sorted_desc >= kth), sorted_desc, NEG_INF
    )
    return filtered, filtered_sorted


def _topp_filter(
    logits: jnp.ndarray,
    topp: jnp.ndarray,
    sorted_desc: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Top-p (nucleus) filter — sorted cumulative-probability cut exactly
    like the reference's sorted-cumsum kernel (sampling.cc). ``topp`` is
    per-row (R,); topp >= 1 keeps everything. ``sorted_desc`` is the
    descending sort of ``logits`` when the caller already has it."""
    if sorted_desc is None:
        sorted_desc = _sorted_desc(logits)
    sorted_probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Keep tokens while the cumulative mass *before* them is < topp.
    keep_sorted = (cum - sorted_probs) < topp[..., None]
    # Threshold logit: smallest kept logit per row.
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < thresh, NEG_INF, logits)


@functools.partial(jax.jit, static_argnames=("topk", "mode", "topk_cap"))
def sample_tokens(
    logits: jnp.ndarray,      # (R, V) float
    key: jax.Array,
    *,
    greedy: jnp.ndarray,      # (R,) bool — argmax instead of sampling
    temperature: jnp.ndarray, # (R,) float
    topp: jnp.ndarray,        # (R,) float; >=1 disables
    topk: int = 0,            # static; 0 disables
    topk_arr: Optional[jnp.ndarray] = None,  # (R,) int32; <=0 disables per row
    mode: str = "full",       # static head specialization (module doc)
    topk_cap: int = 0,        # static k bucket for mode="topk"
) -> jnp.ndarray:
    """Sample one token per request slot. Returns (R,) int32.

    ``mode``/``topk_cap`` come from :func:`choose_sample_mode`; passing
    a mode the batch's decode-head arrays don't satisfy (e.g.
    ``"greedy"`` with a sampling row) silently serves the wrong head —
    the host chooser is the contract."""
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if mode == "greedy":
        return greedy_tok
    t = jnp.maximum(temperature, 1e-6)[..., None]
    scaled = logits / t
    scaled = _apply_topk(scaled, topk)
    if mode == "sample":
        pass  # temperature only: both filters are identity
    elif mode == "topk":
        # k-th value from a static top-k bucket: bitwise the same
        # threshold as the sort path (a descending sort's prefix)
        V = scaled.shape[-1]
        top = jax.lax.top_k(scaled, topk_cap)[0]        # (R, cap)
        kk = jnp.clip(topk_arr, 1, V)
        kth = jnp.take_along_axis(top, (kk - 1)[..., None], axis=-1)
        keep_all = (topk_arr <= 0)[..., None]
        scaled = jnp.where(keep_all | (scaled >= kth), scaled, NEG_INF)
    else:  # "full" — one shared sort feeds both filters
        sorted_desc = _sorted_desc(scaled)
        if topk_arr is not None:
            scaled, sorted_desc = _topk_filter(scaled, topk_arr, sorted_desc)
        scaled = _topp_filter(scaled, topp, sorted_desc)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, sampled)


@functools.partial(jax.jit, static_argnames=("k",))
def beam_topk(logprobs: jnp.ndarray, k: int):
    """Top-k over the vocab per row — the SSM beam expansion head
    (reference ``beam_topk.cc``). Returns (values, indices) each (..., k)."""
    return jax.lax.top_k(logprobs, k)


@jax.jit
def log_softmax(logits: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
