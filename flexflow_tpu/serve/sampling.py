"""Decode heads: greedy argmax, temperature, top-k, top-p sampling.

TPU-native equivalents of the reference decode operators ``argmax``,
``sampling`` (top-p via sorted cumsum, reference ``src/ops/sampling.cc``),
``arg_topk``/``beam_topk`` (reference ``src/ops/arg_topk.cc``,
``beam_topk.cc``). One jitted function handles a whole batch with
per-request parameters as arrays, so mixed greedy/sampling batches run in
a single program (the reference dispatches per-model decode-head ops).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _apply_topk(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Static-k top-k filter: keep the k largest logits per row."""
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _topk_filter(logits: jnp.ndarray, topk: jnp.ndarray) -> jnp.ndarray:
    """Per-row top-k filter (``topk`` (R,) int32; <=0 disables for that
    row) — the dynamic-k counterpart of :func:`_apply_topk` so mixed
    batches honor each request's ``GenerationConfig.topk`` in ONE
    program (the reference dispatches a per-model arg_topk op,
    ``src/ops/arg_topk.cc``). Uses a sorted threshold instead of
    ``lax.top_k`` because k is a traced per-row value."""
    V = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    kk = jnp.clip(topk, 1, V)
    kth = jnp.take_along_axis(sorted_desc, (kk - 1)[..., None], axis=-1)
    keep_all = (topk <= 0)[..., None]
    return jnp.where(keep_all | (logits >= kth), logits, NEG_INF)


def _topp_filter(logits: jnp.ndarray, topp: jnp.ndarray) -> jnp.ndarray:
    """Top-p (nucleus) filter — sorted cumulative-probability cut exactly
    like the reference's sorted-cumsum kernel (sampling.cc). ``topp`` is
    per-row (R,); topp >= 1 keeps everything."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Keep tokens while the cumulative mass *before* them is < topp.
    keep_sorted = (cum - sorted_probs) < topp[..., None]
    # Threshold logit: smallest kept logit per row.
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < thresh, NEG_INF, logits)


@functools.partial(jax.jit, static_argnames=("topk",))
def sample_tokens(
    logits: jnp.ndarray,      # (R, V) float
    key: jax.Array,
    *,
    greedy: jnp.ndarray,      # (R,) bool — argmax instead of sampling
    temperature: jnp.ndarray, # (R,) float
    topp: jnp.ndarray,        # (R,) float; >=1 disables
    topk: int = 0,            # static; 0 disables
    topk_arr: Optional[jnp.ndarray] = None,  # (R,) int32; <=0 disables per row
) -> jnp.ndarray:
    """Sample one token per request slot. Returns (R,) int32."""
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.maximum(temperature, 1e-6)[..., None]
    scaled = logits / t
    scaled = _apply_topk(scaled, topk)
    if topk_arr is not None:
        scaled = _topk_filter(scaled, topk_arr)
    scaled = _topp_filter(scaled, topp)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, sampled)


@functools.partial(jax.jit, static_argnames=("k",))
def beam_topk(logprobs: jnp.ndarray, k: int):
    """Top-k over the vocab per row — the SSM beam expansion head
    (reference ``beam_topk.cc``). Returns (values, indices) each (..., k)."""
    return jax.lax.top_k(logprobs, k)


@jax.jit
def log_softmax(logits: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
