"""Beam-search decoding (non-speculative).

TPU-native counterpart of the reference's beam decode head (reference
``src/ops/beam_topk.cc`` and the beam bookkeeping in
``BeamSearchBatchConfig``, batch_config.h:133-190), applied to plain
generation rather than SSM speculation: the W live hypotheses occupy W
request slots of the shared KV cache, one decode step advances all of
them in a single program, and hypothesis reordering is a slot gather
(``engine.reorder``) instead of the reference's sub-request KV forking.

Scoring follows the standard (HF-compatible) rule: a hypothesis ending
in EOS banks with score ``logprob / len**length_penalty``; at the end
the best of banked + live wins, so greedy (W=1, no EOS) degenerates to
argmax decoding.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from .batch_config import BatchConfig, GenerationConfig, GenerationResult, ProfileInfo
from .engine import InferenceEngine
from .sampling import log_softmax


def _topk_np(x: np.ndarray, k: int):
    idx = np.argpartition(-x, k - 1)[:k]
    idx = idx[np.argsort(-x[idx])]
    return x[idx], idx


def _ensure_beam_pages(engine: InferenceEngine, num_slots: int, lines: int):
    """Paged KV: every beam slot needs its own pages covering ``lines``
    BEFORE the cache-content reorder copies hypotheses across slots
    (reorder moves content between table-resolved pages; equal-length
    beams guarantee equal allocations)."""
    if not getattr(engine, "paged", False):
        return
    for s in range(num_slots):
        if not engine.pager.ensure(s, lines):
            raise RuntimeError(
                f"KV page pool exhausted during beam search (slot {s}, "
                f"{lines} lines) — raise ServingConfig.max_cached_tokens"
            )


def _release_beam_pages(engine: InferenceEngine, num_slots: int):
    if not getattr(engine, "paged", False):
        return
    for s in range(num_slots):
        engine.pager.release(s)


def beam_generate(
    engine: InferenceEngine,
    prompt: Sequence[int],
    gen: GenerationConfig,
    eos_token_id: Optional[int] = None,
) -> List[int]:
    """Beam-search one request; returns the best hypothesis' generated
    tokens. Uses slots [0, W) of the engine's cache."""
    W = gen.num_beams
    R = engine.num_slots
    assert 1 <= W <= R, f"num_beams {W} exceeds {R} cache slots"
    sc = engine.serving
    scratch = engine.scratch_pos
    prompt = list(prompt)
    if not prompt:
        raise ValueError("empty prompt")
    max_total = sc.max_sequence_length
    if len(prompt) >= max_total:
        prompt = prompt[: max_total - 1]
    stops = set(gen.stop_token_ids)
    if eos_token_id is not None:
        stops.add(eos_token_id)

    # --- chunked prefill into slot 0 ---
    n = 0
    logits = None
    _ensure_beam_pages(engine, 1, len(prompt))
    while n < len(prompt):
        toks = prompt[n : n + sc.prefill_chunk]
        bc = BatchConfig.empty(R, sc.prefill_chunk, scratch)
        bc.tokens[0, : len(toks)] = toks
        bc.positions[0, : len(toks)] = np.arange(n, n + len(toks))
        bc.logits_idx[0] = len(toks) - 1
        bc.active[0] = True
        logits = engine.run(bc)
        n += len(toks)
    logp0 = np.asarray(jax.device_get(log_softmax(logits)))[0]  # (V,)

    banked: List[tuple] = []  # (normalized score, tokens)

    def norm(score: float, length: int) -> float:
        return score / (max(1, length) ** gen.length_penalty)

    def select(cand_scores, cand_tokens, parent_of):
        """HF beam rule over 2W sorted candidates: an EOS candidate
        banks only at rank < W; non-EOS fill the live set to W."""
        new_live, parents = [], []
        for rank, (v, t) in enumerate(zip(cand_scores, cand_tokens)):
            toks = parent_of(int(t), rank)
            if int(t) in stops:
                if rank < W:
                    banked.append((norm(float(v), len(toks)), toks))
            else:
                new_live.append((float(v), toks))
                parents.append(rank)
            if len(new_live) == W:
                break
        return new_live, parents

    # --- seed beams from the prefill logits; clone slot 0's cache ---
    try:
        vals, idxs = _topk_np(logp0, min(2 * W, logp0.size))
        seeds, _ = select(vals, idxs, lambda t, rank: [t])
        live = seeds
        _ensure_beam_pages(engine, W, len(prompt))
        src = np.arange(R, dtype=np.int32)
        src[:W] = 0
        engine.reorder(src)

        max_new = min(gen.max_new_tokens, max_total - len(prompt))
        for step in range(1, max_new):
            if not live:
                break
            if len(banked) >= W:
                # early_stopping=False rule: stop once no live hypothesis
                # can still beat the W-th banked score.
                banked.sort(key=lambda x: -x[0])
                del banked[W:]
                best_live = max(s for s, _ in live)
                if banked[-1][0] >= norm(best_live, len(live[0][1])):
                    break
            _ensure_beam_pages(engine, W, len(prompt) + step)
            bc = BatchConfig.empty(R, 1, scratch)
            for b, (score, toks) in enumerate(live):
                bc.tokens[b, 0] = toks[-1]
                bc.positions[b, 0] = len(prompt) + len(toks) - 1
                bc.active[b] = True
            logits = engine.run(bc)
            logp = np.asarray(jax.device_get(log_softmax(logits)))[: len(live)]
            V = logp.shape[-1]
            cand = np.asarray(
                [score for score, _ in live], np.float32
            )[:, None] + logp  # (w, V)
            vals, flat = _topk_np(cand.reshape(-1), min(2 * W, cand.size))
            beam_of = (flat // V).astype(int)
            live_prev = live
            live, parent_ranks = select(
                vals, flat % V,
                lambda t, rank: live_prev[beam_of[rank]][1] + [t],
            )
            parents = [int(beam_of[r]) for r in parent_ranks]
            src = np.arange(R, dtype=np.int32)
            src[: len(parents)] = parents
            engine.reorder(src)

        finals = banked + [(norm(s, len(t)), t) for s, t in live]
        finals.sort(key=lambda x: -x[0])
        return finals[0][1]
    finally:
        _release_beam_pages(engine, W)


def generate_with_beams(
    engine: InferenceEngine,
    prompts: Sequence[Any],
    gen: GenerationConfig,
    eos_token_id: Optional[int] = None,
    tokenizer: Any = None,
) -> List[GenerationResult]:
    """Beam-decode a list of prompts (sequential per request — the
    reference's beam path is also per-request, MAX_BEAM_WIDTH=3)."""
    import time

    results = []
    for i, p in enumerate(prompts):
        if isinstance(p, str):
            assert tokenizer is not None, "string prompt requires a tokenizer"
            toks, text = list(tokenizer.encode(p)), p
        else:
            toks, text = [int(t) for t in p], ""
        prof = ProfileInfo(start_time=time.perf_counter())
        out = beam_generate(engine, toks, gen, eos_token_id)
        prof.finish_time = time.perf_counter()
        prof.llm_decoding_steps = len(out)
        results.append(
            GenerationResult(
                request_id=i,
                prompt=text,
                input_tokens=toks,
                output_tokens=out,
                output_text=tokenizer.decode(out) if tokenizer else "",
                profile=prof,
            )
        )
    return results
