"""Quantized paged KV cache — write-side math + layout registry.

FlexFlow Serve ships int4/int8 quantization as a first-class serving
feature (SURVEY.md, ``--4bit/8bit-quantization``); this repo already
quantizes *weights* (flexflow_tpu/quantization.py). KV-cache
quantization is the other half of the byte budget: at high concurrency
the paged pool (serve/paging.py) is what gates both pool capacity and
decode read bandwidth, so storing pages as int8 doubles the pages a
fixed HBM budget holds and halves the KV bytes the decode hot loop
streams (the EQuARX observation — arxiv 2506.17615 — applied to cache
reads instead of collectives).

Layout
------
A quantized page pool stores, per cache tensor (K and V):

* ``(L, num_pages+1, page_size, KV, dk/pack)`` code elements in place
  of the bf16/f32 pool (int8: one code per byte; int4: two nibble
  codes per byte along dk), and
* ``(L, num_pages+1, KV)`` **float32** scales — one symmetric amax
  scale per page per KV head (``k_scale``/``v_scale`` cache keys).

Dequantization happens *inside* attention (serve/kernels.py: the fused
Pallas ragged-paged kernel multiplies per-page scales into the
QK^T scores and the PV product; the XLA fallback dequantizes the
gathered virtual cache) — full-precision K/V never round-trip HBM.

Write-side contract (:func:`quant_line_write`)
----------------------------------------------
``serve_step``'s KV commit quantizes in the jitted step itself:

1. **amax scaling at commit time.** Each page's scale is the running
   amax (per KV head) of every line committed to it, divided by qmax.
2. **Rescale on growth.** When a new line's amax exceeds the page's
   scale, the page's existing codes are requantized to the new scale
   (``round(q * s_old / s_new)``) so one scale stays exact for the
   whole page. When the scale is unchanged the ratio is exactly 1.0
   and the rewrite is a bitwise identity.
3. **History independence.** A write at in-page offset 0 is by
   construction the first line a slot commits to that physical page
   (cache lines fill pages front to back; spliced prefix-cache pages
   are never written, and a COW'd tail page continues at offset > 0),
   so it RESETS the page's scale instead of inheriting a stale amax
   from the page's previous occupant. Quantized page content is
   therefore a pure function of the tokens written, never of
   allocation history — which is what keeps run-to-run generation
   bitwise deterministic and preemption/recompute parity exact.

int4 (``SPECS["int4"]``: qmax 7, pack=2) stores TWO codes per byte
packed along dk — byte ``j`` of a line carries head-dim entries ``j``
(low nibble) and ``j + dk/2`` (high nibble), each biased by +8 into
[1, 15] exactly like quantization.py's packed int4 weights (garbage
bytes of never-written lines decode to the out-of-band code -8, which
a zero page scale maps to 0.0). The halves-of-dk split (rather than
even/odd interleave) unpacks as one concatenate — no lane-crossing
reshuffle in the Pallas kernel. A fixed HBM budget holds ~4x the bf16
pages (≥3.8x after the scale rows — asserted in the
``serve_kv_hierarchy`` bench phase); the same write-side contract
(running amax, rescale-on-growth, offset-0 reset) applies on the
unpacked code values, so int4 generation keeps the bitwise
run-to-run and preemption/recompute guarantees, at a wider
quantization tolerance than int8 (documented in README "Hierarchical
KV cache" and tests/test_kv_hierarchy.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class KVQuantSpec:
    """One quantized-KV storage layout (see module docstring)."""

    name: str
    bits: int
    qmax: float       # symmetric clip: codes live in [-qmax, qmax]
    dtype: Any        # storage dtype of the page pool
    pack: int = 1     # codes per storage element (int4 packs 2 along dk)

    @property
    def itemsize(self) -> int:
        return jnp.dtype(self.dtype).itemsize


SPECS = {
    "int8": KVQuantSpec("int8", 8, 127.0, jnp.int8, 1),
    # Packed nibbles along dk (halves split, bias +8 — see the module
    # docstring); uint8 storage is the pack=2 discriminator, matching
    # quantization.py's packed int4 weights.
    "int4": KVQuantSpec("int4", 4, 7.0, jnp.uint8, 2),
}


def resolve_spec(kv_quant: Optional[str]) -> Optional[KVQuantSpec]:
    """Validate a ``ServingConfig.kv_quant`` value. None passes
    through; unknown names are a ValueError."""
    if kv_quant is None:
        return None
    spec = SPECS.get(kv_quant)
    if spec is None:
        raise ValueError(
            f"unknown kv_quant {kv_quant!r} (expected one of "
            f"{sorted(SPECS)} or None)"
        )
    return spec


# ---------------------------------------------------------------------------
# nibble packing (pack=2 layouts). The pair lives in ONE place so the
# XLA write/read paths and the in-kernel Pallas unpack (serve/kernels.py
# mirrors the arithmetic op-for-op) can never drift: integer adds,
# shifts and masks only — exact on every backend.


def pack_nibbles(codes: jnp.ndarray) -> jnp.ndarray:
    """(..., dk) signed codes in [-8, 7] → (..., dk//2) uint8: byte j
    holds code j (low nibble) and code j + dk/2 (high nibble), each
    biased +8. dk must be even (the engine validates head_dim % pack
    up front)."""
    dk = codes.shape[-1]
    c = codes.astype(jnp.int32) + 8
    lo, hi = c[..., : dk // 2], c[..., dk // 2 :]
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_nibbles(packed: jnp.ndarray) -> jnp.ndarray:
    """(..., dkp) uint8 → (..., 2*dkp) f32 signed codes (the inverse of
    :func:`pack_nibbles`; all-zero garbage bytes decode to -8, which a
    zero page scale maps to 0.0)."""
    b = packed.astype(jnp.int32)
    lo = (b & 0xF) - 8
    hi = ((b >> 4) & 0xF) - 8
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)


def pool_pack(pool: jnp.ndarray) -> int:
    """Codes per storage element of a quantized page pool — uint8 IS
    the packed-nibble layout (int8 pools store one code per byte), the
    same storage-dtype convention quantization.py's weight path uses."""
    return 2 if pool.dtype == jnp.dtype(jnp.uint8) else 1


def quant_line_write(
    kq: jnp.ndarray,     # (P+1, ps, KV, dk) quantized page pool (one layer)
    scale: jnp.ndarray,  # (P+1, KV) f32 per-page-per-head scales
    phys: jnp.ndarray,   # (R, C) int32 physical page per new line
    off: jnp.ndarray,    # (R, C) int32 in-page offset per new line
    vals: jnp.ndarray,   # (R, C, KV, dk) full-precision lines to commit
    qmax: float,
):
    """Commit full-precision K/V lines into a quantized page pool
    (the quantized twin of ``pool.at[phys, off].set(...)``) — running
    per-page amax scales, rescale-on-growth, offset-0 scale reset; see
    the module docstring for the contract. Returns ``(kq, scale)``.

    Duplicate page indices are safe throughout: the scale update is a
    commutative scatter-max, and every rescale scatter writes values
    that depend only on the page, so colliding writes are identical.
    Shared (refcounted > 1) pages are never the target of a line write
    — the prefix cache COWs the tail page before any slot appends — so
    rescaling page content in place cannot perturb another reader.

    Packed layouts (int4): the pool's trailing dim is dk/pack and the
    pack factor is inferred from the shapes; rescale unpacks the
    touched pages' nibbles, requantizes on code VALUES, and repacks —
    arithmetically identical to the int8 path per code, so every
    determinism guarantee above carries over unchanged.
    """
    P1, ps, KV, dkp = kq.shape
    R, C = phys.shape
    pack = vals.shape[-1] // dkp  # 1 (int8) or 2 (packed int4 nibbles)
    vf = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(vf), axis=-1)  # (R, C, KV)

    def _codes(stored):
        return unpack_nibbles(stored) if pack == 2 else stored.astype(
            jnp.float32
        )

    def _store(codes):
        return pack_nibbles(codes) if pack == 2 else codes.astype(kq.dtype)

    # offset-0 writes mark the page's first use by its current owner:
    # drop the previous occupant's stale amax (history independence)
    first = jnp.zeros((P1,), jnp.int32).at[phys.reshape(-1)].max(
        (off.reshape(-1) == 0).astype(jnp.int32)
    )
    old = jnp.where(first[:, None] > 0, 0.0, scale)     # (P1, KV)
    new = old.at[phys].max(amax / qmax)                 # (P1, KV)

    # Rescale existing codes of every touched page to the grown scale
    # (identity when the scale did not move). Below the crossover the
    # per-line page gather is cheaper; past it (wide prefill chunks
    # touching few distinct pages many times) the full-pool elementwise
    # form does strictly less work than R*C duplicate page gathers.
    if R * C < P1:
        pages = phys.reshape(-1)                        # (R*C,)
        ratio = jnp.where(
            new[pages] > 0.0,
            old[pages] / jnp.maximum(new[pages], 1e-30),
            0.0,
        )                                               # (R*C, KV)
        content = _codes(kq[pages])                     # (R*C, ps, KV, dk)
        requant = jnp.round(content * ratio[:, None, :, None])
        kq = kq.at[pages].set(_store(requant))
    else:
        ratio = jnp.where(
            new > 0.0, old / jnp.maximum(new, 1e-30), 0.0
        )                                               # (P1, KV)
        requant = jnp.round(_codes(kq) * ratio[:, None, :, None])
        kq = _store(requant)

    # quantize the new lines at their page's (final) scale and scatter
    s_line = new[phys]                                  # (R, C, KV)
    q = jnp.round(vf / jnp.maximum(s_line[..., None], 1e-30))
    q = jnp.clip(q, -qmax, qmax)
    kq = kq.at[phys, off].set(_store(q))
    return kq, new


def quant_commit_lines(
    buf: jnp.ndarray,     # (L, P+1, ps, KV, dk) quantized pool
    scale: jnp.ndarray,   # (L, P+1, KV) f32
    s_phys: jnp.ndarray,  # (R, K) source physical pages
    s_off: jnp.ndarray,   # (R, K) source in-page offsets
    d_phys: jnp.ndarray,  # (R, K) destination physical pages
    d_off: jnp.ndarray,   # (R, K) destination in-page offsets
    qmax: float,
):
    """Move quantized lines between table-resolved positions (the
    SpecInfer KV commit, models/*.commit_kv_paged): dequantize the
    source lines at their page scales, then re-commit them through
    :func:`quant_line_write` so destination page scales stay exact
    (codes cannot move between pages verbatim — the pages' scales
    differ). Vectorized over the layer dim. Packed (int4) pools unpack
    the source nibbles here; the write side repacks. Returns
    ``(buf, scale)``."""
    rows = buf[:, s_phys, s_off]                        # (L, R, K, KV, dkp)
    rows = (
        unpack_nibbles(rows) if pool_pack(buf) == 2
        else rows.astype(jnp.float32)
    )                                                   # (L, R, K, KV, dk)
    rows = rows * scale[:, s_phys][..., None]           # dequant at src scale
    return jax.vmap(
        lambda b, s, r: quant_line_write(b, s, d_phys, d_off, r, qmax)
    )(buf, scale, rows)


def page_bytes(
    page_size: int,
    kv_heads: int,
    head_dim: int,
    itemsize: int,
    *,
    scale_heads: int = 0,
) -> int:
    """K+V bytes one physical page costs per layer: two pools of
    ``page_size × kv_heads × head_dim`` elements plus (quantized
    layouts) two f32 scale rows of ``scale_heads`` entries."""
    return 2 * (page_size * kv_heads * head_dim * itemsize
                + 4 * scale_heads)


def quantized_pool_pages(
    fp_pages: int,
    page_size: int,
    kv_heads: int,
    head_dim: int,
    fp_itemsize: int,
    spec: KVQuantSpec,
) -> int:
    """Bytes-per-page accounting: the number of QUANTIZED pages the HBM
    budget of ``fp_pages`` full-precision pages buys. This is how
    ``ServingConfig.max_cached_tokens`` keeps meaning "this much KV
    HBM" with ``kv_quant`` on — the same budget simply holds ~2x the
    pages at int8 and ~4x at packed int4 (vs bf16; the per-page f32
    scales cost ``8·KV / (2·KV·dk·itemsize)`` of a page, well under 1%
    at real head dims, which is why the measured ratios land at ≥1.9x
    and ≥3.8x rather than exactly 2x/4x)."""
    budget = fp_pages * page_bytes(page_size, kv_heads, head_dim,
                                   fp_itemsize)
    # pack>1 stores several codes per element along dk
    qpage = page_bytes(
        page_size, kv_heads, -(-head_dim // spec.pack), spec.itemsize,
        scale_heads=kv_heads,
    )
    return max(fp_pages, budget // qpage)
