"""SpecInfer — speculative inference with token-tree verification.

TPU-native counterpart of the reference SpecInfer loop (reference
``src/runtime/request_manager.cc:2349-2421`` ``generate_spec_infer``,
``BeamSearchBatchConfig``/``TreeVerifyBatchConfig`` ``batch_config.h:
133-190``, and the spec/tree attention kernels ``spec_inc_multihead_self_
attention.cu``, ``tree_inc_multihead_self_attention.cu``):

* A small speculative model (SSM) grows a **token tree** per request by
  beam expansion. Tree nodes live in the *speculative slack region* of
  the SSM's own KV cache — each frontier step runs the shared
  ``serve_step`` in tree-mask mode (siblings share a RoPE position
  ``prefix+depth`` but occupy distinct cache lines ``prefix+node``), so
  beams fork without copying any cache (the reference's sub-request
  beam attention achieves the same sharing).
* The LLM **verifies the whole tree in one step** with a causal bitmask
  (ancestors-or-self), the reference's tree-verify attention.
* The longest accepted root path is **committed** by moving its K/V
  lines inside both caches (``commit_kv``) — the SSM therefore never
  re-prefills committed tokens.

Greedy verification: accepted output is token-identical to incremental
greedy decoding (the property the reference's inference tests assert,
``tests/inference/python_inference_tests.sh:111-123``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .batch_config import BatchConfig, GenerationConfig
from .engine import InferenceEngine
from .request_manager import Request, RequestManager, RequestStatus


@jax.jit
def _greedy(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


class TokenTree:
    """Host-side speculative token tree (reference ``BeamTree``,
    batch_config.h:157-190 + RequestManager::traverse_beam_tree)."""

    def __init__(self, root_token: int):
        self.tokens: List[int] = [int(root_token)]
        self.parents: List[int] = [-1]
        self.depths: List[int] = [0]
        self.logprobs: List[float] = [0.0]
        # O(1) dedup + child lookup: at reference scale (64 requests x
        # 64-token trees, request_manager.h MAX_NUM_REQUESTS) the per-
        # insert linear scan was O(n^2) per speculation round
        self._index: dict = {}
        self._children: List[List[int]] = [[]]

    def __len__(self) -> int:
        return len(self.tokens)

    def add(self, token: int, parent: int, logprob: float) -> Tuple[int, bool]:
        """Add a child; duplicate (parent, token) pairs are merged (the
        analog of the reference's merge_dfs_trees dedup). Returns
        (node index, is_new)."""
        key = (int(parent), int(token))
        hit = self._index.get(key)
        if hit is not None:
            return hit, False
        self.tokens.append(int(token))
        self.parents.append(int(parent))
        self.depths.append(self.depths[parent] + 1)
        self.logprobs.append(float(logprob))
        idx = len(self.tokens) - 1
        self._index[key] = idx
        self._children.append([])
        self._children[parent].append(idx)
        return idx, True

    def append_raw(self, token: int, parent: int, depth: int,
                   logprob: float) -> int:
        """Append WITHOUT dedup — the device-side growth has a fixed
        (D, W) node layout where duplicate (parent, token) pairs are
        legitimate (dedup happens later in merge_trees). Maintains the
        child lists accept_greedy walks."""
        self.tokens.append(int(token))
        self.parents.append(int(parent))
        self.depths.append(int(depth))
        self.logprobs.append(float(logprob))
        idx = len(self.tokens) - 1
        self._index.setdefault((int(parent), int(token)), idx)
        self._children.append([])
        self._children[parent].append(idx)
        return idx

    def children(self, node: int) -> List[int]:
        return self._children[node]

    def ancestor_matrix(self) -> np.ndarray:
        """anc[i, j] = node j is an ancestor of i or i itself — the causal
        BitMask of the reference (batch_config.h:85-99)."""
        n = len(self.tokens)
        anc = np.zeros((n, n), bool)
        for i in range(n):
            j = i
            while j >= 0:
                anc[i, j] = True
                j = self.parents[j]
        return anc

    def accept_greedy(self, greedy_next: np.ndarray) -> Tuple[List[int], int]:
        """Walk from the root accepting children that match the LLM's
        greedy prediction (reference traverse_verify_tree). Returns
        (accepted node indices incl. root, bonus token)."""
        path = [0]
        cur = 0
        while True:
            target = int(greedy_next[cur])
            nxt = None
            for c in self.children(cur):
                if self.tokens[c] == target:
                    nxt = c
                    break
            if nxt is None:
                return path, target
            path.append(nxt)
            cur = nxt


def merge_trees(trees: List["TokenTree"]) -> "TokenTree":
    """Merge per-SSM token trees into one deduplicated tree — the
    reference's ``merge_dfs_trees`` (request_manager.h:178-189): shared
    (parent, token) branches collapse so the LLM verifies each distinct
    continuation once, keeping the max logprob of merged duplicates."""
    assert trees and all(
        t.tokens[0] == trees[0].tokens[0] for t in trees
    ), "trees must share the root (last committed) token"
    merged = TokenTree(trees[0].tokens[0])
    for tree in trees:
        remap = {0: 0}
        for i in range(1, len(tree)):
            parent = remap[tree.parents[i]]
            idx, is_new = merged.add(tree.tokens[i], parent, tree.logprobs[i])
            if not is_new:
                merged.logprobs[idx] = max(
                    merged.logprobs[idx], tree.logprobs[i]
                )
            remap[i] = idx
    return merged


@dataclasses.dataclass
class SpecConfig:
    """Speculation shape (reference MAX_BEAM_WIDTH=3 / MAX_BEAM_DEPTH=8,
    batch_config.h:157-161)."""

    beam_width: int = 2
    beam_depth: int = 4

    @property
    def max_tree_tokens(self) -> int:
        return 1 + self.beam_width * self.beam_depth


class SpecInferManager(RequestManager):
    """Request manager driving the SSM-speculate → LLM-verify loop.

    The LLM engine and SSM engine share slot assignment and serving
    limits; both caches always hold the same committed prefix per slot.
    """

    # The fused decode pipeline bypasses _run_batch and would desync the
    # SSM cache; spec rounds have their own device-side batching anyway.
    supports_fast_decode = False
    # Prefix caching splices pages in ONE engine's pool; the SSM pools
    # page independently, so a spliced LLM prefix would leave the SSM
    # cache cold and desync verification — opt out.
    supports_prefix_cache = False
    # run_sampled bypasses the _run_batch hook that keeps the SSM cache
    # in step with the LLM's — the fused sampling sync path would
    # desync verification, so spec managers keep step + host sample.
    supports_fused_sampling = False

    def __init__(
        self,
        llm_engine: InferenceEngine,
        ssm_engines,  # one engine or a list (multi-SSM tree merge)
        spec: Optional[SpecConfig] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        output_file: Optional[str] = None,
    ):
        super().__init__(llm_engine, tokenizer, eos_token_id, seed, output_file)
        if isinstance(ssm_engines, InferenceEngine):
            ssm_engines = [ssm_engines]
        self.ssms: List[InferenceEngine] = list(ssm_engines)
        assert self.ssms, "SpecInferManager needs at least one SSM"
        self.spec = spec or SpecConfig()
        for ssm_engine in self.ssms:
            assert (
                ssm_engine.num_slots == llm_engine.num_slots
                and ssm_engine.serving.cache_len == llm_engine.serving.cache_len
            ), "LLM and SSM engines must share serving limits"
            assert llm_engine.cfg.vocab_size == ssm_engine.cfg.vocab_size, (
                "LLM/SSM vocab mismatch: draft tokens would be silently "
                "clipped at the verifier's embedding"
            )
        # A merged multi-SSM tree is at worst the concatenation of the
        # per-SSM trees (dedup only shrinks it).
        assert (
            self.max_merged_tokens <= llm_engine.serving.max_spec_tree_tokens
        ), "merged tree larger than the cache's speculative slack region"
        assert all(
            getattr(s, "paged", False) == getattr(llm_engine, "paged", False)
            for s in self.ssms
        ), "LLM and SSM engines must agree on kv_layout"

    @property
    def max_merged_tokens(self) -> int:
        return 1 + len(self.ssms) * (self.spec.max_tree_tokens - 1)

    @property
    def ssm(self) -> InferenceEngine:
        """Primary SSM (kept for single-SSM callers/tests)."""
        return self.ssms[0]

    def _engines(self):
        """Page allocation/reclaim happens on the LLM and every SSM in
        lockstep (shared slots + serving limits; pools sized per
        engine)."""
        return [self.engine, *self.ssms]

    def _spec_lines(self, req: Request) -> int:
        """Cache lines a speculate→verify→commit round touches: the
        committed prefix plus the merged tree's slack lines (node i
        writes line prefix + i)."""
        return req.n_cached + self.max_merged_tokens + 1

    # ------------------------------------------------------------------
    # batch builders

    def _tree_chunk_batch(
        self,
        engine: InferenceEngine,
        reqs: List[Request],
        trees: Dict[int, TokenTree],
        node_lists: Dict[int, List[int]],
        chunk: int,
    ) -> BatchConfig:
        """Batch feeding, per request, the tree nodes in ``node_lists``
        (new frontier for SSM expansion; all nodes for LLM verify).
        RoPE position = prefix + depth; cache line = prefix + node index;
        mask = committed prefix + ancestors-or-self."""
        S1 = engine.serving.cache_len + 1
        R = engine.num_slots
        bc = BatchConfig.empty(R, chunk, engine.scratch_pos)
        bc.cache_positions = np.full((R, chunk), engine.scratch_pos, np.int32)
        bc.mask = np.zeros((R, chunk, S1), bool)
        for req in reqs:
            tree = trees[req.request_id]
            nodes = node_lists[req.request_id]
            anc = tree.ancestor_matrix()
            prefix = req.n_cached
            for c, node in enumerate(nodes):
                bc.tokens[req.slot, c] = tree.tokens[node]
                bc.positions[req.slot, c] = prefix + tree.depths[node]
                bc.cache_positions[req.slot, c] = prefix + node
                bc.mask[req.slot, c, :prefix] = True
                bc.mask[req.slot, c, prefix : prefix + len(tree)] = anc[node]
            bc.active[req.slot] = True
        if getattr(engine, "paged", False):
            bc.page_table = engine.pager.table.copy()
        return bc

    # ------------------------------------------------------------------
    # the SpecInfer round

    def _grow_trees_one_ssm(
        self, ssm: InferenceEngine, reqs: List[Request]
    ) -> Dict[int, TokenTree]:
        """One SSM's beam expansion (reference prepare_next_batch_beam
        loop, request_manager.cc:2397-2407), executed as a single
        device-side program: the whole depth × top-W expansion runs in
        one compiled scan (engine.run_speculate) and the host fetches
        the finished tree in one transfer — no per-depth round trips.

        Trees are built WITHOUT (parent, token) dedup so node index i
        stays identical to the cache slack line prefix+i the device
        wrote (duplicates merely occupy verify slots the tree budget
        already reserves)."""
        W, D = self.spec.beam_width, self.spec.beam_depth
        R = self.engine.num_slots
        root = np.zeros((R,), np.int32)
        prefix = np.full((R,), self.engine.scratch_pos, np.int32)
        active = np.zeros((R,), bool)
        for req in reqs:
            root[req.slot] = req.tokens[-1]
            prefix[req.slot] = req.n_cached
            active[req.slot] = True
        # ffcheck: disable=FF107 -- SpecInfer fetches the finished speculation tree in ONE transfer per round by design (the host builds the verify batch from it)
        toks, parents, logps = jax.device_get(
            ssm.run_speculate(root, prefix, active, W, D)
        )  # one transfer; each (D, R, W)
        toks, parents, logps = (
            np.asarray(toks), np.asarray(parents), np.asarray(logps)
        )

        trees: Dict[int, TokenTree] = {}
        for req in reqs:
            s = req.slot
            tree = TokenTree(int(root[s]))
            for d in range(D):
                for w in range(W):
                    tree.append_raw(
                        int(toks[d, s, w]),
                        0 if d == 0 else 1 + (d - 1) * W + int(parents[d, s, w]),
                        d + 1,
                        float(logps[d, s, w]),
                    )
            trees[req.request_id] = tree
            req.profile.ssm_decoding_steps += D
        return trees

    def _grow_trees(self, reqs: List[Request]) -> Dict[int, TokenTree]:
        """All SSMs speculate independently; their trees merge with
        dedup (reference generate_spec_infer's per-SSM loop +
        merge_dfs_trees, request_manager.cc:2397-2410)."""
        per_ssm = [self._grow_trees_one_ssm(ssm, reqs) for ssm in self.ssms]
        if len(per_ssm) == 1:
            return per_ssm[0]
        return {
            r.request_id: merge_trees(
                [trees[r.request_id] for trees in per_ssm]
            )
            for r in reqs
        }

    def _verify_and_commit(
        self, reqs: List[Request], trees: Dict[int, TokenTree]
    ):
        """LLM tree-verify step + greedy acceptance + KV commit on all
        caches (reference prepare_next_batch_verify + tree attention +
        commit_tokens)."""
        C = self.max_merged_tokens
        node_lists = {
            r.request_id: list(range(len(trees[r.request_id]))) for r in reqs
        }
        bc = self._tree_chunk_batch(self.engine, reqs, trees, node_lists, C)
        logits = self.engine.run(bc, all_logits=True)  # (R, C, V)
        # ffcheck: disable=FF107 -- tree verify: the host acceptance walk needs the greedy tokens; one transfer per round by design
        greedy = np.asarray(jax.device_get(_greedy(logits)))  # (R, C)
        accepted: Dict[int, Tuple[int, List[int]]] = {}  # rid -> (slot, path tokens)

        R = self.engine.num_slots
        K = self.spec.beam_depth + 1  # deepest acceptable path (any SSM)
        scratch = self.engine.scratch_pos
        src = np.full((R, K), scratch, np.int32)
        dst = np.full((R, K), scratch, np.int32)
        for req in reqs:
            tree = trees[req.request_id]
            path, bonus = tree.accept_greedy(greedy[req.slot])
            prefix = req.n_cached
            for k, node in enumerate(path):
                src[req.slot, k] = prefix + node
                dst[req.slot, k] = prefix + k
            req.profile.speculated_tokens += len(tree) - 1
            req.profile.accepted_tokens += len(path) - 1
            req.profile.llm_decoding_steps += 1
            # Tokens: path nodes beyond the root are newly committed
            # outputs; the bonus token is the LLM's own next sample.
            new_tokens = [tree.tokens[n] for n in path[1:]] + [bonus]
            # capture the slot NOW: _append_token may complete the
            # request and free it
            accepted[req.request_id] = (req.slot, [tree.tokens[n] for n in path])
            req.n_cached += len(path)
            for t in new_tokens:
                if req.status is RequestStatus.DECODING:
                    self._append_token(req, t)
        self.engine.commit(src, dst)
        if len(self.ssms) == 1:
            # Single SSM: the merged tree IS its own tree, so the
            # accepted nodes sit at the same slack lines — cheap line
            # move.
            self.ssms[0].commit(src, dst)
        else:
            # Multi-SSM: each SSM's slack region is laid out by its own
            # pre-merge tree indices, so merged-index line moves would
            # commit the wrong lines. Recompute instead: feed the
            # accepted tokens through every SSM at their committed
            # positions (the reference's beam-init recompute,
            # prepare_next_batch_init).
            self._refeed_accepted(reqs, accepted)

    def _refeed_accepted(self, reqs, accepted):
        """Write the accepted tokens' K/V into every SSM cache by
        running them as ordinary causal inputs at committed positions."""
        K = self.spec.beam_depth + 1
        R = self.engine.num_slots
        scratch = self.engine.scratch_pos
        bc = BatchConfig.empty(R, K, scratch)
        for req in reqs:
            slot, toks = accepted[req.request_id]
            start = req.n_cached - len(toks)  # n_cached already advanced
            bc.tokens[slot, : len(toks)] = toks
            bc.positions[slot, : len(toks)] = np.arange(
                start, start + len(toks)
            )
            bc.logits_idx[slot] = len(toks) - 1
            bc.active[slot] = True
        for ssm in self.ssms:
            ssm.run(bc)

    # ------------------------------------------------------------------
    # scheduling

    def register_request(self, prompt, gen: Optional[GenerationConfig] = None):
        gen = gen or GenerationConfig()
        if gen.do_sample:
            # Greedy tree verification cannot honor sampling configs —
            # fail loudly rather than emit a hybrid output (the reference
            # spec path is greedy too; its tests diff spec vs incr greedy).
            raise ValueError(
                "SpecInferManager is greedy-only; use RequestManager for "
                "sampling requests"
            )
        return super().register_request(prompt, gen)

    def _run_batch(self, bc):
        logits = self.engine.run(bc)
        for ssm in self.ssms:
            ssm.run(bc)  # same tokens into every SSM cache
        return logits

    def step(self) -> bool:
        """One SpecInfer scheduling step (reference generate_spec_infer
        loop body). While anyone is prefilling, the mixed batch (prefill
        chunks + decode tokens) goes through BOTH engines (the
        ``_run_batch`` hook) so decoding slots keep making one-token
        progress with the caches in sync — no head-of-line blocking;
        otherwise one full speculate→verify→commit round runs for all
        decoding requests."""
        self._admit_pending()
        if self._active(RequestStatus.PREFILLING):
            return super().step()
        # paged KV: a spec round writes the whole tree's slack lines —
        # reserve prefix + merged-tree pages on the LLM and every SSM
        self._reserve_active_pages(self._spec_lines)
        decoding = self._active(RequestStatus.DECODING)
        if decoding:
            trees = self._grow_trees(decoding)
            self._verify_and_commit(decoding, trees)
            self._step_counter += 1
            return True
        return bool(self.pending)
