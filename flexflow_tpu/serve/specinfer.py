"""SpecInfer — speculative inference with token-tree verification.

TPU-native counterpart of the reference SpecInfer loop (reference
``src/runtime/request_manager.cc:2349-2421`` ``generate_spec_infer``,
``BeamSearchBatchConfig``/``TreeVerifyBatchConfig`` ``batch_config.h:
133-190``, and the spec/tree attention kernels ``spec_inc_multihead_self_
attention.cu``, ``tree_inc_multihead_self_attention.cu``):

* A small speculative model (SSM) grows a **token tree** per request by
  beam expansion. Tree nodes live in the *speculative slack region* of
  the SSM's own KV cache — each frontier step runs the shared
  ``serve_step`` in tree-mask mode (siblings share a RoPE position
  ``prefix+depth`` but occupy distinct cache lines ``prefix+node``), so
  beams fork without copying any cache (the reference's sub-request
  beam attention achieves the same sharing).
* The LLM **verifies the whole tree in one step** with a causal bitmask
  (ancestors-or-self), the reference's tree-verify attention.
* The longest accepted root path is **committed** by moving its K/V
  lines inside both caches (``commit_kv``) — the SSM therefore never
  re-prefills committed tokens.

Greedy verification: accepted output is token-identical to incremental
greedy decoding (the property the reference's inference tests assert,
``tests/inference/python_inference_tests.sh:111-123``).

Beyond the reference loop, speculation here is **adaptive and
composable**:

* **Acceptance-driven tree shaping** (``SpecConfig.adaptive``): a
  per-request :class:`TreeController` tracks an EMA of the accepted
  path length per verify round and moves the request along a BUCKETED
  W×D ladder (``SpecConfig.bucket_ladder``) — toward narrow shallow
  trees when the draft keeps missing (hard prompts: stop paying a wide
  tree for one accepted token), toward the full tree when paths accept
  at depth. Buckets — never free-form shapes — bound compilation: each
  rung costs exactly one speculate program and one verify-chunk
  program, proven by the retrace guard (tests/test_retrace_guard.py).
  The controller reads acceptance from the greedy tokens the verify
  round ALREADY fetched — no extra transfer (ffcheck FF107).
* **Prefix caching** (``supports_prefix_cache=True``): a radix-tree
  hit jumps the LLM *and every SSM* past the cached prefix — the
  pools page independently but share the token offset math, so the
  manager keeps one :class:`~.prefix_cache.PrefixCache` per pool and
  aligns every admission's matched length across them
  (:meth:`SpecInferManager._cache_attach`).
* **Continuous batching**: while anyone is prefilling, requests ride
  the PR-2 dispatch-ahead mixed step — dispatched on the LLM and
  MIRRORED into every SSM (``_mirror_dispatch``) so all caches advance
  in lockstep without a host round-trip; speculation rounds resume the
  moment nobody is prefilling.
* **Self-speculation** (``SpecConfig.draft="early_exit"``): the draft
  is the target's own first ``draft_layers`` blocks (a layer-sliced
  ``serve_step`` over the SAME params and paged KV — zero extra
  model, zero extra cache), verified by the unchanged tree path.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .batch_config import BatchConfig, GenerationConfig
from .engine import InferenceEngine
from .request_manager import Request, RequestManager, RequestStatus


@jax.jit
def _greedy(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


class TokenTree:
    """Host-side speculative token tree (reference ``BeamTree``,
    batch_config.h:157-190 + RequestManager::traverse_beam_tree)."""

    def __init__(self, root_token: int):
        self.tokens: List[int] = [int(root_token)]
        self.parents: List[int] = [-1]
        self.depths: List[int] = [0]
        self.logprobs: List[float] = [0.0]
        # O(1) dedup + child lookup: at reference scale (64 requests x
        # 64-token trees, request_manager.h MAX_NUM_REQUESTS) the per-
        # insert linear scan was O(n^2) per speculation round
        self._index: dict = {}
        self._children: List[List[int]] = [[]]

    def __len__(self) -> int:
        return len(self.tokens)

    def add(self, token: int, parent: int, logprob: float) -> Tuple[int, bool]:
        """Add a child; duplicate (parent, token) pairs are merged (the
        analog of the reference's merge_dfs_trees dedup). Returns
        (node index, is_new)."""
        key = (int(parent), int(token))
        hit = self._index.get(key)
        if hit is not None:
            return hit, False
        self.tokens.append(int(token))
        self.parents.append(int(parent))
        self.depths.append(self.depths[parent] + 1)
        self.logprobs.append(float(logprob))
        idx = len(self.tokens) - 1
        self._index[key] = idx
        self._children.append([])
        self._children[parent].append(idx)
        return idx, True

    def append_raw(self, token: int, parent: int, depth: int,
                   logprob: float) -> int:
        """Append WITHOUT dedup — the device-side growth has a fixed
        (D, W) node layout where duplicate (parent, token) pairs are
        legitimate (dedup happens later in merge_trees). Maintains the
        child lists accept_greedy walks."""
        self.tokens.append(int(token))
        self.parents.append(int(parent))
        self.depths.append(int(depth))
        self.logprobs.append(float(logprob))
        idx = len(self.tokens) - 1
        self._index.setdefault((int(parent), int(token)), idx)
        self._children.append([])
        self._children[parent].append(idx)
        return idx

    def children(self, node: int) -> List[int]:
        return self._children[node]

    def ancestor_matrix(self) -> np.ndarray:
        """anc[i, j] = node j is an ancestor of i or i itself — the causal
        BitMask of the reference (batch_config.h:85-99)."""
        n = len(self.tokens)
        anc = np.zeros((n, n), bool)
        for i in range(n):
            j = i
            while j >= 0:
                anc[i, j] = True
                j = self.parents[j]
        return anc

    def accept_greedy(self, greedy_next: np.ndarray) -> Tuple[List[int], int]:
        """Walk from the root accepting children that match the LLM's
        greedy prediction (reference traverse_verify_tree). Returns
        (accepted node indices incl. root, bonus token)."""
        path = [0]
        cur = 0
        while True:
            target = int(greedy_next[cur])
            nxt = None
            for c in self.children(cur):
                if self.tokens[c] == target:
                    nxt = c
                    break
            if nxt is None:
                return path, target
            path.append(nxt)
            cur = nxt

    def used_width(self, path: List[int]) -> bool:
        """True when some accepted step took a child a WIDTH-1 tree
        would not have drafted — i.e. the accepted child was not its
        parent's highest-logprob candidate. The TreeController's
        width-utility signal: rounds where every accepted step is the
        draft's top pick would have committed identically from a
        narrow tree at a fraction of the drafted tokens."""
        for parent, node in zip(path, path[1:]):
            kids = self._children[parent]
            if len(kids) > 1 and node != max(
                kids, key=lambda c: self.logprobs[c]
            ):
                return True
        return False


def merge_trees(trees: List["TokenTree"]) -> "TokenTree":
    """Merge per-SSM token trees into one deduplicated tree — the
    reference's ``merge_dfs_trees`` (request_manager.h:178-189): shared
    (parent, token) branches collapse so the LLM verifies each distinct
    continuation once, keeping the max logprob of merged duplicates."""
    assert trees and all(
        t.tokens[0] == trees[0].tokens[0] for t in trees
    ), "trees must share the root (last committed) token"
    merged = TokenTree(trees[0].tokens[0])
    for tree in trees:
        remap = {0: 0}
        for i in range(1, len(tree)):
            parent = remap[tree.parents[i]]
            idx, is_new = merged.add(tree.tokens[i], parent, tree.logprobs[i])
            if not is_new:
                merged.logprobs[idx] = max(
                    merged.logprobs[idx], tree.logprobs[i]
                )
            remap[i] = idx
    return merged


def default_buckets(width: int, depth: int) -> Tuple[Tuple[int, int], ...]:
    """Deterministic W×D ladder from (1, 1) up to (width, depth): depth
    doubles first at width 1 (narrow deep chains are the cheap way to
    keep multi-token commits when the draft is good), then width steps
    up at full depth. Each rung costs exactly one speculate program and
    one verify-chunk program — the bounded step-key set the retrace
    guard asserts."""
    ladder: List[Tuple[int, int]] = [(1, 1)]
    d = 1
    while d < depth:
        d = min(depth, d * 2)
        ladder.append((1, d))
    w = 1
    while w < width:
        w = min(width, w * 2)
        ladder.append((w, depth))
    out: List[Tuple[int, int]] = []
    for b in ladder:
        if b not in out:
            out.append(b)
    return tuple(out)


@dataclasses.dataclass
class SpecConfig:
    """Speculation shape + adaptivity (reference MAX_BEAM_WIDTH=3 /
    MAX_BEAM_DEPTH=8, batch_config.h:157-161).

    ``beam_width``/``beam_depth`` bound the token tree; with
    ``adaptive=False`` (default) every round drafts that full shape.

    ``adaptive=True`` turns on acceptance-driven tree shaping: each
    request carries a :class:`TreeController` that EMA-tracks its
    accepted path length and moves it along ``bucket_ladder`` — shrink
    toward (1, 1) when acceptance is poor, grow back when paths accept
    at full depth. ``buckets`` overrides the default ladder (must stay
    within the configured bounds and end at the full shape — the cache
    slack region is sized for it).

    ``draft`` selects the draft source: ``"ssm"`` (external draft
    engines, the reference's SSMs) or ``"early_exit"`` — self-
    speculation from the target's own first ``draft_layers`` blocks
    (LayerSkip-style): a layer-sliced ``serve_step`` over the SAME
    params and KV cache drafts the tree, the full stack verifies it.
    Zero extra model, zero extra cache — the verify pass re-writes
    every tree line anyway, so the shallow draft's K/V never leaks
    into committed state.

    ``verify_skip`` (requires ``adaptive``) is the acceptance-weighted
    escape hatch below the ladder's floor: a request whose controller
    sits at the SMALLEST rung with a near-zero acceptance EMA (≤
    ``skip_threshold`` × depth) skips the speculate+verify dispatches
    entirely and rides the incremental decode path — a cold draft then
    costs ~zero, so speculation is strictly never worse than
    non-speculative continuous batching. Every ``reprobe_every``
    skipped rounds ONE cheap smallest-rung round runs to re-measure
    the draft; an accepting re-probe warms the EMA back over the
    threshold and the request resumes speculating.
    """

    beam_width: int = 2
    beam_depth: int = 4
    # acceptance-driven tree shaping (TreeController)
    adaptive: bool = False
    buckets: Optional[Tuple[Tuple[int, int], ...]] = None
    ema_alpha: float = 0.5
    grow_threshold: float = 0.8
    shrink_threshold: float = 0.3
    # width-utility gate: the EMA of "did some accepted step take a
    # non-top sibling" (TokenTree.used_width) must be at least this to
    # grow into — or stay on — a wider-same-depth rung; below it the
    # controller drops width a narrow tree would have matched for free
    width_threshold: float = 0.1
    # draft source: "ssm" | "early_exit"
    draft: str = "ssm"
    draft_layers: int = 0
    # acceptance-weighted verify-skip (cold drafts ride the
    # incremental decode path; periodic re-probe at the smallest rung)
    verify_skip: bool = False
    skip_threshold: float = 0.1
    reprobe_every: int = 8

    def __post_init__(self):
        if self.beam_width < 1 or self.beam_depth < 1:
            raise ValueError(
                f"beam_width/beam_depth must be >= 1 (got "
                f"{self.beam_width}x{self.beam_depth})"
            )
        if self.draft not in ("ssm", "early_exit"):
            raise ValueError(
                f"unknown draft {self.draft!r} (expected 'ssm' or "
                "'early_exit')"
            )
        if self.draft == "early_exit" and self.draft_layers < 1:
            raise ValueError(
                "draft='early_exit' needs draft_layers >= 1 — the layer "
                "count of the target's truncated draft stack"
            )
        if not 0.0 < self.ema_alpha <= 1.0:
            raise ValueError(
                f"ema_alpha must be in (0, 1] (got {self.ema_alpha})"
            )
        if not 0.0 <= self.shrink_threshold < self.grow_threshold <= 1.0:
            raise ValueError(
                "thresholds must satisfy 0 <= shrink < grow <= 1 (got "
                f"shrink={self.shrink_threshold}, "
                f"grow={self.grow_threshold})"
            )
        if not 0.0 <= self.width_threshold <= 1.0:
            raise ValueError(
                f"width_threshold must be in [0, 1] (got "
                f"{self.width_threshold})"
            )
        if self.verify_skip and not self.adaptive:
            raise ValueError(
                "verify_skip requires adaptive=True — the skip decision "
                "reads the TreeController's rung and acceptance EMA"
            )
        if not 0.0 <= self.skip_threshold < 1.0:
            raise ValueError(
                f"skip_threshold must be in [0, 1) (got "
                f"{self.skip_threshold})"
            )
        if self.skip_threshold > self.shrink_threshold:
            raise ValueError(
                "skip_threshold must not exceed shrink_threshold — the "
                "skip regime sits BELOW the ladder's floor (got "
                f"skip={self.skip_threshold} > "
                f"shrink={self.shrink_threshold})"
            )
        if self.reprobe_every < 1:
            raise ValueError(
                f"reprobe_every must be >= 1 (got {self.reprobe_every})"
            )
        if self.buckets is not None:
            ladder = tuple(
                (int(w), int(d)) for w, d in self.buckets
            )
            if not ladder:
                raise ValueError("buckets must be non-empty")
            if len(set(ladder)) != len(ladder):
                raise ValueError(f"duplicate buckets in {ladder}")
            for w, d in ladder:
                if not (1 <= w <= self.beam_width
                        and 1 <= d <= self.beam_depth):
                    raise ValueError(
                        f"bucket {w}x{d} outside the configured bounds "
                        f"{self.beam_width}x{self.beam_depth}"
                    )
            if ladder[-1] != (self.beam_width, self.beam_depth):
                raise ValueError(
                    "the bucket ladder must end at the configured "
                    f"{self.beam_width}x{self.beam_depth} — the cache "
                    "slack region is sized for the full tree"
                )
            if any(
                ladder[i][0] * ladder[i][1]
                >= ladder[i + 1][0] * ladder[i + 1][1]
                for i in range(len(ladder) - 1)
            ):
                raise ValueError(
                    f"buckets must grow strictly in tree tokens: {ladder}"
                )
            self.buckets = ladder

    @property
    def bucket_ladder(self) -> Tuple[Tuple[int, int], ...]:
        """The W×D rungs adaptive shaping moves along (smallest first;
        the single full shape when ``adaptive`` is off)."""
        if self.buckets is not None:
            return self.buckets
        if not self.adaptive:
            return ((self.beam_width, self.beam_depth),)
        return default_buckets(self.beam_width, self.beam_depth)

    @property
    def max_tree_tokens(self) -> int:
        return 1 + self.beam_width * self.beam_depth


class TreeController:
    """Per-request acceptance-driven tree shaping.

    Folds each verify round's accepted path length (drafted tokens the
    verifier accepted) into an EMA and moves the request one rung along
    the bucket ladder when the EMA leaves the hysteresis band: EMA ≤
    ``shrink_threshold``·D shrinks, EMA ≥ ``grow_threshold``·D grows —
    but only depth earns growth for free. WIDTH is gated on its own
    utility EMA (``TokenTree.used_width``: did an accepted step take a
    non-top sibling?): a request whose fully-accepted chains never
    touch a second branch will not grow into a wider rung, and when it
    is already sitting on one it steps DOWN to the narrow same-depth
    rung — the narrow tree would have committed the identical path at
    a fraction of the drafted tokens, which is exactly the drafted-
    accept-rate waste this controller exists to cut.

    On a resize the EMA is clamped INTO the new rung's band so one
    stale average cannot chain resizes — the trajectory is a pure,
    deterministic function of the acceptance sequence, and the
    acceptance sequence itself comes from the greedy tokens the verify
    round already fetched (no extra ``device_get``, ffcheck FF107).

    Starts at the FULL tree (the fixed-shape baseline's behavior) and
    earns its way down: a cold request speculates exactly like the
    non-adaptive manager until its own acceptance says otherwise.
    """

    def __init__(self, spec: SpecConfig):
        self.spec = spec
        self.ladder = spec.bucket_ladder
        self.idx = len(self.ladder) - 1
        # mid-band prior: "good enough to stay" — not "perfect". A
        # perfect-acceptance prior would make a hard prompt pay several
        # full-size rounds just to walk the EMA down; mid-band keeps the
        # cold request at the baseline shape yet lets ONE bad round
        # start the descent.
        depth = float(self.ladder[self.idx][1])
        self.ema = 0.5 * (
            spec.shrink_threshold + spec.grow_threshold
        ) * depth
        self.width_ema = 1.0                        # width presumed useful
        self.resizes = 0
        # acceptance-weighted verify-skip (SpecConfig.verify_skip):
        # rounds skipped since the last spec/re-probe round, plus
        # lifetime counters the manager mirrors into SchedulerStats
        self._skip_streak = 0
        self.skipped_rounds = 0
        self.reprobes = 0

    @property
    def bucket(self) -> Tuple[int, int]:
        return self.ladder[self.idx]

    def next_action(self) -> str:
        """One verify-skip state-machine transition — call exactly once
        per scheduling round for a DECODING request. Returns ``"spec"``
        (run a normal speculate+verify round), ``"skip"`` (ride the
        incremental decode path: the draft is cold and a tree would be
        pure overhead) or ``"reprobe"`` (the skip cadence came due —
        run the cheap smallest-rung round so a draft that warmed back
        up can exit the skip regime through :meth:`observe`).

        The skip regime engages only BELOW the ladder's floor: the
        controller must sit on rung 0 — (1, 1) on the default ladder —
        with its acceptance EMA at or under ``skip_threshold`` × depth.
        Any other state resets the streak, so a request that resizes
        upward or warms its EMA flows straight back to "spec"."""
        spec = self.spec
        if not spec.verify_skip or self.idx != 0:
            self._skip_streak = 0
            return "spec"
        _, depth = self.bucket
        if self.ema > spec.skip_threshold * depth:
            self._skip_streak = 0
            return "spec"
        if self._skip_streak >= spec.reprobe_every:
            self._skip_streak = 0
            self.reprobes += 1
            return "reprobe"
        self._skip_streak += 1
        self.skipped_rounds += 1
        return "skip"

    def observe(self, accepted_len: int, used_width: bool = False) -> bool:
        """Record one round's accepted path length (and whether tree
        width contributed to it); returns True when the bucket
        changed."""
        a = self.spec.ema_alpha
        width, depth = self.bucket
        self.ema = (1.0 - a) * self.ema + a * float(accepted_len)
        self.width_ema = (1.0 - a) * self.width_ema + a * float(
            bool(used_width)
        )
        frac = self.ema / depth
        move = 0
        if frac <= self.spec.shrink_threshold and self.idx > 0:
            move = -1
        elif frac >= self.spec.grow_threshold:
            nxt = (
                self.ladder[self.idx + 1]
                if self.idx + 1 < len(self.ladder) else None
            )
            prv = self.ladder[self.idx - 1] if self.idx > 0 else None
            if nxt is not None and (
                nxt[1] > depth
                or self.width_ema >= self.spec.width_threshold
            ):
                move = 1
            elif (
                prv is not None and prv[1] == depth and prv[0] < width
                and self.width_ema < self.spec.width_threshold
            ):
                # fully-accepting chains that never used a sibling:
                # drop the width, keep the depth
                move = -1
        if move == 0:
            return False
        self.idx += move
        self.resizes += 1
        _, new_depth = self.bucket
        lo = self.spec.shrink_threshold * new_depth
        hi = self.spec.grow_threshold * new_depth
        self.ema = min(max(self.ema, lo), hi)
        return True


class SpecInferManager(RequestManager):
    """Request manager driving the SSM-speculate → LLM-verify loop.

    The LLM engine and SSM engines share slot assignment and serving
    limits; all caches always hold the same committed prefix per slot.
    With ``SpecConfig.draft="early_exit"`` there are no SSM engines at
    all — the LLM drafts off its own truncated layer stack.
    """

    # The LLM-only fast decode pipeline bypasses _run_batch and would
    # desync the SSM caches; pure-decode iterations run speculation
    # rounds instead, and prefill churn goes through the pipelined
    # mixed step WITH the SSM mirror (_mirror_dispatch).
    supports_fast_decode = False
    # run_sampled bypasses the _run_batch hook that keeps the SSM cache
    # in step with the LLM's — the fused sampling sync path would
    # desync verification, so spec managers keep step + host sample.
    supports_fused_sampling = False

    def __init__(
        self,
        llm_engine: InferenceEngine,
        ssm_engines=None,  # engine | [engines] | None (early-exit draft)
        spec: Optional[SpecConfig] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        output_file: Optional[str] = None,
    ):
        if isinstance(ssm_engines, InferenceEngine):
            ssm_engines = [ssm_engines]
        self.ssms: List[InferenceEngine] = list(ssm_engines or [])
        self.spec = spec or SpecConfig()
        if self.spec.draft == "early_exit":
            if self.ssms:
                raise ValueError(
                    "draft='early_exit' self-speculates off the target's "
                    "own truncated layer stack — external SSM engines "
                    "cannot be combined with it (drop ssms or use "
                    "draft='ssm')"
                )
            L = llm_engine.cfg.num_hidden_layers
            if not 1 <= self.spec.draft_layers < L:
                raise ValueError(
                    f"draft_layers={self.spec.draft_layers} must be in "
                    f"[1, {L - 1}] for this target ({L} layers): the "
                    "draft must be a strict prefix of the verifier's "
                    "stack"
                )
        elif not self.ssms:
            raise ValueError(
                "SpecInferManager needs at least one SSM engine (or "
                "SpecConfig(draft='early_exit') to self-speculate)"
            )
        super().__init__(llm_engine, tokenizer, eos_token_id, seed, output_file)
        for ssm_engine in self.ssms:
            assert (
                ssm_engine.num_slots == llm_engine.num_slots
                and ssm_engine.serving.cache_len == llm_engine.serving.cache_len
            ), "LLM and SSM engines must share serving limits"
            assert llm_engine.cfg.vocab_size == ssm_engine.cfg.vocab_size, (
                "LLM/SSM vocab mismatch: draft tokens would be silently "
                "clipped at the verifier's embedding"
            )
        # A merged multi-SSM tree is at worst the concatenation of the
        # per-SSM trees (dedup only shrinks it) at the LADDER MAX shape.
        assert (
            self.max_merged_tokens <= llm_engine.serving.max_spec_tree_tokens
        ), "merged tree larger than the cache's speculative slack region"
        assert all(
            getattr(s, "paged", False) == getattr(llm_engine, "paged", False)
            for s in self.ssms
        ), "LLM and SSM engines must agree on kv_layout"
        # per-request adaptive tree controllers (SpecConfig.adaptive)
        self._controllers: Dict[int, TreeController] = {}
        # verify-skip SSM cache debt: cache lines ending at n_cached
        # that the skipped rounds advanced on the LLM ONLY (a skipped
        # round must cost one engine step, not one per engine). Repaid
        # through _sync_ssm_caches before anything next touches the
        # mirrors (re-probe/spec round, mixed-phase mirror dispatch,
        # completion-time prefix publish).
        self._ssm_lag: Dict[int, int] = {}
        # Draft pricing (autotune cost model, 2 × params per token):
        # the denominator of spec_distill's accept-rate-per-draft-FLOP
        # utility; stamped into ProfileInfo.draft_flops_per_token.
        self.draft_flops_per_token = self._price_draft_flops()
        # Distillation harvest hook (serve/spec_distill.py): when set,
        # every verify round hands the sink (context tokens, teacher
        # logits over the accepted path) pairs. The full-logit fetch is
        # a reviewed blocking site, taken only with a sink attached —
        # production serving keeps this None.
        self.logit_sink: Optional[Any] = None
        # Prefix caching: one radix tree per SSM pool, kept in lockstep
        # with the LLM's through the _cache_attach/_cache_insert hooks
        # (insert publishes the same blocks everywhere; attach aligns
        # every pool to the common matched length). The SSM trees carry
        # no stats sink (the LLM pool's counters are THE telemetry) and
        # no host spill tier (the LLM tier is the capacity story; an
        # SSM-side miss only shortens the common match).
        self.ssm_prefix_caches: List[Any] = []
        if self.prefix_cache is not None:
            from .prefix_cache import PrefixCache

            for ssm_engine in self.ssms:
                pc = PrefixCache(
                    ssm_engine.pager,
                    copy_page=ssm_engine.copy_page,
                    policy=llm_engine.serving.cache_policy,
                )
                ssm_engine.pager.reclaim_cb = pc.reclaim
                self.ssm_prefix_caches.append(pc)

    def _price_draft_flops(self) -> float:
        """Dense FLOPs one drafted token costs in the draft stack —
        the serving cost model's forward-pass pricing (2 × params),
        summed over every SSM (a multi-draft round drafts once per
        SSM). The early-exit self-draft prices the target's first
        ``draft_layers`` blocks. This is the denominator of the
        accept-rate-per-draft-FLOP utility (serve/spec_distill.py)."""
        from .autotune.cost_model import ModelGeometry

        if self.spec.draft == "early_exit":
            cfg = dataclasses.replace(
                self.engine.cfg,
                num_hidden_layers=self.spec.draft_layers,
            )
            return 2.0 * ModelGeometry.from_model_config(cfg).param_count()
        return sum(
            2.0 * ModelGeometry.from_model_config(s.cfg).param_count()
            for s in self.ssms
        )

    @property
    def n_drafts(self) -> int:
        """Independent draft trees per round: the SSM count, or one for
        the early-exit self-draft."""
        return max(1, len(self.ssms))

    @property
    def max_merged_tokens(self) -> int:
        return 1 + self.n_drafts * (
            self.spec.beam_width * self.spec.beam_depth
        )

    @property
    def ssm(self) -> InferenceEngine:
        """Primary SSM (kept for single-SSM callers/tests)."""
        return self.ssms[0]

    def _engines(self):
        """Page allocation/reclaim happens on the LLM and every SSM in
        lockstep (shared slots + serving limits; pools sized per
        engine)."""
        return [self.engine, *self.ssms]

    def _prefix_caches(self):
        return super()._prefix_caches() + self.ssm_prefix_caches

    # ------------------------------------------------------------------
    # adaptive tree shaping

    def _ctrl(self, req: Request) -> TreeController:
        ctrl = self._controllers.get(req.request_id)
        if ctrl is None:
            ctrl = self._controllers[req.request_id] = TreeController(
                self.spec
            )
        return ctrl

    def _bucket(self, req: Request) -> Tuple[int, int]:
        """This request's CURRENT tree shape."""
        if not self.spec.adaptive:
            return (self.spec.beam_width, self.spec.beam_depth)
        return self._ctrl(req).bucket

    def _tree_tokens(self, req: Request) -> int:
        W, D = self._bucket(req)
        return 1 + self.n_drafts * W * D

    def _spec_lines(self, req: Request) -> int:
        """Cache lines a speculate→verify→commit round touches for THIS
        request: the committed prefix plus its CURRENT tree's slack
        lines (node i writes line prefix + i) — a controller-shrunk
        tree reserves proportionally fewer pages."""
        return req.n_cached + self._tree_tokens(req) + 1

    # ------------------------------------------------------------------
    # prefix-cache composition

    def _cache_attach(self, slot: int, tokens) -> int:
        """Attach the SAME matched prefix on the LLM pool and every SSM
        pool, or none at all: the engines must jump past an identical
        prefix or the SSM would draft over cold cache lines the
        verifier trusts. The common match is the MINIMUM of the
        per-pool probes; if any pool then fails to materialize it
        (page shortage mid-splice), every pool rolls back to a cold
        admission."""
        caches = [self.prefix_cache, *self.ssm_prefix_caches]
        m = min(pc.match_len(tokens) for pc in caches)
        if m <= 0:
            return 0
        got = self.prefix_cache.attach(slot, tokens, limit=m)
        ok = got > 0
        for pc in self.ssm_prefix_caches:
            if not ok:
                break
            ok = pc.attach(slot, tokens, limit=got) == got
        if not ok:
            self._release_pages(slot)
            return 0
        return got

    # ------------------------------------------------------------------
    # batch builders

    def _tree_chunk_batch(
        self,
        engine: InferenceEngine,
        reqs: List[Request],
        trees: Dict[int, TokenTree],
        node_lists: Dict[int, List[int]],
        chunk: int,
    ) -> BatchConfig:
        """Batch feeding, per request, the tree nodes in ``node_lists``
        (new frontier for SSM expansion; all nodes for LLM verify).
        RoPE position = prefix + depth; cache line = prefix + node index;
        mask = committed prefix + ancestors-or-self. ``spec_nodes``
        records the per-slot node count — with adaptive shaping the
        rows of a (bucketed) verify dispatch are ragged in tree size."""
        S1 = engine.serving.cache_len + 1
        R = engine.num_slots
        bc = BatchConfig.empty(R, chunk, engine.scratch_pos)
        bc.cache_positions = np.full((R, chunk), engine.scratch_pos, np.int32)
        bc.mask = np.zeros((R, chunk, S1), bool)
        bc.spec_nodes = np.zeros((R,), np.int32)
        for req in reqs:
            tree = trees[req.request_id]
            nodes = node_lists[req.request_id]
            anc = tree.ancestor_matrix()
            prefix = req.n_cached
            for c, node in enumerate(nodes):
                bc.tokens[req.slot, c] = tree.tokens[node]
                bc.positions[req.slot, c] = prefix + tree.depths[node]
                bc.cache_positions[req.slot, c] = prefix + node
                bc.mask[req.slot, c, :prefix] = True
                bc.mask[req.slot, c, prefix : prefix + len(tree)] = anc[node]
            bc.spec_nodes[req.slot] = len(nodes)
            bc.active[req.slot] = True
        if getattr(engine, "paged", False):
            bc.page_table = engine.pager.table.copy()
        return bc

    # ------------------------------------------------------------------
    # the SpecInfer round

    def _grow_trees_one_ssm(
        self, ssm: InferenceEngine, reqs: List[Request], W: int, D: int,
        num_layers: Optional[int] = None,
    ) -> Dict[int, TokenTree]:
        """One draft's beam expansion (reference prepare_next_batch_beam
        loop, request_manager.cc:2397-2407), executed as a single
        device-side program: the whole depth × top-W expansion runs in
        one compiled scan (engine.run_speculate) and the host fetches
        the finished tree in one transfer — no per-depth round trips.
        ``num_layers`` routes the expansion through the layer-sliced
        early-exit step (self-speculation: ``ssm`` is then the LLM
        engine itself).

        Trees are built WITHOUT (parent, token) dedup so node index i
        stays identical to the cache slack line prefix+i the device
        wrote (duplicates merely occupy verify slots the tree budget
        already reserves)."""
        R = self.engine.num_slots
        root = np.zeros((R,), np.int32)
        prefix = np.full((R,), self.engine.scratch_pos, np.int32)
        active = np.zeros((R,), bool)
        for req in reqs:
            root[req.slot] = req.tokens[-1]
            prefix[req.slot] = req.n_cached
            active[req.slot] = True
        # ffcheck: disable=FF107 -- SpecInfer fetches the finished speculation tree in ONE transfer per round by design (the host builds the verify batch from it)
        toks, parents, logps = jax.device_get(
            ssm.run_speculate(root, prefix, active, W, D,
                              num_layers=num_layers)
        )  # one transfer; each (D, R, W)
        toks, parents, logps = (
            np.asarray(toks), np.asarray(parents), np.asarray(logps)
        )

        trees: Dict[int, TokenTree] = {}
        for req in reqs:
            s = req.slot
            tree = TokenTree(int(root[s]))
            for d in range(D):
                for w in range(W):
                    tree.append_raw(
                        int(toks[d, s, w]),
                        0 if d == 0 else 1 + (d - 1) * W + int(parents[d, s, w]),
                        d + 1,
                        float(logps[d, s, w]),
                    )
            trees[req.request_id] = tree
            req.profile.ssm_decoding_steps += D
        return trees

    def _grow_trees(
        self, reqs: List[Request], W: int, D: int
    ) -> Dict[int, TokenTree]:
        """All drafts speculate independently at this round's W×D; their
        trees merge with dedup (reference generate_spec_infer's per-SSM
        loop + merge_dfs_trees, request_manager.cc:2397-2410). The
        early-exit draft is the LLM engine itself through the
        layer-sliced step — one tree, nothing to merge."""
        tr = self.tracer
        if tr.enabled:
            tr.event("spec_draft", width=W, depth=D, rows=len(reqs),
                     draft=self.spec.draft)
        if self.spec.draft == "early_exit":
            return self._grow_trees_one_ssm(
                self.engine, reqs, W, D,
                num_layers=self.spec.draft_layers,
            )
        per_ssm = [
            self._grow_trees_one_ssm(ssm, reqs, W, D) for ssm in self.ssms
        ]
        if len(per_ssm) == 1:
            return per_ssm[0]
        return {
            r.request_id: merge_trees(
                [trees[r.request_id] for trees in per_ssm]
            )
            for r in reqs
        }

    def _verify_and_commit(
        self, reqs: List[Request], trees: Dict[int, TokenTree],
        W: int, D: int,
    ):
        """LLM tree-verify step + greedy acceptance + KV commit on all
        caches (reference prepare_next_batch_verify + tree attention +
        commit_tokens). The verify chunk is the ROUND's bucket size —
        one compiled program per ladder rung; the commit src/dst keep
        the LADDER-MAX path shape so every bucket shares one commit
        program."""
        C = 1 + self.n_drafts * (W * D)
        node_lists = {
            r.request_id: list(range(len(trees[r.request_id]))) for r in reqs
        }
        bc = self._tree_chunk_batch(self.engine, reqs, trees, node_lists, C)
        logits = self.engine.run(bc, all_logits=True)  # (R, C, V)
        # ffcheck: disable=FF107 -- tree verify: the host acceptance walk needs the greedy tokens; one transfer per round by design
        greedy = np.asarray(jax.device_get(_greedy(logits)))  # (R, C)
        full_logits = None
        if self.logit_sink is not None:
            # ffcheck: disable=FF107 -- distillation harvest (serve/spec_distill.py): the attached sink needs the verify round's full teacher logits; one reviewed extra transfer per round, never taken in production serving (logit_sink stays None)
            full_logits = np.asarray(jax.device_get(logits))
        accepted: Dict[int, Tuple[int, List[int]]] = {}  # rid -> (slot, path tokens)

        R = self.engine.num_slots
        K = self.spec.beam_depth + 1  # ladder-max acceptable path
        scratch = self.engine.scratch_pos
        src = np.full((R, K), scratch, np.int32)
        dst = np.full((R, K), scratch, np.int32)
        for req in reqs:
            tree = trees[req.request_id]
            path, bonus = tree.accept_greedy(greedy[req.slot])
            prefix = req.n_cached
            for k, node in enumerate(path):
                src[req.slot, k] = prefix + node
                dst[req.slot, k] = prefix + k
            drafted = len(tree) - 1
            n_accepted = len(path) - 1
            req.profile.speculated_tokens += drafted
            req.profile.accepted_tokens += n_accepted
            req.profile.llm_decoding_steps += 1
            req.profile.spec_rounds += 1
            self.stats.spec_rounds += 1
            self.stats.spec_drafted += drafted
            self.stats.spec_accepted += n_accepted
            tr = self.tracer
            if tr.enabled:
                tr.event(
                    "spec_verify",
                    trace_id=self.trace_of(req.request_id),
                    rid=req.request_id, drafted=drafted,
                    accepted=n_accepted,
                )
            if self.spec.adaptive:
                # the controller reads acceptance from the ALREADY
                # fetched greedy walk — no extra transfer (FF107)
                ctrl = self._ctrl(req)
                if ctrl.observe(n_accepted, tree.used_width(path)):
                    self.stats.spec_resizes += 1
                    self._log.debug(
                        "spec resize: request %d %dx%d -> %dx%d "
                        "(ema %.2f, accepted %d)",
                        req.request_id, W, D, ctrl.bucket[0],
                        ctrl.bucket[1], ctrl.ema, n_accepted,
                    )
                req.profile.tree_resizes = ctrl.resizes
                req.profile.tree_width, req.profile.tree_depth = ctrl.bucket
            else:
                req.profile.tree_width, req.profile.tree_depth = W, D
            req.profile.draft_flops_per_token = self.draft_flops_per_token
            if full_logits is not None:
                # teacher rows for the accepted path: row k is the
                # verifier's next-token distribution after consuming
                # context tokens[:prefix+1+k] — exactly the on-policy
                # (prompt, target-logits) pairs distillation trains on
                self.logit_sink(
                    list(req.tokens) + [tree.tokens[n] for n in path[1:]],
                    full_logits[req.slot, path],
                )
            # Tokens: path nodes beyond the root are newly committed
            # outputs; the bonus token is the LLM's own next sample.
            new_tokens = [tree.tokens[n] for n in path[1:]] + [bonus]
            # capture the slot NOW: _append_token may complete the
            # request and free it
            accepted[req.request_id] = (req.slot, [tree.tokens[n] for n in path])
            req.n_cached += len(path)
            for t in new_tokens:
                if req.status is RequestStatus.DECODING:
                    self._append_token(req, t)
            if req.status is not RequestStatus.DECODING:
                self._controllers.pop(req.request_id, None)
        self.engine.commit(src, dst)
        if self.spec.draft == "early_exit":
            # self-draft: ONE cache — the engine commit above already
            # moved the verifier's (and therefore the draft's) lines
            pass
        elif len(self.ssms) == 1:
            # Single SSM: the merged tree IS its own tree, so the
            # accepted nodes sit at the same slack lines — cheap line
            # move.
            self.ssms[0].commit(src, dst)
        else:
            # Multi-SSM: each SSM's slack region is laid out by its own
            # pre-merge tree indices, so merged-index line moves would
            # commit the wrong lines. Recompute instead: feed the
            # accepted tokens through every SSM at their committed
            # positions (the reference's beam-init recompute,
            # prepare_next_batch_init).
            self._refeed_accepted(reqs, accepted)

    def _refeed_accepted(self, reqs, accepted):
        """Write the accepted tokens' K/V into every SSM cache by
        running them as ordinary causal inputs at committed positions."""
        K = self.spec.beam_depth + 1
        R = self.engine.num_slots
        scratch = self.engine.scratch_pos
        bc = BatchConfig.empty(R, K, scratch)
        for req in reqs:
            slot, toks = accepted[req.request_id]
            start = req.n_cached - len(toks)  # n_cached already advanced
            bc.tokens[slot, : len(toks)] = toks
            bc.positions[slot, : len(toks)] = np.arange(
                start, start + len(toks)
            )
            bc.logits_idx[slot] = len(toks) - 1
            bc.active[slot] = True
        for ssm in self.ssms:
            ssm.run(bc)

    # ------------------------------------------------------------------
    # scheduling

    def _preempt(self, req: Request):
        # recompute preemption re-prefills prompt + generated tokens
        # through EVERY engine on re-admission — the skip debt is void
        self._ssm_lag.pop(req.request_id, None)
        super()._preempt(req)

    def register_request(self, prompt, gen: Optional[GenerationConfig] = None):
        gen = gen or GenerationConfig()
        if gen.do_sample:
            # Greedy tree verification cannot honor sampling configs —
            # fail loudly rather than emit a hybrid output (the reference
            # spec path is greedy too; its tests diff spec vs incr greedy).
            raise ValueError(
                "SpecInferManager is greedy-only; use RequestManager for "
                "sampling requests"
            )
        return super().register_request(prompt, gen)

    def _run_batch(self, bc):
        logits = self.engine.run(bc)
        for ssm in self.ssms:
            ssm.run(bc)  # same tokens into every SSM cache
        return logits

    def _decode_skipped(self, reqs: List[Request]) -> None:
        """The verify-skip arm (SpecConfig.verify_skip): ONE C=1
        incremental decode step for every request whose draft is cold —
        the same decode-row batch, step program ((1, False, False) step
        key) and greedy argmax the non-speculative sync scheduler runs,
        so the skip arm is bitwise the incremental decode path by
        construction. Only the TARGET engine steps — that is the whole
        point of the skip (a cold draft costs ~zero, so speculation
        never loses to non-speculative decoding). The SSM mirrors fall
        behind instead; the per-request debt is recorded in
        ``_ssm_lag`` and repaid by :meth:`_sync_ssm_caches` right
        before anything next feeds the mirrors."""
        R = self.engine.num_slots
        bc = BatchConfig.empty(R, 1, self.engine.scratch_pos)
        bc.qlens = np.zeros((R,), np.int32)
        for req in reqs:
            bc.tokens[req.slot, 0] = req.tokens[-1]
            bc.positions[req.slot, 0] = len(req.tokens) - 1
            bc.active[req.slot] = True
            bc.logits_idx[req.slot] = 0
            bc.qlens[req.slot] = 1
        self._attach_paging_metadata(bc)
        logits = self.engine.run(bc)  # (R, V); the LLM alone
        # ffcheck: disable=FF107 -- verify-skip incremental arm: blocking greedy decode step by design — the skip exists to cost exactly one non-speculative step, same transfer the sync path pays
        sampled = np.asarray(jax.device_get(_greedy(logits)))  # (R,)
        for req in reqs:
            req.n_cached += 1
            req.n_sched = req.n_cached
            req.profile.llm_decoding_steps += 1
            req.profile.draft_flops_per_token = self.draft_flops_per_token
            self.stats.verify_skipped_rounds += 1
            if self.ssms:
                self._ssm_lag[req.request_id] = (
                    self._ssm_lag.get(req.request_id, 0) + 1
                )
            self._append_token(req, int(sampled[req.slot]))
            if req.status is not RequestStatus.DECODING:
                self._controllers.pop(req.request_id, None)
                if self.prefix_cache is not None:
                    # completion publishes this slot's prefix blocks on
                    # every pool — the SSM pools' lines must hold real
                    # K/V, not skip-round holes
                    self._sync_ssm_caches([req])
                self._ssm_lag.pop(req.request_id, None)

    def _sync_ssm_caches(self, reqs: List[Request]) -> None:
        """Repay the verify-skip SSM cache debt: replay the cache lines
        [n_cached - lag, n_cached) — tokens the skipped rounds ran
        through the LLM only — as ordinary causal inputs through every
        SSM mirror (the :meth:`_refeed_accepted` pattern), chunked at
        ``prefill_chunk``. ONE bounded step key per SSM regardless of
        how long a request skipped, and the lag is normally capped at
        ``reprobe_every`` anyway. Pages were reserved in lockstep all
        along (_ensure_pages covers every engine), so the lines are
        already granted."""
        if not self.ssms:
            return
        reqs = [r for r in reqs if self._ssm_lag.get(r.request_id)]
        if not reqs:
            return
        C = self.engine.serving.prefill_chunk
        R = self.engine.num_slots
        while reqs:
            bc = BatchConfig.empty(R, C, self.engine.scratch_pos)
            bc.qlens = np.zeros((R,), np.int32)
            bc.prefill_offsets = np.zeros((R,), np.int32)
            rest: List[Request] = []
            for req in reqs:
                lag = self._ssm_lag[req.request_id]
                off = req.n_cached - lag
                toks = req.tokens[off : off + min(lag, C)]
                n = len(toks)
                bc.tokens[req.slot, :n] = toks
                bc.positions[req.slot, :n] = np.arange(off, off + n)
                bc.active[req.slot] = True
                bc.logits_idx[req.slot] = n - 1
                bc.qlens[req.slot] = n
                bc.prefill_offsets[req.slot] = off
                if lag > n:
                    self._ssm_lag[req.request_id] = lag - n
                    rest.append(req)
                else:
                    self._ssm_lag.pop(req.request_id, None)
            self._attach_paging_metadata(bc)
            for ssm in self.ssms:
                ssm.run(bc)
            reqs = rest

    def _mirror_dispatch(self, last, host_tokens, use_last, positions,
                         logits_idx, key, greedy, temperature, topp,
                         topk) -> None:
        """Continuous-batching composition: dispatch the SAME pipelined
        mixed step into every SSM. The LLM's previous sampled tokens
        (``last``) feed the ``use_last`` rows of BOTH programs, so each
        SSM writes K/V for exactly the token sequence the LLM is
        decoding — the SSM's own sampled output is discarded. The
        early-exit self-draft has no SSMs (one cache): nothing to
        mirror."""
        for ssm in self.ssms:
            ssm.run_mixed(last, host_tokens, use_last, positions,
                          logits_idx, key, greedy, temperature, topp, topk)

    def step(self) -> bool:
        """One SpecInfer scheduling step (reference generate_spec_infer
        loop body). While anyone is prefilling, the mixed batch (prefill
        chunks + decode tokens) runs through EVERY engine — pipelined
        via the PR-2 mixed step with the SSM mirror under
        ``continuous_batching`` (admissions and chunk progression never
        drain the pipeline), or the blocking sync batch otherwise — so
        decoding slots keep making one-token progress with the caches
        in sync (no head-of-line blocking). Once nobody is prefilling,
        the pipeline is drained and one full speculate→verify→commit
        round runs per W×D bucket present among the decoding requests
        (adaptive controllers group them; non-adaptive = one bucket)."""
        self._admit_pending()
        sc = self.engine.serving
        if self._active(RequestStatus.PREFILLING):
            # the prefill phase mirrors decode rows into every SSM —
            # skip-lagged requests must replay their missed lines FIRST
            # or the mirror would write K/V computed over cache holes
            self._sync_ssm_caches(self._active(RequestStatus.DECODING))
            if sc.continuous_batching and not sc.inference_debugging:
                self._reclaim_slots_for_admission()
                self._reserve_active_pages(
                    lambda r: self._lines_needed(r, sc.mixed_chunk)
                )
                return self._step_pipelined(mixed=True)
            return self._step_sync()
        # speculation rounds read host-side roots (req.tokens[-1]) —
        # drain whatever the pipelined prefill phase left in flight
        self._flush_all()
        decoding = self._active(RequestStatus.DECODING)
        # acceptance-weighted verify-skip: decide each request's round
        # BEFORE reserving pages — a skipped row prices one incremental
        # decode line, not a speculation tree's slack region
        actions: Dict[int, str] = {}
        if self.spec.verify_skip:
            for req in decoding:
                action = self._ctrl(req).next_action()
                actions[req.request_id] = action
                if action == "skip":
                    self._log.debug(
                        "verify-skip: request %d rides incremental "
                        "decode (ema %.3f <= %.3f)",
                        req.request_id, self._ctrl(req).ema,
                        self.spec.skip_threshold * self._bucket(req)[1],
                    )
                elif action == "reprobe":
                    self.stats.spec_reprobes += 1
                    self._log.debug(
                        "verify-skip: request %d re-probes the draft "
                        "at %dx%d after %d skipped rounds",
                        req.request_id, *self._bucket(req),
                        self.spec.reprobe_every,
                    )
        # paged KV: a spec round writes the whole tree's slack lines —
        # reserve prefix + tree pages (per-request shapes) on the LLM
        # and every SSM; verify-skip rows need only their next line
        self._reserve_active_pages(
            lambda r: (
                self._lines_needed(r)
                if actions.get(r.request_id) == "skip"
                else self._spec_lines(r)
            )
        )
        decoding = [r for r in decoding if r.status is RequestStatus.DECODING]
        if not decoding:
            return bool(self.pending)
        skipped = [
            r for r in decoding if actions.get(r.request_id) == "skip"
        ]
        if skipped:
            self._decode_skipped(skipped)
        groups: Dict[Tuple[int, int], List[Request]] = {}
        for req in decoding:
            if actions.get(req.request_id) == "skip":
                continue
            groups.setdefault(self._bucket(req), []).append(req)
        for bucket in sorted(groups):
            reqs = [
                r for r in groups[bucket]
                if r.status is RequestStatus.DECODING
            ]
            if not reqs:
                continue  # an earlier bucket's round completed them
            # a re-probing request's SSM mirrors missed every skipped
            # round — replay those lines before the draft reads them
            self._sync_ssm_caches(reqs)
            trees = self._grow_trees(reqs, *bucket)
            self._verify_and_commit(reqs, trees, *bucket)
        self._step_counter += 1
        self._maybe_log_stats()
        return True
