"""Batch descriptors — host-side PODs shipped to the device each step.

Mirrors the reference's ``BatchConfig`` family (reference
``include/flexflow/batch_config.h:39-201``, ``src/runtime/batch_config.cc``):
fixed-size padded arrays describing which request slot each token belongs
to and where it lands in the KV cache. The reference ships these to every
GPU as Legion futures; here they become the (static-shape) arguments of
the jitted step function, so padding to the compile-time maxima plays the
same role static shapes play for XLA.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

# Reference limits (batch_config.h:58-60,157-161). Ours are configurable
# via ServingConfig; these are the defaults.
MAX_NUM_REQUESTS = 16
MAX_NUM_TOKENS = 1024
MAX_SPEC_TREE_TOKEN_NUM = 64
MAX_BEAM_WIDTH = 3
MAX_BEAM_DEPTH = 8


@dataclasses.dataclass
class BatchConfig:
    """One step's device inputs, padded to (num_slots, chunk).

    ``positions`` of padding tokens point at the cache's scratch row so
    their K/V writes are harmless (models/llama.py init_kv_cache).
    """

    tokens: np.ndarray        # (R, C) int32
    positions: np.ndarray     # (R, C) int32 RoPE/sequence positions
    logits_idx: np.ndarray    # (R,) int32 — which chunk index to sample from
    active: np.ndarray        # (R,) bool — slots participating this step
    mask: Optional[np.ndarray] = None  # (R, C, S+1) bool; None => causal
    # Cache line indices when they differ from sequence positions (tree
    # tokens: siblings share a position but need distinct lines).
    cache_positions: Optional[np.ndarray] = None
    # Paged-KV metadata (Ragged Paged Attention layout, serve/paging.py).
    # page_table: (R, pages_per_slot) int32 physical page per logical
    # page — a snapshot of the batch-building engine's allocator table
    # (each engine dispatches with its OWN authoritative table; this
    # copy is host-side metadata for telemetry and tests).
    page_table: Optional[np.ndarray] = None
    # Ragged per-slot lengths: committed cache lines + this step's new
    # tokens for each active slot (0 for idle slots) — the kernel-side
    # sequence-length metadata of the ragged batch.
    seq_lens: Optional[np.ndarray] = None  # (R,) int32
    # Ragged per-row QUERY lengths: how many of this row's chunk columns
    # carry real tokens this step (decode rows 1, prefill rows up to the
    # chunk, idle rows 0). The mixed continuous-batching step pads every
    # row to the static chunk; qlens is the ragged truth the scheduler
    # and tests reason about.
    qlens: Optional[np.ndarray] = None  # (R,) int32
    # Per-row prefill START offset: the first prompt token position this
    # dispatch carries for each prefilling row (0 for cold prefills;
    # past the cached prefix on a prefix-cache hit — serve/
    # prefix_cache.py). ``positions`` already encode it on the device
    # side (the kernels handle ragged rows unchanged); this field
    # carries it explicitly for telemetry and tests.
    prefill_offsets: Optional[np.ndarray] = None  # (R,) int32
    # SpecInfer verify metadata: how many token-tree nodes (root
    # included) this verify dispatch carries per slot. With adaptive
    # tree shaping (serve/specinfer.py TreeController) slots in the
    # same W×D bucket dispatch together and slots outside it carry 0 —
    # the ragged truth of the padded (R, C) verify step, for telemetry
    # and tests (the device side already ignores padding columns via
    # the tree mask).
    spec_nodes: Optional[np.ndarray] = None  # (R,) int32

    @property
    def num_slots(self) -> int:
        return self.tokens.shape[0]

    @property
    def chunk(self) -> int:
        return self.tokens.shape[1]

    @classmethod
    def empty(cls, num_slots: int, chunk: int, scratch_pos: int) -> "BatchConfig":
        return cls(
            tokens=np.zeros((num_slots, chunk), np.int32),
            positions=np.full((num_slots, chunk), scratch_pos, np.int32),
            logits_idx=np.zeros((num_slots,), np.int32),
            active=np.zeros((num_slots,), bool),
        )


@dataclasses.dataclass
class GenerationConfig:
    """Per-request decode head parameters (reference ``GenerationConfig``
    in inference/models/* and the sampling/argmax decode ops)."""

    do_sample: bool = False
    temperature: float = 0.8
    topp: float = 0.95
    topk: int = 0  # 0 = disabled
    max_new_tokens: int = 128
    stop_token_ids: tuple = ()
    # Beam-search decode head (reference beam_topk.cc); >1 routes
    # generation through serve.beam.beam_generate.
    num_beams: int = 1
    length_penalty: float = 1.0


@dataclasses.dataclass
class ProfileInfo:
    """Per-request profiling (reference ``ProfileInfo``,
    request_manager.h:271-277: llm_decoding_steps + start/finish).
    ``first_token_time`` is stamped when the host observes the request's
    first sampled token (TTFT as a client would measure it — with the
    dispatch-ahead pipeline that is the flush, not the device sample)."""

    start_time: float = 0.0
    finish_time: float = 0.0
    first_token_time: float = 0.0
    # Prompt tokens served from the prefix cache at admission (prefill
    # started past them); 0 on a miss or with caching off.
    cached_prefix_len: int = 0
    # Of those, tokens whose pages were re-admitted from the HOST spill
    # tier (hierarchical KV cache, ServingConfig.host_cache_bytes) —
    # a host hit instead of the prefill recompute plain eviction would
    # have cost; 0 with the tier off.
    host_hit_tokens: int = 0
    llm_decoding_steps: int = 0
    ssm_decoding_steps: int = 0
    # Speculation accounting (serve/specinfer.py). ``speculated_tokens``
    # counts DRAFTED tree nodes (root excluded — the root is the
    # previous round's committed token, never a drafted one) and
    # ``accepted_tokens`` the drafted tokens the verifier accepted —
    # the free root/bonus tokens appear in NEITHER, so
    # accepted/speculated is the honest drafted-accept rate
    # (``drafted_accept_rate``). Committed output per verify dispatch —
    # accepted + the verifier's own bonus sample — is the separate
    # tokens-per-verify-step figure (output tokens / llm_decoding_steps).
    speculated_tokens: int = 0
    accepted_tokens: int = 0
    # Adaptive tree shaping (SpecConfig.adaptive): verify rounds this
    # request ran, ladder moves its controller made, and the tree shape
    # it ended on (the configured W×D when the controller is off).
    spec_rounds: int = 0
    tree_resizes: int = 0
    tree_width: int = 0
    tree_depth: int = 0
    # Draft pricing (serve/spec_distill.py accept-rate-per-draft-FLOP):
    # dense FLOPs one drafted token cost in the draft stack that served
    # this request — the cost model's 2×params forward pricing, summed
    # over the SSMs (0.0 outside speculation).
    draft_flops_per_token: float = 0.0
    # Context-parallel long-context serving (ServingConfig.kv_shard=
    # "context"): how many sequence shards this request's KV pages
    # striped over (1 = the single-pool layout).
    context_shards: int = 1
    # Cluster serving (serve/cluster/): which engine replica served the
    # request's decode phase (-1 outside a cluster), and the router's
    # queue-delay estimate for that replica at placement time — the
    # figure SLO admission sheds on (ServingConfig.slo_queue_delay_s).
    replica_id: int = -1
    router_queue_delay_s: float = 0.0
    # Fault tolerance: how many times this request was RE-ADMITTED
    # (replica death failover or migration-queue recompute drain — each
    # re-prefills prompt + tokens generated so far, the vLLM-style
    # recompute path), and the replica that received the most recent
    # failover re-admission (-1 when the request never moved).
    retries: int = 0
    failover_replica_id: int = -1
    # Replica RPC transport (serve/cluster/remote.py): transport-level
    # retry attempts spent on RPCs that carried this request's work
    # (its submit, plus every step/drain retried while it was live on
    # a remote replica) — the per-request mirror of
    # ClusterStats.rpc_retries. 0 outside a transported cluster.
    transport_retries: int = 0

    @property
    def latency_s(self) -> float:
        return max(0.0, self.finish_time - self.start_time)

    @property
    def ttft_s(self) -> float:
        """Time to first token (0 when no token was ever produced)."""
        if not self.first_token_time:
            return 0.0
        return max(0.0, self.first_token_time - self.start_time)

    def tpot_s(self, n_output_tokens: int) -> float:
        """Time per output token over the decode phase (first token →
        finish; 0 with fewer than two output tokens)."""
        if n_output_tokens < 2 or not self.first_token_time:
            return 0.0
        span = max(0.0, self.finish_time - self.first_token_time)
        return span / (n_output_tokens - 1)


@dataclasses.dataclass
class GenerationResult:
    """reference ``GenerationResult`` (request_manager.h): token ids in +
    out, detokenized text, profiling. ``error`` is set (and the token
    lists may be empty/partial) when the request failed instead of
    completing — e.g. it could never be admitted under the configured
    KV budget."""

    request_id: int
    prompt: str
    input_tokens: List[int]
    output_tokens: List[int]
    output_text: str
    profile: ProfileInfo
    error: Optional[str] = None


@dataclasses.dataclass
class StreamEvent:
    """One ``generate_stream`` event: a newly drained token for
    ``request_id``, or (``done=True``, ``token=None``) the request's
    terminal event — with ``error`` set when it failed rather than
    completed."""

    request_id: int
    token: Optional[int]
    done: bool = False
    error: Optional[str] = None
