"""Automatic prefix caching — radix-tree KV reuse over the paged pool.

Serving traffic is dominated by shared prompt prefixes: system prompts,
few-shot templates, multi-turn conversations that resend the whole
history. Re-prefilling those tokens recomputes K/V the pool already
holds. This module keeps a **token-block radix tree** mapping page-
aligned prompt blocks to live physical KV pages (vLLM's automatic
prefix caching / SGLang's RadixAttention, grafted onto serve/paging.py's
refcounted pool): on admission the RequestManager walks the tree with
the new prompt, splices every matched page into the request's page
table, and starts prefill at the first uncached token — a full hit
turns a multi-chunk prefill into a single-token step.

Design points:

* **Blocks are page-sized** (one tree node per physical page) and keys
  are hash-chained — ``node.key = hash((parent.key, block_tokens))`` —
  so a block's identity pins the entire prefix behind it, never just
  its own tokens. Lookup walks the tree (children keyed by the exact
  block tuple); the hash chain is carried for logging/telemetry and as
  a cheap cross-check that two walks agree on identity.
* **Pages are shared, never copied, on the hit path.** A matched page
  is spliced by reference (``PageAllocator.splice`` bumps refcounts);
  attention only ever READS the shared prefix, so any number of
  requests can hang off the same physical pages.
* **Copy-on-write for partial tails.** When the match ends inside a
  page (a prompt shorter than the cached one, or a cached partial tail
  block), the request must append K/V lines into that page — so it
  gets a private copy first (``PageAllocator.cow`` + the engine's
  device-side ``copy_page``). Full-page matches never COW: the next
  write lands in a fresh page.
* **The cache never causes preemption.** Tree-held pages with no slot
  references (refcount 1) are idle and reclaimable; the allocator's
  ``reclaim_cb`` points at :meth:`PrefixCache.reclaim`, which evicts
  LRU leaves until the shortfall is covered — so a cold pool and a
  cached pool admit exactly the same requests, the cached one just
  starts them further along.
* **Hierarchical host tier (FlexFlow's CPU offloading, PAPER.md
  §SpecInfer feature list).** With ``ServingConfig.host_cache_bytes``
  set, reclaim SPILLS instead of dropping: the victim page's content
  (codes + quantized scale rows) is sliced out of the pool by one
  jitted program and copied device→host ASYNCHRONOUSLY
  (``engine.fetch_page``; the copies are harvested to numpy at the
  scheduler's existing flush sync point, never mid-decode — ffcheck
  FF107 lints the hot path for accidental blocking transfers), and the
  node stays in the tree as HOST-resident: tokens, hash chain and
  content survive, only the HBM page is freed. A later ``match`` that
  walks through a host-resident node re-admits it in :meth:`attach` —
  a fresh page is taken, the content uploads host→device
  (``engine.upload_page``, async, ordered before the prefill that
  reads it) and the node is device-resident again — so a miss-to-HBM
  becomes a host HIT instead of a prefill recompute. The round-trip is
  byte-exact, which keeps cold / spilled-then-readmitted / warm
  generations BITWISE identical (tests/test_kv_hierarchy.py). The
  host tier has its own LRU: past the byte budget, cold host LEAVES
  are dropped for real. Since spilling keeps the node in place, spill
  victims need not be leaves — any idle (refcount-1) device page can
  spill, and interior spills keep their chains walkable.
* **Insertion is pure bookkeeping.** On completion (cache_policy
  "complete", the default — caches prompt AND generated tokens, the
  multi-turn case) or at prefill end ("prefill"), the request's valid
  prefix blocks are inserted/refreshed; the pages already hold the K/V,
  the tree just takes a reference. Only lines actually written on
  device are published: ``valid`` excludes the final sampled token
  (its K/V is only written when it becomes a later step's input).

Cache hits change only the page table and the prefill start offset —
never the jitted step (MPK-style: reuse logic stays out of the kernel;
the kernels already handle ragged rows).
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..logging_utils import get_logger
from .paging import PageAllocator


#: ``_Node.page`` sentinel for HOST-resident nodes (spilled to the
#: hierarchical host tier; ``host`` holds the page content).
HOST_PAGE = -1


class _Node:
    """One cached token block: ``tokens`` (≤ page_size; shorter only for
    tail blocks) backed by physical ``page`` whose first ``len(tokens)``
    lines hold those tokens' K/V. ``key`` is the hash chain identifying
    the whole prefix ending at this block. A spilled node has
    ``page == HOST_PAGE`` and carries the page's content in ``host``
    (device slices until harvested, numpy afterwards)."""

    __slots__ = ("tokens", "page", "key", "parent", "children", "partials",
                 "last_used", "host")

    def __init__(self, tokens: Tuple[int, ...], page: int, key: int,
                 parent: "_Node"):
        self.tokens = tokens
        self.page = page
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _Node] = {}  # full blocks
        self.partials: Dict[Tuple[int, ...], _Node] = {}  # tail blocks
        self.last_used = 0
        self.host = None  # device/host page content when spilled

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.partials


def _chain(parent_key: int, tokens: Tuple[int, ...]) -> int:
    return hash((parent_key, tokens))


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class PrefixCache:
    """Radix tree of cached prompt blocks over a :class:`PageAllocator`.

    ``copy_page(src, dst)`` is the device-side page copy used by COW
    (engine.copy_page); None skips the data movement (allocator-level
    tests that only exercise the bookkeeping invariants). ``stats`` is
    a SchedulerStats or a zero-arg callable returning one — the
    RequestManager passes a callable so event counters follow when a
    bench swaps ``rm.stats`` for a fresh object mid-run.

    The hierarchical host tier activates when ``host_cache_bytes`` > 0
    and both page movers are supplied: ``fetch_page(page)`` starts an
    async device→host copy of one physical page's content and returns
    a handle (engine.fetch_page), ``upload_page(page, values)`` writes
    a handle back into a pool row (engine.upload_page);
    ``page_bytes`` prices one spilled page against the byte budget.
    """

    def __init__(
        self,
        pager: PageAllocator,
        *,
        copy_page: Optional[Callable[[int, int], None]] = None,
        policy: str = "complete",
        stats=None,
        fetch_page: Optional[Callable[[int], dict]] = None,
        upload_page: Optional[Callable[[int, dict], None]] = None,
        host_cache_bytes: int = 0,
        page_bytes: int = 1,
    ):
        if policy not in ("complete", "prefill"):
            raise ValueError(
                f"unknown cache_policy {policy!r} "
                "(expected 'complete' or 'prefill')"
            )
        self.pager = pager
        self.page_size = pager.page_size
        self.copy_page = copy_page
        self.policy = policy
        self._stats_src = stats
        self.fetch_page = fetch_page
        self.upload_page = upload_page
        self.host_cache_bytes = int(host_cache_bytes or 0)
        self.page_bytes = max(1, int(page_bytes))
        self.host_bytes = 0          # current host-tier occupancy
        self._pending_spills: List[_Node] = []  # un-harvested handles
        self._pinned: set = set()    # nodes mid-attach: never spill/drop
        self._root = _Node((), pager.scratch_page, hash(()), parent=None)
        self._tick = itertools.count(1)
        self._log = get_logger("serve")

    @property
    def spill_enabled(self) -> bool:
        return (
            self.host_cache_bytes > 0
            and self.fetch_page is not None
            and self.upload_page is not None
        )

    @property
    def stats(self):
        return self._stats_src() if callable(self._stats_src) else self._stats_src

    # ------------------------------------------------------------------
    # accounting

    def _nodes(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            for c in n.children.values():
                out.append(c)
                stack.append(c)
            for c in n.partials.values():
                out.append(c)
                stack.append(c)
        return out

    @property
    def cached_pages(self) -> int:
        return len(self._nodes())

    @property
    def host_pages(self) -> int:
        """Nodes currently resident in the host tier (spilled)."""
        return sum(1 for n in self._nodes() if n.host is not None)

    def page_refs(self) -> Dict[int, int]:
        """References the tree holds per physical page (each page lives
        in exactly one node; HOST-resident nodes hold no device page,
        so they contribute nothing) — feeds
        ``PageAllocator.check_no_leaks(external=...)``."""
        refs: Dict[int, int] = {}
        for n in self._nodes():
            if n.page != HOST_PAGE:
                refs[n.page] = refs.get(n.page, 0) + 1
        return refs

    # ------------------------------------------------------------------
    # lookup

    def _walk(
        self, tokens: Sequence[int], *, peek: bool = False,
        limit: Optional[int] = None,
    ) -> Tuple[List[_Node], int]:
        """Longest cached prefix of ``tokens`` as tree NODES (device- or
        host-resident) plus the matched token count. Capped at
        ``len(tokens) - 1`` — the last prompt token is always
        recomputed so its logit exists to sample the first output from
        — and additionally at ``limit`` when given (SpecInfer aligns
        the LLM's and every SSM pool's matches to their common minimum,
        serve/specinfer.py: the engines' caches must jump past the SAME
        prefix or verification would desync). ``peek`` leaves the LRU
        ticks untouched — a read-only probe (the cluster router scores
        every replica's tree but places on at most one; a scoring walk
        must not make a losing replica's blocks look recently used)."""
        cap = len(tokens) - 1
        if limit is not None:
            cap = min(cap, int(limit))
        limit = cap
        node, nodes, matched = self._root, [], 0
        tick = None if peek else next(self._tick)
        ps = self.page_size
        while matched < limit:
            rem = limit - matched
            if rem >= ps:
                child = node.children.get(tuple(tokens[matched:matched + ps]))
                if child is not None:
                    if tick is not None:
                        child.last_used = tick
                    nodes.append(child)
                    matched += ps
                    node = child
                    continue
            # no full-block descent: best partial overlap with any block
            # hanging off this node (a full block used partially, or a
            # cached tail block)
            want = tokens[matched:limit]
            best, best_len = None, 0
            for cand in itertools.chain(
                node.children.values(), node.partials.values()
            ):
                n = _common_prefix(cand.tokens, want)
                if n > best_len:
                    best, best_len = cand, n
            if best is not None:
                if tick is not None:
                    best.last_used = tick
                nodes.append(best)
                matched += best_len
            break
        return nodes, matched

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``: the physical pages
        covering it (``HOST_PAGE`` = -1 for spilled blocks whose
        content lives in the host tier — :meth:`attach` re-admits them
        before splicing) and the matched token count."""
        nodes, matched = self._walk(tokens)
        return [n.page for n in nodes], matched

    def match_len(self, tokens: Sequence[int],
                  limit: Optional[int] = None) -> int:
        """Read-only probe: how many leading tokens a fresh admission
        of ``tokens`` would find cached (device OR host tier), WITHOUT
        touching LRU state. The cluster router's prefix-aware placement
        score (serve/cluster/router.py) and SpecInfer's cross-pool
        match alignment (serve/specinfer.py)."""
        _, matched = self._walk(tokens, peek=True, limit=limit)
        return matched

    # ------------------------------------------------------------------
    # admission: splice + COW

    def _readmit(self, node: _Node, logical: int = 0) -> bool:
        """Bring one HOST-resident node back to the device: take a free
        page — from logical page ``logical``'s owning shard under
        context parallelism, so re-admitted pages land back on the
        striped layout — upload the spilled content into it (async
        host→device, ordered before any step that reads it) and hand
        the tree's reference over to the new page. Byte-exact — codes
        and scales land exactly as spilled, so generation over the
        re-admitted prefix is bitwise the warm path's. False when no
        page could be freed even by further spilling (the match
        truncates there)."""
        fresh = self.pager.claim_free_page(
            self.pager.shard_of_logical(logical)
        )
        if fresh is None:
            return False
        self.upload_page(fresh, node.host)
        if node in self._pending_spills:
            self._pending_spills.remove(node)
        node.page = fresh
        node.host = None
        self.host_bytes -= self.page_bytes
        st = self.stats
        if st is not None:
            st.readmits += 1
            st.host_hit_tokens += len(node.tokens)
            st.host_bytes = self.host_bytes
        self._log.debug(
            "prefix readmit: host page -> %d (%d tokens, chain %x)",
            fresh, len(node.tokens), node.key & 0xFFFFFFFF,
        )
        return True

    def attach(self, slot: int, tokens: Sequence[int],
               limit: Optional[int] = None) -> int:
        """Admission-time hit path: match ``tokens`` (never past
        ``limit`` when given — SpecInfer's cross-pool alignment),
        re-admit any HOST-resident blocks on the matched path (host
        tier → device, async upload), splice the matched pages into
        ``slot``'s (empty) table, COW the tail page when the match ends
        mid-page, and return the matched token count — the request's
        prefill start offset. Falls back block-by-block when a page
        cannot be had (truncates the match / drops the partial tail
        rather than fail the admission); returns 0 on a miss."""
        nodes, matched = self._walk(tokens, limit=limit)
        # Pin the whole matched path for the rest of the admission:
        # BOTH the re-admissions and the COW below may take free pages,
        # and a dry free list triggers reclaim — which must not spill,
        # evict or host-drop a block this admission is about to splice
        # (an evicted node's page would land on the free list while
        # still listed here, and splicing it would alias a page another
        # slot can be handed).
        self._pinned = set(map(id, nodes))
        try:
            for i, n in enumerate(nodes):
                if n.host is not None and not self._readmit(n, logical=i):
                    # nodes[:-1] are full blocks: i full blocks match
                    nodes = nodes[:i]
                    matched = i * self.page_size
                    break
            pages = [n.page for n in nodes]
            cow_src = None
            if matched % self.page_size:
                # request appends K/V into the tail page → private copy
                # (from the tail's owning shard — logical page index
                # len(pages)-1 — so the striping invariant holds)
                fresh = self.pager.take_free_page(
                    self.pager.shard_of_logical(len(pages) - 1)
                )
                if fresh is None:
                    matched -= matched % self.page_size
                    pages = pages[:-1]
                else:
                    cow_src = pages[-1]
                    pages[-1] = fresh
            if not matched:
                return 0
            self.pager.splice(slot, pages)
        finally:
            self._pinned = set()
        if cow_src is not None:
            if self.stats is not None:
                self.stats.prefix_cows += 1
            if self.copy_page is not None:
                self.copy_page(cow_src, pages[-1])
            self._log.debug(
                "prefix COW: slot %d page %d -> %d (tail at %d)",
                slot, cow_src, pages[-1], matched,
            )
        self._log.debug(
            "prefix hit: slot %d matched %d/%d tokens (%d pages)",
            slot, matched, len(tokens), len(pages),
        )
        return matched

    # ------------------------------------------------------------------
    # insertion

    def _adopt(self, node: _Node, blk: Tuple[int, ...], page: int,
               tick: int, full: bool) -> Optional[_Node]:
        """Insert/refresh one block under ``node``; returns the child to
        descend into (full blocks only). A physical page lives in at
        most ONE node: re-inserting the page this slot spliced from the
        tree refreshes in place, and a tail block the owner has since
        extended (same page, longer tokens) is re-keyed rather than
        duplicated."""
        bucket = node.children if full else node.partials
        hit = bucket.get(blk)
        if hit is not None:
            hit.last_used = tick
            return hit
        # same page already cached here under a shorter tail? The owner
        # extended the block in place (decode grew the page) — re-key.
        for key, cand in list(node.partials.items()):
            if cand.page == page:
                if _common_prefix(cand.tokens, blk) == len(cand.tokens):
                    del node.partials[key]
                    cand.tokens = blk
                    cand.key = _chain(node.key, blk)
                    cand.last_used = tick
                    bucket[blk] = cand
                    return cand
                return None  # diverged content on one page — stale; skip
        child = _Node(blk, page, _chain(node.key, blk), parent=node)
        child.last_used = tick
        self.pager.acquire(page)
        bucket[blk] = child
        if self.stats is not None:
            self.stats.prefix_inserts += 1
        return child

    def insert(self, slot: int, tokens: Sequence[int], valid: int) -> None:
        """Publish ``slot``'s pages for ``tokens[:valid]`` into the tree
        (``valid`` = cache lines actually written on device). Existing
        nodes are refreshed (LRU) and kept — the tree's page wins over
        the slot's duplicate, which simply drains with the slot. The
        pages keep serving this slot unchanged; the tree just holds an
        extra reference from here on."""
        ps = self.page_size
        valid = min(int(valid), len(tokens))
        row = self.pager.table[slot]
        node = self._root
        tick = next(self._tick)
        for d in range(-(-valid // ps)):
            lo = d * ps
            blk = tuple(int(t) for t in tokens[lo:min(lo + ps, valid)])
            if not blk:
                break
            page = int(row[d])
            if page == self.pager.scratch_page:
                break  # lines beyond the slot's materialized pages
            child = self._adopt(node, blk, page, tick, full=len(blk) == ps)
            if child is None or len(blk) < ps:
                break
            node = child
        self._log.debug(
            "prefix insert: slot %d published %d tokens (%d blocks, "
            "%d cached pages total)",
            slot, valid, -(-valid // ps), self.cached_pages,
        )

    # ------------------------------------------------------------------
    # eviction (the allocator's reclaim_cb)

    def _unlink(self, victim: _Node) -> None:
        parent = victim.parent
        bucket = (
            parent.children if victim.tokens in parent.children
            and parent.children[victim.tokens] is victim else parent.partials
        )
        del bucket[victim.tokens]

    def _evict_one(self, shard: Optional[int] = None) -> bool:
        """Free the least-recently-used idle leaf (refcount 1 — held
        only by the tree, no slot references, no children pinning it as
        interior) — on ``shard`` when given (context parallelism:
        reclaim for a striped allocation must free a page the SHORT
        shard owns). Returns False when nothing is evictable."""
        victim = None
        for n in self._nodes():
            if not n.is_leaf or n.host is not None or id(n) in self._pinned:
                continue
            if int(self.pager.refcount[n.page]) != 1:
                continue  # spliced into a live slot — not idle
            if shard is not None and (
                self.pager.shard_of_page(n.page) != shard
            ):
                continue  # another shard's page cannot cover this need
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        self._unlink(victim)
        self.pager.release_ref(victim.page)
        if self.stats is not None:
            self.stats.prefix_evictions += 1
        self._log.debug(
            "prefix evict: page %d (chain %x, lru %d)",
            victim.page, victim.key & 0xFFFFFFFF, victim.last_used,
        )
        return True

    def _spill_one(self, shard: Optional[int] = None) -> bool:
        """Spill the LRU idle (refcount-1) DEVICE-resident node to the
        host tier: async device→host content copy, page freed, node
        kept in the tree as host-resident. Unlike :meth:`_evict_one`
        this needs no leaf restriction — the node stays in place, so
        interior chains remain walkable. ``shard`` filters victims to
        one shard's pages (context parallelism) — which is also what
        keeps the HOT TAIL resident while cold MIDDLE pages spill: a
        long request's tail pages are the recently-used ones on every
        shard, so per-shard LRU never picks them first. Returns False
        when nothing is spillable."""
        victim = None
        for n in self._nodes():
            if n.host is not None or id(n) in self._pinned:
                continue
            if int(self.pager.refcount[n.page]) != 1:
                continue  # spliced into a live slot — not idle
            if shard is not None and (
                self.pager.shard_of_page(n.page) != shard
            ):
                continue  # reclaim must free the SHORT shard's HBM
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        page = victim.page
        victim.host = self.fetch_page(page)   # async D2H starts here
        self._pending_spills.append(victim)
        victim.page = HOST_PAGE
        self.pager.release_ref(page)
        self.host_bytes += self.page_bytes
        st = self.stats
        if st is not None:
            st.spills += 1
            st.host_bytes = self.host_bytes
        self._log.debug(
            "prefix spill: page %d -> host (%d tokens, chain %x, "
            "host %d/%d bytes)",
            page, len(victim.tokens), victim.key & 0xFFFFFFFF,
            self.host_bytes, self.host_cache_bytes,
        )
        # host-tier LRU: past the byte budget, cold host LEAVES drop
        # for real (interior host nodes are skipped — removing one
        # would orphan device-resident descendants; best-effort
        # overshoot until their subtrees peel)
        while self.host_bytes > self.host_cache_bytes:
            if not self._drop_host_one():
                break
        return True

    def _drop_host_one(self) -> bool:
        """Truly evict the LRU host-resident leaf (host-tier LRU).
        Returns False when no droppable host leaf exists."""
        victim = None
        for n in self._nodes():
            if n.host is None or not n.is_leaf or id(n) in self._pinned:
                continue
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        self._unlink(victim)
        if victim in self._pending_spills:
            self._pending_spills.remove(victim)
        self.host_bytes -= self.page_bytes
        st = self.stats
        if st is not None:
            st.prefix_evictions += 1
            st.host_bytes = self.host_bytes
        self._log.debug(
            "prefix host drop: %d tokens (chain %x, lru %d)",
            len(victim.tokens), victim.key & 0xFFFFFFFF, victim.last_used,
        )
        return True

    def harvest(self) -> None:
        """Convert pending spill handles (device slices with async D2H
        copies in flight) to numpy host buffers, releasing their device
        memory. Called from the RequestManager's flush — the
        scheduler's existing blocking sync point, by which time the
        copies have landed — so the decode hot path itself never waits
        on a transfer."""
        import numpy as np

        for node in self._pending_spills:
            if node.host is not None:
                node.host = {
                    k: np.asarray(v) for k, v in node.host.items()
                }
        self._pending_spills.clear()

    def reclaim(self, shortfall: int, shard: Optional[int] = None) -> int:
        """Free ``shortfall`` pages: spill LRU idle cached pages to the
        host tier when it is enabled (content survives, HBM frees),
        else evict LRU idle leaves outright. Evicting a leaf can expose
        its parent as the next leaf, so deep idle chains peel
        bottom-up. Under context parallelism the allocator passes the
        SHORT shard — only that shard's pages are candidates (freeing
        another shard's HBM cannot satisfy a striped allocation).
        Returns the number of pages freed."""
        freed = 0
        while freed < shortfall:
            ok = (
                self._spill_one(shard) if self.spill_enabled
                else self._evict_one(shard)
            )
            if not ok:
                break
            freed += 1
        return freed

    # ------------------------------------------------------------------
    # tree export/import (cluster warm-standby adoption, serve/cluster/)

    def export_tree(self, fetch_page=None) -> List[dict]:
        """Serialize the whole tree for warm-standby adoption: preorder
        entries ``{"parent": <entry index, -1 = root>, "tokens": [...],
        "payload": {buffer: ndarray}}`` — the radix block keys plus
        every page's CONTENT bytes (codes + quant scale rows +
        generic-decoder pos lines), host-spilled nodes included (their
        bytes ship straight from the PR-7 host tier). Device-resident
        pages start their async gathers first and ONE blocking harvest
        converts them — this runs on the failover/adoption path, off
        every decode loop, the same reviewed flush-point pattern as the
        migration harvest. ``fetch_page`` defaults to the spill tier's
        mover (engines pass theirs explicitly when the tier is off)."""
        import jax
        import numpy as np

        fetch = fetch_page or self.fetch_page
        entries: List[dict] = []
        pending: List[Tuple[int, object]] = []  # (entry idx, device slices)
        stack = [
            (child, -1)
            for child in itertools.chain(
                reversed(list(self._root.partials.values())),
                reversed(list(self._root.children.values())),
            )
        ]
        while stack:
            node, parent_pos = stack.pop()
            pos = len(entries)
            entry = {
                "parent": parent_pos,
                "tokens": [int(t) for t in node.tokens],
                "payload": None,
            }
            if node.host is not None:
                entry["payload"] = {
                    k: np.asarray(v) for k, v in node.host.items()
                }
            elif fetch is not None:
                pending.append((pos, fetch(node.page)))
            entries.append(entry)
            for child in itertools.chain(
                reversed(list(node.partials.values())),
                reversed(list(node.children.values())),
            ):
                stack.append((child, pos))
        if pending:
            # ffcheck: disable=FF107 -- standby-adoption flush point: the dead replica's tree ships AFTER its circuit opened (failover path, outside every decode loop); the async per-page gathers above are harvested in this ONE blocking sync before serialization
            values = jax.device_get([h for _, h in pending])
            for (pos, _), val in zip(pending, values):
                entries[pos]["payload"] = dict(val)
        self._log.debug(
            "prefix export: %d blocks (%d shipped from the host tier)",
            len(entries), len(entries) - len(pending),
        )
        return entries

    def import_tree(self, entries: Sequence[dict],
                    upload_page=None) -> int:
        """Adopt an exported tree: for each entry (parents first) take
        a page the tree owns (:meth:`PageAllocator.claim_free_page` —
        reclaim may evict/spill this cache's own cold blocks to make
        room), upload the shipped content and link the node under its
        parent. Blocks already present are kept (the standby's copy
        wins — it may be mid-splice); a block that cannot get a page is
        skipped WITH its subtree (children without K/V behind them
        would serve garbage), so adoption under pool pressure is
        partial, never corrupt. Returns the number of blocks adopted."""
        up = upload_page or self.upload_page
        if up is None:
            raise ValueError(
                "import_tree needs an upload_page mover (engine."
                "upload_page) — adopted blocks carry page CONTENT"
            )
        nodes_by_pos: Dict[int, Tuple[_Node, int]] = {
            -1: (self._root, 0)
        }
        tick = next(self._tick)
        adopted = 0
        for i, entry in enumerate(entries):
            parent_entry = nodes_by_pos.get(int(entry["parent"]))
            if parent_entry is None:
                continue  # parent was skipped — skip the subtree
            parent, depth = parent_entry
            blk = tuple(int(t) for t in entry["tokens"])
            if not blk or entry["payload"] is None:
                continue
            full = len(blk) == self.page_size
            bucket = parent.children if full else parent.partials
            existing = bucket.get(blk)
            if existing is not None:
                existing.last_used = tick
                nodes_by_pos[i] = (existing, depth + 1)
                continue
            page = self.pager.claim_free_page(
                self.pager.shard_of_logical(depth)
            )
            if page is None:
                continue  # pool full — partial adoption, subtree skipped
            up(page, entry["payload"])
            node = _Node(blk, page, _chain(parent.key, blk), parent=parent)
            node.last_used = tick
            bucket[blk] = node
            nodes_by_pos[i] = (node, depth + 1)
            adopted += 1
            if self.stats is not None:
                self.stats.prefix_inserts += 1
        self._log.debug(
            "prefix import: adopted %d/%d blocks", adopted, len(entries),
        )
        return adopted

    def clear(self) -> int:
        """Drop every cached page (tree refs released; pages with no
        slot references return to the free list; host-tier content is
        discarded). Returns the number of nodes released."""
        nodes = self._nodes()
        for n in nodes:
            if n.page != HOST_PAGE:
                self.pager.release_ref(n.page)
        self._root.children.clear()
        self._root.partials.clear()
        self._pending_spills.clear()
        self.host_bytes = 0
        return len(nodes)
