"""Automatic prefix caching — radix-tree KV reuse over the paged pool.

Serving traffic is dominated by shared prompt prefixes: system prompts,
few-shot templates, multi-turn conversations that resend the whole
history. Re-prefilling those tokens recomputes K/V the pool already
holds. This module keeps a **token-block radix tree** mapping page-
aligned prompt blocks to live physical KV pages (vLLM's automatic
prefix caching / SGLang's RadixAttention, grafted onto serve/paging.py's
refcounted pool): on admission the RequestManager walks the tree with
the new prompt, splices every matched page into the request's page
table, and starts prefill at the first uncached token — a full hit
turns a multi-chunk prefill into a single-token step.

Design points:

* **Blocks are page-sized** (one tree node per physical page) and keys
  are hash-chained — ``node.key = hash((parent.key, block_tokens))`` —
  so a block's identity pins the entire prefix behind it, never just
  its own tokens. Lookup walks the tree (children keyed by the exact
  block tuple); the hash chain is carried for logging/telemetry and as
  a cheap cross-check that two walks agree on identity.
* **Pages are shared, never copied, on the hit path.** A matched page
  is spliced by reference (``PageAllocator.splice`` bumps refcounts);
  attention only ever READS the shared prefix, so any number of
  requests can hang off the same physical pages.
* **Copy-on-write for partial tails.** When the match ends inside a
  page (a prompt shorter than the cached one, or a cached partial tail
  block), the request must append K/V lines into that page — so it
  gets a private copy first (``PageAllocator.cow`` + the engine's
  device-side ``copy_page``). Full-page matches never COW: the next
  write lands in a fresh page.
* **The cache never causes preemption.** Tree-held pages with no slot
  references (refcount 1) are idle and reclaimable; the allocator's
  ``reclaim_cb`` points at :meth:`PrefixCache.reclaim`, which evicts
  LRU leaves until the shortfall is covered — so a cold pool and a
  cached pool admit exactly the same requests, the cached one just
  starts them further along.
* **Insertion is pure bookkeeping.** On completion (cache_policy
  "complete", the default — caches prompt AND generated tokens, the
  multi-turn case) or at prefill end ("prefill"), the request's valid
  prefix blocks are inserted/refreshed; the pages already hold the K/V,
  the tree just takes a reference. Only lines actually written on
  device are published: ``valid`` excludes the final sampled token
  (its K/V is only written when it becomes a later step's input).

Cache hits change only the page table and the prefill start offset —
never the jitted step (MPK-style: reuse logic stays out of the kernel;
the kernels already handle ragged rows).
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..logging_utils import get_logger
from .paging import PageAllocator


class _Node:
    """One cached token block: ``tokens`` (≤ page_size; shorter only for
    tail blocks) backed by physical ``page`` whose first ``len(tokens)``
    lines hold those tokens' K/V. ``key`` is the hash chain identifying
    the whole prefix ending at this block."""

    __slots__ = ("tokens", "page", "key", "parent", "children", "partials",
                 "last_used")

    def __init__(self, tokens: Tuple[int, ...], page: int, key: int,
                 parent: "_Node"):
        self.tokens = tokens
        self.page = page
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _Node] = {}  # full blocks
        self.partials: Dict[Tuple[int, ...], _Node] = {}  # tail blocks
        self.last_used = 0

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.partials


def _chain(parent_key: int, tokens: Tuple[int, ...]) -> int:
    return hash((parent_key, tokens))


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class PrefixCache:
    """Radix tree of cached prompt blocks over a :class:`PageAllocator`.

    ``copy_page(src, dst)`` is the device-side page copy used by COW
    (engine.copy_page); None skips the data movement (allocator-level
    tests that only exercise the bookkeeping invariants). ``stats`` is
    a SchedulerStats or a zero-arg callable returning one — the
    RequestManager passes a callable so event counters follow when a
    bench swaps ``rm.stats`` for a fresh object mid-run.
    """

    def __init__(
        self,
        pager: PageAllocator,
        *,
        copy_page: Optional[Callable[[int, int], None]] = None,
        policy: str = "complete",
        stats=None,
    ):
        if policy not in ("complete", "prefill"):
            raise ValueError(
                f"unknown cache_policy {policy!r} "
                "(expected 'complete' or 'prefill')"
            )
        self.pager = pager
        self.page_size = pager.page_size
        self.copy_page = copy_page
        self.policy = policy
        self._stats_src = stats
        self._root = _Node((), pager.scratch_page, hash(()), parent=None)
        self._tick = itertools.count(1)
        self._log = get_logger("serve")

    @property
    def stats(self):
        return self._stats_src() if callable(self._stats_src) else self._stats_src

    # ------------------------------------------------------------------
    # accounting

    def _nodes(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            for c in n.children.values():
                out.append(c)
                stack.append(c)
            for c in n.partials.values():
                out.append(c)
                stack.append(c)
        return out

    @property
    def cached_pages(self) -> int:
        return len(self._nodes())

    def page_refs(self) -> Dict[int, int]:
        """References the tree holds per physical page (each page lives
        in exactly one node) — feeds
        ``PageAllocator.check_no_leaks(external=...)``."""
        refs: Dict[int, int] = {}
        for n in self._nodes():
            refs[n.page] = refs.get(n.page, 0) + 1
        return refs

    # ------------------------------------------------------------------
    # lookup

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``: returns the physical
        pages covering it and the matched token count. Capped at
        ``len(tokens) - 1`` — the last prompt token is always
        recomputed so its logit exists to sample the first output from.
        A tail block may match partially (the new prompt diverges or
        ends inside it); the caller COWs that page before any write."""
        limit = len(tokens) - 1
        node, pages, matched = self._root, [], 0
        tick = next(self._tick)
        ps = self.page_size
        while matched < limit:
            rem = limit - matched
            if rem >= ps:
                child = node.children.get(tuple(tokens[matched:matched + ps]))
                if child is not None:
                    child.last_used = tick
                    pages.append(child.page)
                    matched += ps
                    node = child
                    continue
            # no full-block descent: best partial overlap with any block
            # hanging off this node (a full block used partially, or a
            # cached tail block)
            want = tokens[matched:limit]
            best, best_len = None, 0
            for cand in itertools.chain(
                node.children.values(), node.partials.values()
            ):
                n = _common_prefix(cand.tokens, want)
                if n > best_len:
                    best, best_len = cand, n
            if best is not None:
                best.last_used = tick
                pages.append(best.page)
                matched += best_len
            break
        return pages, matched

    # ------------------------------------------------------------------
    # admission: splice + COW

    def attach(self, slot: int, tokens: Sequence[int]) -> int:
        """Admission-time hit path: match ``tokens``, splice the matched
        pages into ``slot``'s (empty) table, COW the tail page when the
        match ends mid-page, and return the matched token count — the
        request's prefill start offset. Falls back block-by-block when
        COW cannot get a page (drops the partial tail rather than fail
        the admission); returns 0 on a miss."""
        pages, matched = self.match(tokens)
        cow_src = None
        if matched % self.page_size:
            # the request appends K/V into the tail page → private copy
            fresh = self.pager.take_free_page()
            if fresh is None:
                matched -= matched % self.page_size
                pages = pages[:-1]
            else:
                cow_src = pages[-1]
                pages[-1] = fresh
        if not matched:
            return 0
        self.pager.splice(slot, pages)
        if cow_src is not None:
            if self.stats is not None:
                self.stats.prefix_cows += 1
            if self.copy_page is not None:
                self.copy_page(cow_src, pages[-1])
            self._log.debug(
                "prefix COW: slot %d page %d -> %d (tail at %d)",
                slot, cow_src, pages[-1], matched,
            )
        self._log.debug(
            "prefix hit: slot %d matched %d/%d tokens (%d pages)",
            slot, matched, len(tokens), len(pages),
        )
        return matched

    # ------------------------------------------------------------------
    # insertion

    def _adopt(self, node: _Node, blk: Tuple[int, ...], page: int,
               tick: int, full: bool) -> Optional[_Node]:
        """Insert/refresh one block under ``node``; returns the child to
        descend into (full blocks only). A physical page lives in at
        most ONE node: re-inserting the page this slot spliced from the
        tree refreshes in place, and a tail block the owner has since
        extended (same page, longer tokens) is re-keyed rather than
        duplicated."""
        bucket = node.children if full else node.partials
        hit = bucket.get(blk)
        if hit is not None:
            hit.last_used = tick
            return hit
        # same page already cached here under a shorter tail? The owner
        # extended the block in place (decode grew the page) — re-key.
        for key, cand in list(node.partials.items()):
            if cand.page == page:
                if _common_prefix(cand.tokens, blk) == len(cand.tokens):
                    del node.partials[key]
                    cand.tokens = blk
                    cand.key = _chain(node.key, blk)
                    cand.last_used = tick
                    bucket[blk] = cand
                    return cand
                return None  # diverged content on one page — stale; skip
        child = _Node(blk, page, _chain(node.key, blk), parent=node)
        child.last_used = tick
        self.pager.acquire(page)
        bucket[blk] = child
        if self.stats is not None:
            self.stats.prefix_inserts += 1
        return child

    def insert(self, slot: int, tokens: Sequence[int], valid: int) -> None:
        """Publish ``slot``'s pages for ``tokens[:valid]`` into the tree
        (``valid`` = cache lines actually written on device). Existing
        nodes are refreshed (LRU) and kept — the tree's page wins over
        the slot's duplicate, which simply drains with the slot. The
        pages keep serving this slot unchanged; the tree just holds an
        extra reference from here on."""
        ps = self.page_size
        valid = min(int(valid), len(tokens))
        row = self.pager.table[slot]
        node = self._root
        tick = next(self._tick)
        for d in range(-(-valid // ps)):
            lo = d * ps
            blk = tuple(int(t) for t in tokens[lo:min(lo + ps, valid)])
            if not blk:
                break
            page = int(row[d])
            if page == self.pager.scratch_page:
                break  # lines beyond the slot's materialized pages
            child = self._adopt(node, blk, page, tick, full=len(blk) == ps)
            if child is None or len(blk) < ps:
                break
            node = child
        self._log.debug(
            "prefix insert: slot %d published %d tokens (%d blocks, "
            "%d cached pages total)",
            slot, valid, -(-valid // ps), self.cached_pages,
        )

    # ------------------------------------------------------------------
    # eviction (the allocator's reclaim_cb)

    def _evict_one(self) -> bool:
        """Free the least-recently-used idle leaf (refcount 1 — held
        only by the tree, no slot references, no children pinning it as
        interior). Returns False when nothing is evictable."""
        victim = None
        for n in self._nodes():
            if not n.is_leaf:
                continue
            if int(self.pager.refcount[n.page]) != 1:
                continue  # spliced into a live slot — not idle
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        parent = victim.parent
        bucket = (
            parent.children if victim.tokens in parent.children
            and parent.children[victim.tokens] is victim else parent.partials
        )
        del bucket[victim.tokens]
        self.pager.release_ref(victim.page)
        if self.stats is not None:
            self.stats.prefix_evictions += 1
        self._log.debug(
            "prefix evict: page %d (chain %x, lru %d)",
            victim.page, victim.key & 0xFFFFFFFF, victim.last_used,
        )
        return True

    def reclaim(self, shortfall: int) -> int:
        """Evict LRU idle cached pages until ``shortfall`` pages hit the
        free list (or nothing idle remains). Evicting a leaf can expose
        its parent as the next leaf, so deep idle chains peel bottom-up.
        Returns the number of pages freed."""
        freed = 0
        while freed < shortfall and self._evict_one():
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every cached page (tree refs released; pages with no
        slot references return to the free list). Returns the number of
        nodes released."""
        nodes = self._nodes()
        for n in nodes:
            self.pager.release_ref(n.page)
        self._root.children.clear()
        self._root.partials.clear()
        return len(nodes)
