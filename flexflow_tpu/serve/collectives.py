"""Quantized tensor-parallel decode collectives (EQuARX, PAPERS.md
arxiv 2506.17615).

Megatron-TP decode is collective-bound: every layer ends in TWO
all-reduces (the attention out-projection and the MLP down-projection
are row-parallel), each moving an ``(R, C, D)`` f32 partial across the
``model`` axis, and at decode (C=1, small R) the reduce latency — not
its FLOPs — serializes against the next layer's compute. EQuARX's
observation is that the reduce operand tolerates aggressive
quantization: ship int8 CODES plus per-block f32 amax scales (~1/4 the
f32 bytes at block=128) and dequantize-and-sum at the receiver. This
module is that collective for the whole-step decode walk
(``ServingConfig.fused_decode=("whole_step",)`` on a TP mesh,
models/*.serve_step_whole): the walk issues ONE of these per fusion
point instead of leaving the reduce to GSPMD, so the byte count is an
explicit, quantizable quantity.

Two modes (``ServingConfig.quantized_allreduce``):

``"exact"`` (default, the fp fallback)
    literally ``lax.psum`` — the same reduction GSPMD inserts for the
    row-parallel matmuls, so the collective-explicit walk stays
    BITWISE the GSPMD-scheduled unfused step (asserted in
    tests/test_whole_step.py). This is the mode every correctness
    claim is anchored on.

``"int8"``
    per-shard symmetric int8 quantization over ``block``-wide channel
    groups (one f32 amax scale per block), ``all_gather`` of codes +
    scales, dequantized accumulation in ABSOLUTE shard order (shard
    0..n-1 on every shard — deterministic, replicated result). Wire
    bytes drop to ``1/4 + 4/block`` of f32 (~27% at block=128).
    Tolerance contract: the reduced value differs from the exact sum
    by at most ``n · amax_block / 254`` per element (each shard's
    rounding error is ≤ scale/2 = amax/254); greedy decode tokens are
    asserted equal to the exact mode's in tests, logits within the
    documented bound. NOT bitwise — choosing it is an explicit
    accuracy/bandwidth trade, like kv_quant.

The gather-then-sum shape (rather than quantized reduce-scatter +
all-gather) is chosen for determinism: every shard applies the same
association, so the result replicates exactly and run-to-run bitwise
determinism survives. On-chip the codes move over ICI; the follow-up
(ROADMAP item 5b) is issuing these as in-kernel RDMA ring hops so the
reduce for layer i overlaps layer i+1's weight DMA.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

#: channel-group width one f32 amax scale covers in "int8" mode — the
#: EQuARX block size; 128 keeps the scale overhead at 4/128 bytes per
#: element and matches the TPU lane width.
BLOCK = 128

#: modes ServingConfig.quantized_allreduce accepts (None means "exact")
MODES = ("exact", "int8")


def resolve_mode(mode: Optional[str]) -> str:
    """Validate a ``ServingConfig.quantized_allreduce`` value (None
    passes through as "exact"; unknown names are a ValueError, raised
    at engine construction like kv_quant's)."""
    if mode is None:
        return "exact"
    if mode not in MODES:
        raise ValueError(
            f"unknown quantized_allreduce {mode!r} (expected one of "
            f"{MODES} or None)"
        )
    return mode


def quantize_blocks(
    x: jnp.ndarray, block: int = BLOCK
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization of the trailing dim in ``block``-wide
    groups: ``x (..., D)`` → ``(codes int8 (..., D), scales f32
    (..., D/block))``. The trailing dim pads up to a block multiple
    internally; padding never reaches the wire shape (D is preserved).
    All-zero blocks carry scale 0 and decode to exact zeros."""
    D = x.shape[-1]
    pad = (-D) % block
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * (xf.ndim - 1) + [(0, pad)])
    g = xf.reshape(xf.shape[:-1] + (-1, block))      # (..., G, block)
    amax = jnp.max(jnp.abs(g), axis=-1)              # (..., G)
    scale = amax / 127.0
    q = jnp.round(g / jnp.maximum(scale[..., None], 1e-30))
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    codes = q.reshape(xf.shape)[..., :D]
    return codes, scale


def dequantize_blocks(
    codes: jnp.ndarray, scales: jnp.ndarray, block: int = BLOCK
) -> jnp.ndarray:
    """Inverse of :func:`quantize_blocks` (f32 out): codes
    ``(..., D)`` × scales ``(..., G)`` → ``(..., D)``."""
    D = codes.shape[-1]
    pad = (-D) % block
    cf = codes.astype(jnp.float32)
    if pad:
        cf = jnp.pad(cf, [(0, 0)] * (cf.ndim - 1) + [(0, pad)])
    g = cf.reshape(cf.shape[:-1] + (-1, block))
    out = g * scales[..., None]
    return out.reshape(cf.shape)[..., :D]


def tp_allreduce(
    x: jnp.ndarray,
    axis_name: str,
    mode: str = "exact",
    block: int = BLOCK,
) -> jnp.ndarray:
    """All-reduce a row-parallel partial over the named (shard_map
    manual) mesh axis — the decode-collective chokepoint of the
    whole-step walk. ``mode="exact"`` IS ``lax.psum`` (bitwise the
    GSPMD reduction); ``mode="int8"`` ships quantized codes + per-block
    scales and accumulates the dequantized shards in absolute shard
    order (see the module docstring for the tolerance contract)."""
    if mode == "exact":
        return lax.psum(x, axis_name)
    if mode != "int8":
        raise ValueError(f"unknown collective mode {mode!r}")
    codes, scales = quantize_blocks(x, block)
    # tiled=False stacks shard contributions on a fresh leading axis in
    # absolute shard order; summing over it applies one association on
    # every shard, so the result replicates exactly.
    all_codes = lax.all_gather(codes, axis_name)     # (n, ..., D)
    all_scales = lax.all_gather(scales, axis_name)   # (n, ..., G)
    parts = dequantize_blocks(all_codes, all_scales, block)
    return parts.sum(axis=0).astype(x.dtype)


def allreduce_wire_bytes(
    x_shape: Tuple[int, ...], mode: str = "exact", block: int = BLOCK
) -> int:
    """Per-shard payload bytes ONE allreduce of an f32 tensor with
    shape ``x_shape`` puts on the interconnect — the bench's
    bytes-moved accounting (exact: 4 B/elt; int8: 1 B/elt + 4 B per
    ``block`` elements of scale)."""
    n = 1
    for d in x_shape:
        n *= int(d)
    if mode == "exact":
        return 4 * n
    groups = n // x_shape[-1] * (-(-x_shape[-1] // block))
    return n + 4 * groups
