"""RemoteReplica — the Replica surface over an RPC transport.

The cluster front-end (:class:`~.manager.ClusterManager`, the
:class:`~.router.Router`, :mod:`.migration`) was deliberately written
against the Replica surface; this module makes a replica living behind
a :class:`~.transport.Transport` (in-process loopback, or a subprocess
TCP server) look exactly like the in-process one:

* **Every RPC gets a deadline, bounded retries and exponential
  backoff** (:meth:`RemoteReplica._rpc` — ``ServingConfig.
  rpc_deadline_s`` / ``rpc_retries`` / ``rpc_backoff_s``). Retries
  reuse the request's ``seq``, so the server's response cache makes a
  retried ``step``/``submit`` at-most-once even when only the RESPONSE
  was lost. A call that exhausts its retries raises the final
  :class:`~.transport.TransportError` to the caller — the manager's
  drive loop feeds it to the SAME HealthMonitor machine a local step
  exception feeds (``rpc_errors`` counted in ClusterStats).
* **Heartbeats carry the SchedulerStats the queue-delay estimates
  read.** Every state-bearing response (step/heartbeat/drain/submit)
  piggybacks an envelope — telemetry + per-request flushed state — and
  the client keeps a MIRROR: ``rm.requests[rid]`` are
  :class:`_RequestView` objects holding flushed tokens/status/error,
  ``rm.stats`` replays the last ``SchedulerStats`` snapshot, and
  ``load()``/``backlog_tokens()`` are computed client-side from the
  mirror (the same inputs the in-process estimate reads). The mirror
  only ever holds FLUSHED truth — which is exactly what failover
  re-admission needs, and why ``_on_replica_down`` works even when the
  transport to the dead replica is gone.
* **Heartbeat gaps are counted in deterministic cluster steps**, never
  wall clock: the manager stamps ``last_contact_step`` on every
  successful exchange and raises ONE gap observation per cluster step
  once ``heartbeat_gap_steps`` elapse without contact — preserving
  PR-9's no-wall-clock transition contract (and its threshold
  arithmetic: a replica that is simultaneously gapped and erroring is
  observed once per step, never twice).
* **Fault injection is client-side**, at the same two seams the
  in-process cluster uses: ``FaultPlan`` replica kinds
  (crash/transient/latency/oom) fire at the top of :meth:`step`
  exactly like ``Replica.step`` does, and the transport kinds
  (drop/delay/disconnect/partition) are consulted per RPC attempt in
  :meth:`_rpc` — so PR-9's deterministic chaos machinery transfers to
  the wire unchanged.

Profile mirroring: the CLIENT owns the authoritative
:class:`ProfileInfo` (it is what ``ClusterManager.result`` returns).
Server-side counter fields merge in as deltas over a per-home base —
so a request that failed over accumulates ``llm_decoding_steps``
across homes exactly like the in-process shared-object flow — while
client-owned routing fields (``replica_id``, ``retries``,
``transport_retries``…) are never touched by a merge.
"""
from __future__ import annotations

import dataclasses
import itertools
import random
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from ...logging_utils import get_logger
from ...obs.tracer import NULL_TRACER
from ..batch_config import GenerationConfig, ProfileInfo
from ..request_manager import TERMINAL_STATUSES, RequestStatus
from .server import gen_to_wire
from .transport import (
    _STATS_LOCK,
    RemoteError,
    RpcFuture,
    Transport,
    TransportError,
)


class _AsyncCall:
    """One logical RPC in flight: the seq is assigned and attempt 0
    issued (without blocking on the response) at CONSTRUCTION — the
    call site in the concurrent drive loop is where the serial loop
    would have blocked. :meth:`wait` harvests the response, and on the
    rare failure path drives attempts 1..N SYNCHRONOUSLY with exactly
    the serial ``_rpc`` semantics — per-attempt fault consults, seq
    reuse (the server's response cache keeps retries at-most-once),
    ``rpc_retries``/``rpc_errors`` accounting, exponential backoff on
    real links, ``rpc_retry``/``rpc`` tracer events. The sync ``_rpc``
    is literally ``_AsyncCall(...).wait()``, so there is ONE retry
    state machine for both drive loops."""

    __slots__ = ("owner", "method", "args", "seq", "deadline", "retries",
                 "retries_spent", "t0", "completed_at", "future",
                 "_pre_exc")

    def __init__(self, owner: "RemoteReplica", method: str,
                 args: Dict[str, Any], retryable: bool = True):
        self.owner = owner
        self.method = method
        self.args = args
        self.seq = next(owner._seq)  # ONE seq per logical call, reused
        # across retries — the server's response cache de-duplicates
        self.deadline = owner.serving.rpc_deadline_s
        self.retries = owner.serving.rpc_retries if retryable else 0
        self.retries_spent = 0
        self.t0 = time.perf_counter() if owner.tracer.enabled else 0.0
        #: perf_counter stamp of the final successful attempt's
        #: completion (set by the transport's resolving thread for the
        #: in-flight fast path) — the manager derives RTT from it
        self.completed_at: Optional[float] = None
        self.future: Optional[RpcFuture] = None
        self._pre_exc: Optional[TransportError] = None
        try:
            self._consult_faults(attempt=0)
        except TransportError as exc:
            # the injected fault consumed attempt 0 WITHOUT touching
            # the transport — wait() resumes at attempt 1, exactly the
            # serial loop's flow
            self._pre_exc = exc
            return
        self.future = owner.transport.call_async(
            self.seq, method, args, self.deadline
        )

    def _consult_faults(self, attempt: int) -> None:
        owner = self.owner
        if owner.fault_injector is None:
            return
        extra = owner.fault_injector.on_rpc(
            owner.index, owner.steps_taken, self.method, attempt
        )
        if extra:
            if extra >= self.deadline:
                from .transport import DeadlineExceeded

                raise DeadlineExceeded(
                    f"injected delay {extra}s exceeds the "
                    f"{self.deadline}s rpc deadline ({self.method})"
                )
            # a slow-but-alive link: the health machine sees it as
            # step latency, same as the in-process "latency" fault kind
            owner.injected_latency_s += extra

    def wait(self) -> Any:
        """Harvest the response (or exhaust the retry budget and raise
        the final :class:`TransportError`; a :class:`RemoteError` —
        the server executed and raised — propagates immediately,
        never retried)."""
        owner = self.owner
        tr = owner.tracer
        owner._last_call_retries = 0
        last_exc: Optional[TransportError] = None
        if self._pre_exc is not None:
            last_exc = self._pre_exc
            self._note_attempt_failed(0, last_exc)
        else:
            try:
                result = self.future.result()
                self.completed_at = self.future.completed_at
                self._note_ok(attempts=1)
                return result
            except TransportError as exc:
                last_exc = exc
                self._note_attempt_failed(0, exc)
        for attempt in range(1, self.retries + 1):
            self.retries_spent += 1
            owner._last_call_retries += 1
            st = owner.stats
            if st is not None:
                # same lock as the transports' wire counters: a reader
                # thread mid-_count() must not interleave with this RMW
                with _STATS_LOCK:
                    st.rpc_retries += 1
            if tr.enabled:
                # retries/backoff are part of the request's wire
                # story — each is its own event on the wire lane
                tr.event(
                    "rpc_retry", method=self.method, attempt=attempt,
                    replica=owner.index,
                    error=type(last_exc).__name__,
                )
            if owner.transport.needs_backoff:
                # ffcheck: disable=FF109 -- retry backoff against a real socket peer is inherently wall-clock (the link recovers with time, not with steps); gated off for loopback via needs_backoff
                time.sleep(
                    owner.serving.rpc_backoff_s * (2 ** (attempt - 1))
                )
            try:
                self._consult_faults(attempt)
                result = owner.transport.call(
                    self.seq, self.method, self.args, self.deadline
                )
                self.completed_at = time.perf_counter()
                self._note_ok(attempts=attempt + 1)
                return result
            except TransportError as exc:
                last_exc = exc
                self._note_attempt_failed(attempt, exc)
                continue
        st = owner.stats
        if st is not None:
            with _STATS_LOCK:
                st.rpc_errors += 1
        assert last_exc is not None
        self.completed_at = time.perf_counter()
        if tr.enabled:
            tr.event(
                "rpc", t=self.t0, dur=time.perf_counter() - self.t0,
                method=self.method, replica=owner.index,
                attempts=self.retries + 1, ok=False,
                error=type(last_exc).__name__,
            )
        raise last_exc

    def _note_ok(self, attempts: int) -> None:
        owner = self.owner
        tr = owner.tracer
        if tr.enabled:
            tr.event(
                "rpc", t=self.t0, dur=time.perf_counter() - self.t0,
                method=self.method, replica=owner.index,
                attempts=attempts, ok=True,
            )

    def _note_attempt_failed(self, attempt: int,
                             exc: TransportError) -> None:
        owner = self.owner
        if getattr(exc, "kind", None) == "disconnect":
            owner.transport.drop_connection()
        owner._log.debug(
            "rpc %s to replica %d attempt %d failed: %s",
            self.method, owner.index, attempt, exc,
        )


class HeartbeatGap(RuntimeError):
    """No successful contact with a remote replica for
    ``heartbeat_gap_steps`` cluster steps — the manager feeds this to
    the health machine like a step failure (one observation per step)."""


#: ProfileInfo fields whose server-side values merge as DELTAS over the
#: per-home base (counters that must accumulate across failover homes).
_PROFILE_COUNTERS = (
    "llm_decoding_steps", "ssm_decoding_steps",
    "speculated_tokens", "accepted_tokens", "spec_rounds", "tree_resizes",
)
#: server-owned "latest state" fields — overwritten by each merge.
_PROFILE_LATEST = (
    "cached_prefix_len", "host_hit_tokens", "tree_width", "tree_depth",
    "context_shards",
)


class _RequestView:
    """Client-side mirror of one remote request — Request-shaped for
    everything the manager reads (status/tokens/error/pipeline_refs)
    and writes (``profile``)."""

    __slots__ = ("request_id", "prompt", "tokens", "prompt_len", "n_sched",
                 "slot", "pipeline_refs", "status", "error",
                 "_profile", "_profile_base")

    def __init__(self, rid: int):
        self.request_id = rid
        self.prompt = ""
        self.tokens: List[int] = []
        self.prompt_len = 0
        self.n_sched = 0
        self.slot = -1
        self.pipeline_refs = 0
        self.status = RequestStatus.PENDING
        self.error: Optional[str] = None
        self._profile = ProfileInfo()
        self._profile_base = {}
        self._rebase()

    # profile replacement (failover re-admission binds the carried
    # cluster profile onto the new home's view) re-anchors the merge
    # base so the new home's counters ADD to the carried totals
    @property
    def profile(self) -> ProfileInfo:
        return self._profile

    @profile.setter
    def profile(self, value: ProfileInfo) -> None:
        self._profile = value
        self._rebase()

    def _rebase(self) -> None:
        self._profile_base = {
            f: getattr(self._profile, f) for f in _PROFILE_COUNTERS
        }
        self._profile_base["start_time"] = self._profile.start_time
        self._profile_base["first_token_time"] = (
            self._profile.first_token_time
        )

    @property
    def output_tokens(self) -> List[int]:
        return self.tokens[self.prompt_len:]

    def apply(self, state: Dict[str, Any]) -> None:
        self.tokens = [int(t) for t in state["tokens"]]
        self.prompt_len = int(state["prompt_len"])
        self.n_sched = int(state["n_sched"])
        self.slot = int(state["slot"])
        self.pipeline_refs = int(state["pipeline_refs"])
        self.status = RequestStatus(state["status"])
        self.error = state["error"]
        prof = state.get("profile")
        if prof:
            self._merge_profile(prof)

    def _merge_profile(self, server: Dict[str, Any]) -> None:
        p, base = self._profile, self._profile_base
        for f in _PROFILE_COUNTERS:
            setattr(p, f, base[f] + int(server.get(f, 0)))
        for f in _PROFILE_LATEST:
            if server.get(f):
                setattr(p, f, server[f])
        # times: the FIRST home's start/first-token stamps win; finish
        # follows the latest home
        if not base["start_time"] and server.get("start_time"):
            p.start_time = server["start_time"]
        if not base["first_token_time"] and server.get("first_token_time"):
            p.first_token_time = server["first_token_time"]
        if server.get("finish_time"):
            p.finish_time = server["finish_time"]


class _RemoteStats:
    """SchedulerStats-shaped replay of the last heartbeat snapshot:
    ``snapshot()`` feeds ClusterStats aggregation unchanged, and
    counter reads (``stats.retraces`` …) resolve against the snapshot.
    Zero until the first envelope (or after a bench-style stat swap —
    counting resumes at the next heartbeat's snapshot)."""

    def __init__(self):
        self._snap: Dict[str, Any] = {}

    def update(self, snap: Dict[str, Any]) -> None:
        self._snap = dict(snap)

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._snap)

    def __getattr__(self, name):
        snap = object.__getattribute__(self, "_snap")
        if name in snap:
            return snap[name]
        if name.startswith("_"):
            raise AttributeError(name)
        return 0


class _RemoteRM:
    """The slice of the RequestManager surface the ClusterManager
    drives, proxied over the owner's transport (see module docstring
    for the mirror semantics)."""

    prefix_cache = None  # scoring goes through RemoteReplica.prefix_score

    def __init__(self, owner: "RemoteReplica"):
        self._owner = owner
        self.requests: Dict[int, _RequestView] = {}
        self.stats = _RemoteStats()
        self.hold_finished: set = set()

    def submit(
        self,
        prompt: Union[str, Sequence[int]],
        gen: Optional[GenerationConfig] = None,
        max_new_tokens: Optional[int] = None,
        trace_id: Optional[int] = None,
    ) -> int:
        if isinstance(prompt, str):
            raise ValueError(
                "remote replicas take token-list prompts (the cluster "
                "front-end tokenizes)"
            )
        gen = gen or GenerationConfig()
        if max_new_tokens is not None:
            gen = dataclasses.replace(gen, max_new_tokens=max_new_tokens)
        owner = self._owner
        args = {
            "tokens": [int(t) for t in prompt], "gen": gen_to_wire(gen),
        }
        if trace_id is not None:
            # cross-host correlation: the trace id rides the RPC
            # envelope so the server-side scheduler's spans for this
            # request stitch under the cluster-wide timeline
            args["trace_id"] = int(trace_id)
        res = owner._rpc("submit", args)
        rid = int(res["rid"])
        view = _RequestView(rid)
        self.requests[rid] = view
        owner._apply_envelope(res)
        view.profile.transport_retries += owner._last_call_retries
        return rid

    def hold_on_finish(self, rid: int) -> None:
        self._owner._rpc("hold_on_finish", {"rid": int(rid)})
        self.hold_finished.add(int(rid))

    def release_held(self, rid: int) -> None:
        res = self._owner._rpc("release_held", {"rid": int(rid)})
        self.hold_finished.discard(int(rid))
        self._owner._apply_envelope(res)

    def bind_profile(self, rid: int, profile: ProfileInfo) -> None:
        """Attach the carried cluster-side profile to a view (failover
        re-admission / migration adoption): later envelope merges add
        this home's counters on top of the carried totals."""
        self.requests[int(rid)].profile = profile

    def drain(self) -> None:
        self._owner.drain()

    def generate(self, prompts, gen=None, max_new_tokens=None):
        """Blocking convenience driver (bench warmup parity with the
        in-process ``rep.rm.generate``): submit, step to completion,
        return the mirrored outputs."""
        owner = self._owner
        rids = [self.submit(p, gen, max_new_tokens) for p in prompts]
        while any(
            self.requests[r].status not in TERMINAL_STATUSES for r in rids
        ):
            if not owner.step():
                break
        owner.drain()
        return [self.requests[r] for r in rids]


class RemoteReplica:
    """One cluster member living behind a transport (see module
    docstring). Carries the exact Replica telemetry/scheduling/fault
    surface the Router and ClusterManager drive."""

    is_remote = True

    def __init__(
        self,
        index: int,
        transport: Transport,
        serving,
        *,
        role: str = "mixed",
        stats=None,
        local=None,
    ):
        self.index = int(index)
        self.role = role
        self.transport = transport
        self.serving = serving
        self.rm = _RemoteRM(self)
        self.local = local  # loopback: the wrapped in-process Replica
        self.fault_injector = None
        self.steps_taken = 0
        self.injected_latency_s = 0.0
        #: cluster step of the last successful exchange — the manager
        #: stamps it; heartbeat-gap detection compares against it in
        #: CLUSTER steps (deterministic, no wall clock)
        self.last_contact_step = 0
        self._stats_src = stats
        # Seqs start at a random 62-bit point per CLIENT INCARNATION,
        # not at 1: a recovered manager re-dialing a STILL-RUNNING
        # server (ClusterManager.recover) must not collide with the
        # server's bounded response cache for the dead manager's seqs —
        # a collision replays the old client's cached response instead
        # of executing the new call. Retries still reuse one seq, so
        # the at-most-once contract is untouched; nothing downstream
        # depends on seq values (bitwise tests assert on outputs).
        self._seq = itertools.count(
            random.SystemRandom().getrandbits(62) | 1
        )
        self._telemetry: Dict[str, Any] = {}
        self._pending_abandon = False
        self._last_call_retries = 0
        self._log = get_logger("serve")
        # Observability: the WIRE tracer — rpc spans, retries and
        # envelope-shipped server events land on it when
        # obs.attach_observability wires a live one (lane "wire",
        # clocked by the client-side step counter).
        self.tracer = NULL_TRACER

    def bind_stats(self, stats) -> None:
        """Late-bind the ClusterStats source (the manager owns it but
        replicas are built first) — the transport's wire-byte counters
        follow the same callable."""
        self._stats_src = stats
        self.transport._stats_src = stats

    @property
    def stats(self):
        return (
            self._stats_src() if callable(self._stats_src)
            else self._stats_src
        )

    @property
    def engine(self):
        """The underlying engine when one is reachable in-process
        (loopback — lets the oom fault kind squeeze the real pool);
        None behind a socket."""
        return self.local.engine if self.local is not None else None

    # ------------------------------------------------------------------
    # the RPC core: deadline + bounded retries + exponential backoff

    def _rpc(self, method: str, args: Dict[str, Any],
             retryable: bool = True) -> Any:
        # issue-then-immediately-harvest: on an inline transport this
        # IS the pre-async serial exchange, bit for bit — one retry
        # state machine serves both drive loops (see _AsyncCall)
        return _AsyncCall(self, method, args, retryable=retryable).wait()

    def _apply_envelope(self, result: Dict[str, Any]) -> None:
        tel = result.get("telemetry")
        if tel is not None:
            self._telemetry = tel
            self.rm.stats.update(tel.get("stats") or {})
            self.rm.hold_finished = set(tel.get("hold_finished") or ())
            shipped = tel.get("trace_events")
            if shipped and self.tracer.enabled:
                # the replica server's spans come home inside every
                # state-bearing envelope — merge them (already tagged
                # with the replica lane) so the front-end's buffer
                # holds ONE stitched cross-host timeline
                self.tracer.buffer.extend(
                    shipped, lane=f"replica{self.index}"
                )
        for rid, state in (result.get("updates") or {}).items():
            view = self.rm.requests.get(int(rid))
            if view is not None:
                view.apply(state)

    def _spread_step_retries(self) -> None:
        """Mirror transport retries spent on this step/drain into every
        live request's profile (ISSUE: per-request
        ``ProfileInfo.transport_retries``) — the retried RPC carried
        all of their work."""
        if not self._last_call_retries:
            return
        for view in self.rm.requests.values():
            if view.status not in TERMINAL_STATUSES:
                view.profile.transport_retries += self._last_call_retries

    def _flush_pending_abandon(self) -> None:
        """An ``abandon`` that could not reach the server (the replica
        went DOWN because the link died) replays before the next
        exchange — a recovered replica must start from a clean
        scheduler, exactly like the in-process probe re-admission."""
        if not self._pending_abandon:
            return
        self._rpc("abandon", {})
        self._pending_abandon = False

    # ------------------------------------------------------------------
    # router-facing telemetry (mirror-computed — see module docstring)

    def prefix_score(self, tokens: Sequence[int]) -> int:
        if len(tokens) < 2:
            return 0
        try:
            return int(self._rpc("prefix_score",
                                 {"tokens": [int(t) for t in tokens]}
                                 )["score"])
        except (TransportError, RemoteError):
            # an unreachable replica scores 0 — routing falls elsewhere
            # and the health machinery catches the outage via its own
            # step/heartbeat observations
            return 0

    def active_requests(self) -> int:
        return sum(
            1 for v in self.rm.requests.values()
            if v.status not in TERMINAL_STATUSES
        )

    def load(self) -> float:
        return float(self.active_requests())

    def backlog_tokens(self) -> int:
        n = 0
        for v in self.rm.requests.values():
            if v.status in TERMINAL_STATUSES:
                continue
            if v.status is RequestStatus.DECODING:
                n += 1
            else:
                n += max(0, v.prompt_len - v.n_sched)
        return n

    def token_rate(self) -> float:
        return float(self._telemetry.get("token_rate", 0.0))

    def queue_delay_s(self) -> float:
        if (
            int(self._telemetry.get("rate_samples", 0)) < 2
            or self.token_rate() <= 0.0
        ):
            return 0.0
        return self.backlog_tokens() / self.token_rate()

    # ------------------------------------------------------------------
    # scheduling passthrough

    def has_work(self) -> bool:
        return self.active_requests() > 0 or bool(
            self._telemetry.get("has_work", False)
        )

    def heartbeat(self) -> bool:
        """One liveness + telemetry exchange. Returns False on failure
        — the manager's GAP accounting (cluster steps since last
        contact) turns sustained failures into health observations;
        single losses just cost a retry."""
        try:
            self._flush_pending_abandon()
            res = self._rpc("heartbeat", {})
        except (TransportError, RemoteError):
            return False
        self._apply_envelope(res)
        return True

    def step(self) -> bool:
        self.steps_taken += 1
        self.injected_latency_s = 0.0
        if self.fault_injector is not None:
            self.fault_injector.on_step(self)  # may raise InjectedFault
        self._flush_pending_abandon()
        res = self._rpc("step", {})
        self._apply_envelope(res)
        self._spread_step_retries()
        return bool(res.get("progressed", False))

    # ------------------------------------------------------------------
    # async issue/finish pairs — the concurrent drive loop's surface.
    # ISSUE methods run everything the serial path ran BEFORE its
    # blocking exchange (fault kinds, abandon replay, bookkeeping) and
    # may raise exactly what the serial path raised there; FINISH
    # methods harvest the response and apply the envelope→mirror
    # update. The manager issues in replica-index order, then finishes
    # in replica-index order — so every mirror/stats/tracer mutation
    # happens on the MANAGER's thread in a deterministic order no
    # matter how completions interleave on the wire.

    def step_async(self) -> "_AsyncCall":
        """Issue this replica's step RPC without waiting. Replica-kind
        faults fire here (issue time is the serial loop's call site) —
        may raise InjectedFault/TransportError exactly like
        :meth:`step`'s pre-exchange half."""
        self.steps_taken += 1
        self.injected_latency_s = 0.0
        if self.fault_injector is not None:
            self.fault_injector.on_step(self)  # may raise InjectedFault
        self._flush_pending_abandon()
        return _AsyncCall(self, "step", {})

    def finish_step(self, call: "_AsyncCall") -> bool:
        """Harvest a :meth:`step_async` ticket: envelope→mirror, retry
        spread, progressed flag. Raises the final TransportError on
        retry exhaustion — the manager feeds it to the health machine
        like a serial step failure."""
        res = call.wait()
        self._apply_envelope(res)
        self._spread_step_retries()
        return bool(res.get("progressed", False))

    def heartbeat_async(self) -> Optional["_AsyncCall"]:
        """Issue a liveness+telemetry exchange without waiting. Returns
        None when the pending-abandon replay (which must precede any
        exchange) could not be delivered — the heartbeat is already a
        failure."""
        try:
            self._flush_pending_abandon()
        except (TransportError, RemoteError):
            return None
        return _AsyncCall(self, "heartbeat", {})

    def finish_heartbeat(self, call: Optional["_AsyncCall"]) -> bool:
        if call is None:
            return False
        try:
            res = call.wait()
        except (TransportError, RemoteError):
            return False
        self._apply_envelope(res)
        return True

    def prefix_score_async(self,
                           tokens: Sequence[int]) -> Optional["_AsyncCall"]:
        """Issue a prefix-cache peek without waiting (None for prompts
        too short to score — the serial fast path)."""
        if len(tokens) < 2:
            return None
        return _AsyncCall(
            self, "prefix_score", {"tokens": [int(t) for t in tokens]}
        )

    def finish_prefix_score(self, call: Optional["_AsyncCall"]) -> int:
        if call is None:
            return 0
        try:
            return int(call.wait()["score"])
        except (TransportError, RemoteError):
            # an unreachable replica scores 0 — routing falls elsewhere
            # and the health machinery catches the outage via its own
            # step/heartbeat observations
            return 0

    def drain(self) -> None:
        self._flush_pending_abandon()
        res = self._rpc("drain", {})
        self._apply_envelope(res)
        self._spread_step_retries()

    # ------------------------------------------------------------------
    # fault tolerance

    def reset_rate(self) -> None:
        self._telemetry["token_rate"] = 0.0
        self._telemetry["rate_samples"] = 0

    def abandon(self) -> int:
        """Client-side teardown ALWAYS happens (the mirror is the
        manager's truth and must drop to zero load even when the
        transport is gone); the server-side teardown replays on the
        next successful exchange if it cannot be delivered now."""
        dropped = 0
        for view in self.rm.requests.values():
            view.pipeline_refs = 0
            if view.status not in TERMINAL_STATUSES:
                view.status = RequestStatus.ERROR
                view.error = "replica down — failed over"
                dropped += 1
        self.rm.hold_finished = set()
        self.reset_rate()
        self._telemetry["has_work"] = False
        try:
            self._rpc("abandon", {})
            self._pending_abandon = False
        except (TransportError, RemoteError) as exc:
            self._pending_abandon = True
            self._log.warning(
                "replica %d abandon could not be delivered (%s) — "
                "replaying before its next exchange", self.index, exc,
            )
        return dropped

    # ------------------------------------------------------------------
    # migration + standby adoption (page bytes over the wire)

    def migrate_out(self, rid: int) -> Dict[str, Any]:
        return self._rpc("migrate_out", {"rid": int(rid)})

    def migrate_in(self, payload: Dict[str, Any],
                   gen: GenerationConfig,
                   trace_id: Optional[int] = None) -> Optional[int]:
        args = {
            "tokens": payload["tokens"],
            "prompt_len": payload["prompt_len"],
            "prompt": payload.get("prompt", ""),
            "page_size": payload["page_size"],
            "pages": payload["pages"],
            "gen": gen_to_wire(gen),
        }
        if trace_id is not None:
            # the trace context follows the pages: the decode server's
            # adoption + decode spans stitch under the same timeline
            args["trace_id"] = int(trace_id)
        res = self._rpc("migrate_in", args)
        rid = res.get("rid")
        if rid is None:
            self._apply_envelope(res)
            return None
        rid = int(rid)
        self.rm.requests[rid] = _RequestView(rid)
        self._apply_envelope(res)
        return rid

    def export_prefix_tree(self) -> List[Dict[str, Any]]:
        return self._rpc("export_tree", {})["entries"]

    def import_prefix_tree(self, entries: List[Dict[str, Any]]) -> int:
        res = self._rpc("import_tree", {"entries": entries})
        self._apply_envelope(res)
        return int(res.get("adopted", 0))

    # ------------------------------------------------------------------
    # audits

    def check_no_leaks(self) -> None:
        """Run the page-pool refcount audit ON the replica; a remote
        ``AssertionError`` surfaces here as :class:`RemoteError` with
        the audit's message."""
        self._rpc("check_no_leaks", {})

    def close(self) -> None:
        self.transport.close()
