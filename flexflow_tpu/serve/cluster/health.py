"""Replica health — per-replica state machine + circuit breaker.

Every cluster replica carries a :class:`ReplicaHealth` driven by two
observation streams the :class:`~.manager.ClusterManager` feeds it from
the drive loop: step EXCEPTIONS (``record_failure``) and step LATENCIES
(``record_success`` — wall seconds per ``Replica.step``, plus any
fault-injected extra, compared against the replica's own latency EMA).
The state machine::

    HEALTHY ──exception──────────────→ SUSPECT ──threshold──→ DOWN
        │                                 │ clean streak          │
        └──sustained latency spikes──→ SUSPECT                    │ backoff
                                          │ MORE spikes           ▼ (steps)
    HEALTHY ←──probe_successes──────── PROBING ←──────────────────┘
                                          │ any failure → DOWN, backoff ×2

* **HEALTHY** — normal rotation.
* **SUSPECT** — still routable (in rotation), but on watch: one more
  consecutive exception (``failure_threshold``) circuit-breaks it, and
  ``recovery_steps`` clean steps return it to HEALTHY. Entered on a
  first exception or on ``latency_spike_steps`` consecutive step
  latencies above ``latency_spike_factor`` × the replica's EMA.
* **DOWN** — the circuit is OPEN: the replica is excluded from
  ``Router.route`` scoring, its session affinities are dropped (they
  re-pin on survivors), and every in-flight request it held is
  re-admitted elsewhere through recompute (manager failover). A
  sustained spike run (``spike_down_steps``) also trips the breaker —
  a stalled replica is as dead as a crashed one to its requests.
* **PROBING** — the circuit is HALF-OPEN: after an exponential backoff
  (``probe_backoff_steps`` × 2^(trips-1) CLUSTER steps, capped) the
  replica re-enters routing; ``probe_successes`` clean steps that
  actually carried work close the circuit (→ HEALTHY, backoff reset),
  any failure re-opens it with the backoff doubled.

Everything here is DETERMINISTIC given the observation stream: backoff
is counted in cluster steps (not wall time) and spike detection only
compares latencies the manager reports — which is what lets the
fault-injection harness (:mod:`.faults`) script exact failure scenarios
and the chaos tests replay them bit-for-bit.

The machine consumes an OBSERVATION STREAM, not a failure mechanism —
which is why remote replicas (PR 12, :mod:`.remote`) plug in
unchanged: a step RPC that exhausted its retries and a heartbeat GAP
(no successful exchange for ``heartbeat_gap_steps`` cluster steps)
both arrive as ``record_failure`` observations, deduplicated by the
manager to at most ONE per replica per cluster step (a replica that is
simultaneously gapped and RPC-erroring must not burn
``failure_threshold`` twice as fast), and an injected transport
``delay`` under the RPC deadline arrives as reported step latency the
spike detector prices exactly like the in-process "latency" fault.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional


class HealthState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DOWN = "down"
    PROBING = "probing"


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Knobs of the per-replica state machine (defaults sized for the
    in-process cluster's step cadence; a multi-host deployment with RPC
    heartbeats would widen the backoffs, not change the machine)."""

    # consecutive step exceptions that trip the breaker (the FIRST
    # exception always demotes to SUSPECT)
    failure_threshold: int = 2
    # a step latency above factor × the replica's own EMA is a spike …
    latency_spike_factor: float = 8.0
    # … this many CONSECUTIVE spikes demote HEALTHY → SUSPECT …
    latency_spike_steps: int = 3
    # … and this many trip the breaker outright (a stalled replica)
    spike_down_steps: int = 6
    # EMA warmup: no spike verdicts before this many clean samples
    min_latency_samples: int = 8
    # DOWN → PROBING after probe_backoff_steps × 2^(trips-1) cluster
    # steps, capped at probe_backoff_max_steps
    probe_backoff_steps: int = 8
    probe_backoff_max_steps: int = 256
    # clean PROBING steps (that carried work) to close the circuit
    probe_successes: int = 3
    # clean SUSPECT steps to return to HEALTHY
    recovery_steps: int = 5


class ReplicaHealth:
    """One replica's health record. All transitions are returned to the
    caller ("suspect"/"down"/"recovered"/None) so the manager can count
    them and run failover on "down"."""

    def __init__(self, index: int, config: Optional[HealthConfig] = None):
        self.index = int(index)
        self.cfg = config or HealthConfig()
        self.state = HealthState.HEALTHY
        self.consecutive_failures = 0
        self.trips = 0                # times the breaker opened
        self.down_at_step = -1        # cluster step of the last trip
        self.backoff_steps = self.cfg.probe_backoff_steps
        self.last_error: Optional[str] = None
        self._ema = 0.0               # step-latency EMA (clean samples)
        self._samples = 0
        self._spike_run = 0
        self._clean_run = 0           # SUSPECT recovery streak
        self._probe_ok = 0

    # ------------------------------------------------------------------

    @property
    def routable(self) -> bool:
        """May the router place (or keep) traffic here? DOWN is the only
        excluded state — PROBING traffic IS the probe."""
        return self.state is not HealthState.DOWN

    def _trip(self, step_no: int, why: str) -> str:
        self.trips += 1
        self.state = HealthState.DOWN
        self.down_at_step = int(step_no)
        self.backoff_steps = min(
            self.cfg.probe_backoff_steps * (2 ** (self.trips - 1)),
            self.cfg.probe_backoff_max_steps,
        )
        self.last_error = why
        self._probe_ok = 0
        self._spike_run = 0
        self._clean_run = 0
        return "down"

    def record_failure(self, exc: BaseException, step_no: int) -> str:
        """A step (or drain) raised. Returns the transition taken:
        "down" when the breaker tripped, else "suspect"."""
        why = f"{type(exc).__name__}: {exc}"
        self.consecutive_failures += 1
        self._spike_run = 0
        self._clean_run = 0
        if (
            self.state is HealthState.PROBING
            or self.consecutive_failures >= self.cfg.failure_threshold
        ):
            # half-open circuits re-open on ANY failure
            return self._trip(step_no, why)
        self.state = HealthState.SUSPECT
        self.last_error = why
        return "suspect"

    def record_success(
        self, latency_s: float, step_no: int, had_work: bool = True
    ) -> Optional[str]:
        """A step completed in ``latency_s`` (fault-injected extra
        included — the harness reports, this machine only compares).
        Returns "suspect"/"down"/"recovered" on a transition."""
        self.consecutive_failures = 0
        spike = (
            self._samples >= self.cfg.min_latency_samples
            and self._ema > 0.0
            and latency_s > self.cfg.latency_spike_factor * self._ema
        )
        if spike:
            self._spike_run += 1
        else:
            self._spike_run = 0
            # only clean samples feed the EMA: a spike must not
            # legitimize the next one by dragging the baseline up
            self._ema = (
                latency_s if self._samples == 0
                else 0.8 * self._ema + 0.2 * latency_s
            )
            self._samples += 1
        if self.state is HealthState.PROBING:
            if spike and self._spike_run >= self.cfg.spike_down_steps:
                return self._trip(step_no, "sustained step-latency spike "
                                           "while probing")
            if not spike and had_work:
                self._probe_ok += 1
                if self._probe_ok >= self.cfg.probe_successes:
                    return self._close()
            return None
        if spike:
            if self._spike_run >= self.cfg.spike_down_steps:
                return self._trip(
                    step_no,
                    f"sustained step-latency spike ({latency_s:.3f}s vs "
                    f"EMA {self._ema:.3f}s)",
                )
            if (
                self.state is HealthState.HEALTHY
                and self._spike_run >= self.cfg.latency_spike_steps
            ):
                self.state = HealthState.SUSPECT
                self._clean_run = 0
                return "suspect"
            return None
        if self.state is HealthState.SUSPECT:
            self._clean_run += 1
            if self._clean_run >= self.cfg.recovery_steps:
                self.state = HealthState.HEALTHY
                return "recovered"
        return None

    def maybe_probe(self, step_no: int) -> bool:
        """DOWN → PROBING once the backoff expired (half-open: the
        router may place traffic again). Returns True on transition."""
        if (
            self.state is HealthState.DOWN
            and step_no - self.down_at_step >= self.backoff_steps
        ):
            self.state = HealthState.PROBING
            self._probe_ok = 0
            return True
        return False

    def _close(self) -> str:
        """Close the circuit: PROBING proved itself. The backoff resets
        — a later, unrelated trip starts the schedule over — and the
        latency EMA re-warms so pre-outage timings don't spike-flag the
        recovered replica's first steps."""
        self.state = HealthState.HEALTHY
        self.trips = 0
        self.backoff_steps = self.cfg.probe_backoff_steps
        self._probe_ok = 0
        self._ema = 0.0
        self._samples = 0
        self.last_error = None
        return "recovered"


class HealthMonitor:
    """The cluster's health records, indexed by replica position."""

    def __init__(self, n_replicas: int,
                 config: Optional[HealthConfig] = None):
        self.cfg = config or HealthConfig()
        self.replicas: List[ReplicaHealth] = [
            ReplicaHealth(i, self.cfg) for i in range(n_replicas)
        ]

    def __getitem__(self, pos: int) -> ReplicaHealth:
        return self.replicas[pos]

    def __len__(self) -> int:
        return len(self.replicas)

    def add(self) -> ReplicaHealth:
        """A fresh HEALTHY record for a replica joining the cluster
        (live scale_out — serve/cluster/reconfigure.py)."""
        h = ReplicaHealth(len(self.replicas), self.cfg)
        self.replicas.append(h)
        return h

    def remove(self, pos: int) -> None:
        """Drop the record at ``pos`` (a retired replica leaves the
        membership) and re-index the survivors to their new positions."""
        del self.replicas[pos]
        for i, h in enumerate(self.replicas):
            h.index = i

    def routable(self, pos: int) -> bool:
        return self.replicas[pos].routable

    def snapshot(self) -> List[str]:
        return [h.state.value for h in self.replicas]
