"""Prefill→decode KV page migration (disaggregated serving).

Under disaggregation (``ServingConfig.prefill_replicas`` /
``decode_replicas``) a request prefills on a prefill-pool replica and
decodes on a decode-pool replica. The hand-off ships the PAGES, not the
prompt: re-prefilling on the decode side would cost the whole prompt's
compute again, while the prefilled K/V already exists page-granular in
the source pool (the Ragged Paged Attention layout is exactly what
makes this tractable — PAPERS.md).

The hand-off point is the chunked-prefill boundary from PR 2: the
prefill-final mixed-step dispatch writes the last prompt lines AND
samples the first output token on device, so the source replica runs
the request with ``max_new_tokens=1`` — its completion IS the boundary
— and what migrates is (pages covering lines ``[0, prompt_len)``) +
(the first sampled token). The destination adopts the request straight
into DECODING (``RequestManager.adopt_prefilled``) and its next step is
bit-for-bit the step the source would have run.

Byte-exactness: pages move through the PR-7 spill-tier hooks —
``engine.fetch_page`` (one jitted gather per page, ``gather_page_kv``,
async D2H copies) then ``engine.upload_page`` (``scatter_page_kv``,
async H2D) — which round-trip codes, quantized scale rows and
generic-decoder position lines exactly (tests/test_kv_hierarchy.py).
Quantized pools need no special casing: a partial tail page's scale
rows migrate with it, so rescale-on-growth on the destination continues
the same scale history the source would have (the offset-0-reset
guarantee), keeping disaggregated generation BITWISE identical to
single-replica over fp, int8 and int4 pools (tests/test_cluster.py).

The harvest between gather and upload is a BLOCKING sync — the one
deliberate flush point of the hand-off. It runs at the prefill→decode
boundary, outside every decode loop (the decode replica has not even
seen the request yet; the source replica's pipeline is already drained
because the request completed), which is why the FF107 suppression
below is a reviewed decision and not an accident.
"""
from __future__ import annotations

from typing import Optional

from ...logging_utils import get_logger
from ...metrics import ClusterStats
from ..request_manager import RequestStatus

_log = get_logger("serve")


def migrate_request(
    src,
    dst,
    rid: int,
    gen,
    *,
    stats: Optional[ClusterStats] = None,
    injector=None,
    trace_id: Optional[int] = None,
    tracer=None,
) -> Optional[int]:
    """Move a prefilled request from replica ``src`` to replica ``dst``.

    ``rid`` must be COMPLETED on ``src`` (the ``max_new_tokens=1``
    prefill pass) with its slot HELD (``hold_on_finish``) and no
    dispatches in flight. ``gen`` is the generation config the DECODE
    side should run (the source ran a 1-token override; after an
    earlier failover it is the remaining budget). Returns the request
    id on ``dst`` — adopted into DECODING with the migrated pages — or
    None when ``dst`` has no slot/pages right now (nothing moved; the
    caller retries later; the source keeps holding).

    Transactional: an exception during the page hand-off (a real
    transport error on multi-host, or the fault harness's
    ``InjectedMigrationFault``) rolls the destination's adoption back
    (``RequestManager.rollback_adopt``) and re-raises — the source
    still holds the request with its pages, so the caller can retry or
    fall back to recompute re-admission with nothing leaked on either
    side. ``injector`` (serve/cluster/faults.py) is consulted FIRST,
    before any adoption, so scripted failures exercise the clean path
    and real mid-transfer exceptions exercise the rollback.
    """
    if injector is not None:
        injector.migration_fault(src)  # may raise InjectedMigrationFault
    src_remote = getattr(src, "is_remote", False)
    dst_remote = getattr(dst, "is_remote", False)
    if src_remote or dst_remote:
        if not (src_remote and dst_remote):
            raise ValueError(
                "mixed in-process/remote migration — the cluster's "
                "replica_transport is uniform, so both ends must be "
                "RemoteReplica"
            )
        return _migrate_remote(src, dst, rid, gen, stats=stats,
                               trace_id=trace_id, tracer=tracer)
    req = src.rm.requests[rid]
    assert req.status is RequestStatus.COMPLETED, (
        f"migrating request {rid} in state {req.status}"
    )
    assert req.pipeline_refs == 0, "migration with dispatches in flight"
    assert req.slot >= 0, "migration source slot already released"
    src_eng, dst_eng = src.engine, dst.engine
    assert src_eng.pager.page_size == dst_eng.pager.page_size, (
        "prefill and decode pools disagree on page_size"
    )
    prompt_len = req.prompt_len
    rid_dst = dst.rm.adopt_prefilled(
        req.tokens, prompt_len, gen,
        profile=req.profile, prompt_text=req.prompt,
        trace_id=trace_id,
    )
    if rid_dst is None:
        return None
    try:
        n_pages = src_eng.pager.pages_for(prompt_len)
        src_row = src_eng.pager.table[req.slot]
        dst_row = dst_eng.pager.table[dst.rm.requests[rid_dst].slot]
        # start every page's async D2H gather before the one blocking
        # harvest, then upload (async H2D, ordered before any dst step
        # that reads the pages)
        handles = [
            src_eng.fetch_page(int(src_row[j])) for j in range(n_pages)
        ]
        import jax

        # ffcheck: disable=FF107 -- migration flush point: the prefill→decode hand-off harvests its page gathers in ONE blocking sync at the chunked-prefill boundary — the source pipeline is already drained (request completed) and the destination has not started the request, so no decode step anywhere waits on this transfer
        values = jax.device_get(handles)
        for j in range(n_pages):
            dst_eng.upload_page(int(dst_row[j]), values[j])
    except Exception:
        dst.rm.rollback_adopt(rid_dst)
        raise
    bytes_moved = dst_eng.page_host_bytes() * n_pages
    if stats is not None:
        stats.migrations += 1
        stats.migrated_pages += n_pages
        stats.migrated_bytes += bytes_moved
    if tracer is not None and tracer.enabled:
        tracer.event(
            "migrate",
            trace_id=-1 if trace_id is None else trace_id,
            src=src.index, dst=dst.index, pages=n_pages,
            bytes=bytes_moved,
        )
    _log.debug(
        "migrate: request %d replica %d -> %d (%d pages, %d bytes, "
        "prompt %d tokens)",
        rid, src.index, dst.index, n_pages, bytes_moved, prompt_len,
    )
    return rid_dst


def _migrate_remote(src, dst, rid: int, gen,
                    *, stats: Optional[ClusterStats] = None,
                    trace_id: Optional[int] = None, tracer=None,
                    ) -> Optional[int]:
    """The over-the-wire hand-off: the SOURCE server gathers + harvests
    the held prefill's pages (``migrate_out`` — codes, quant scale rows
    and pos lines serialize byte-exact through the frame codec) and the
    DESTINATION server adopts + uploads them transactionally
    (``migrate_in`` rolls its adoption back server-side on any upload
    failure before the error crosses the wire). Same contract as the
    in-process path: None = no capacity on ``dst`` right now, nothing
    moved, the source keeps holding; an exception (transport fault
    mid-hand-off included) leaves the source holding too — the caller
    retries with backoff or falls back to recompute re-admission."""
    view = src.rm.requests[rid]
    out = src.migrate_out(rid)
    rid_dst = dst.migrate_in(out, gen, trace_id=trace_id)
    if rid_dst is None:
        return None
    # the cluster-side profile object follows the request to its new
    # home (the in-process path shares it by reference; the mirror
    # binds it so the decode home's counters merge onto it)
    dst.rm.bind_profile(rid_dst, view.profile)
    n_pages = len(out["pages"])
    bytes_moved = sum(
        arr.nbytes for page in out["pages"] for arr in page.values()
    )
    if stats is not None:
        stats.migrations += 1
        stats.migrated_pages += n_pages
        stats.migrated_bytes += bytes_moved
    if tracer is not None and tracer.enabled:
        # the WIRE HOP of a migrated request's timeline: the same trace
        # id as its prefill-replica and decode-replica spans, on the
        # wire lane (the underlying migrate_out/migrate_in rpc spans
        # carry the byte-level story)
        tracer.event(
            "wire_migrate", lane="wire",
            trace_id=-1 if trace_id is None else trace_id,
            src=src.index, dst=dst.index, pages=n_pages,
            bytes=bytes_moved,
        )
    _log.debug(
        "migrate (wire): request %d replica %d -> %d (%d pages, %d "
        "bytes on the wire, prompt %d tokens)",
        rid, src.index, dst.index, n_pages, bytes_moved,
        out["prompt_len"],
    )
    return rid_dst
