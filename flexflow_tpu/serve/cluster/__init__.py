"""Cluster serving — multi-replica engine pool behind a prefix-aware
router, with optional disaggregated prefill/decode pools.

The "millions of users" layer over the single-engine serve stack
(ROADMAP item 1): one process drives N :class:`Replica` — each its own
:class:`InferenceEngine` with its own mesh/TP group, KV page pool and
prefix-cache radix tree — behind a front-end :class:`Router` that
places each request by prefix-cache affinity (longest radix-tree match
wins; FlexFlow's RequestManager-orchestrated batches, scaled out),
session affinity for multi-turn chat, and SLO-aware admission with
load shedding. Disaggregation (``ServingConfig.prefill_replicas`` /
``decode_replicas``) splits the pools and ships prefilled KV PAGES
from a prefill replica to a decode replica at the chunked-prefill
boundary (:mod:`.migration` — byte-exact over fp/int8/int4 pools, so
disaggregated generation is bitwise the single-replica path's).

Configuration lives on :class:`~flexflow_tpu.serve.ServingConfig`
(``replicas``, ``router_policy``, ``prefill_replicas`` /
``decode_replicas``, ``slo_queue_delay_s``) and is validated at
construction. Entry points::

    cm = ClusterManager.build(llama, cfg, params, serving)
    cm.generate(prompts, max_new_tokens=32)      # blocking
    cid = cm.submit(prompt, session_id="chat-7") # non-blocking
    for ev in cm.generate_stream(prompts): ...   # per-token events

Fault tolerance (:mod:`.health` + :mod:`.faults`): every replica runs
under a per-replica health state machine (HEALTHY → SUSPECT → DOWN →
PROBING) with a circuit breaker — a DOWN replica leaves routing, its
in-flight requests re-admit to survivors through recompute (bounded
retries, terminal ``GenerationResult.error`` past them — never a hang),
and probe re-admission closes the circuit after exponential backoff.
Failure scenarios are scripted deterministically with
:class:`FaultPlan` / :class:`FaultInjector`
(``ClusterManager.attach_faults``).

Telemetry: :class:`flexflow_tpu.metrics.ClusterStats` (router counters
+ failover/health/migration-queue counters + per-replica SchedulerStats
aggregation) via ``ClusterManager.cluster_stats()``, logged at
``FF_LOG=serve=debug``; per-request ``ProfileInfo.replica_id`` /
``router_queue_delay_s`` / ``retries`` / ``failover_replica_id``.
"""
from .faults import Fault, FaultInjector, FaultPlan, InjectedFault
from .health import HealthConfig, HealthMonitor, HealthState, ReplicaHealth
from .manager import ClusterManager, ClusterRequest
from .migration import migrate_request
from .replica import Replica
from .router import POLICIES, Router

__all__ = [
    "ClusterManager",
    "ClusterRequest",
    "Replica",
    "Router",
    "POLICIES",
    "migrate_request",
    "HealthConfig",
    "HealthMonitor",
    "HealthState",
    "ReplicaHealth",
    "Fault",
    "FaultPlan",
    "FaultInjector",
    "InjectedFault",
]
