"""Cluster serving — multi-replica engine pool behind a prefix-aware
router, with optional disaggregated prefill/decode pools.

The "millions of users" layer over the single-engine serve stack
(ROADMAP item 1): one process drives N :class:`Replica` — each its own
:class:`InferenceEngine` with its own mesh/TP group, KV page pool and
prefix-cache radix tree — behind a front-end :class:`Router` that
places each request by prefix-cache affinity (longest radix-tree match
wins; FlexFlow's RequestManager-orchestrated batches, scaled out),
session affinity for multi-turn chat, and SLO-aware admission with
load shedding. Disaggregation (``ServingConfig.prefill_replicas`` /
``decode_replicas``) splits the pools and ships prefilled KV PAGES
from a prefill replica to a decode replica at the chunked-prefill
boundary (:mod:`.migration` — byte-exact over fp/int8/int4 pools, so
disaggregated generation is bitwise the single-replica path's).

Configuration lives on :class:`~flexflow_tpu.serve.ServingConfig`
(``replicas``, ``router_policy``, ``prefill_replicas`` /
``decode_replicas``, ``slo_queue_delay_s``) and is validated at
construction. Entry points::

    cm = ClusterManager.build(llama, cfg, params, serving)
    cm.generate(prompts, max_new_tokens=32)      # blocking
    cid = cm.submit(prompt, session_id="chat-7") # non-blocking
    for ev in cm.generate_stream(prompts): ...   # per-token events

Fault tolerance (:mod:`.health` + :mod:`.faults`): every replica runs
under a per-replica health state machine (HEALTHY → SUSPECT → DOWN →
PROBING) with a circuit breaker — a DOWN replica leaves routing, its
in-flight requests re-admit to survivors through recompute (bounded
retries, terminal ``GenerationResult.error`` past them — never a hang),
and probe re-admission closes the circuit after exponential backoff.
Failure scenarios are scripted deterministically with
:class:`FaultPlan` / :class:`FaultInjector`
(``ClusterManager.attach_faults``).

Multi-host transport (:mod:`.transport` + :mod:`.remote` +
:mod:`.server`, ``ServingConfig.replica_transport``): replicas can run
behind a length-prefixed binary RPC protocol — in-process loopback
(every call through the real wire codec; BITWISE the in-process
cluster) or localhost TCP to subprocess replica servers (``python -m
flexflow_tpu.serve.cluster.server``). Every RPC gets a deadline with
bounded retries and exponential backoff; heartbeats carry the
``SchedulerStats`` the queue-delay estimates read; RPC errors and
heartbeat gaps (counted in deterministic cluster steps) feed the same
health machine; ``FaultPlan`` grows transport kinds (drop/delay/
disconnect/partition) injected at the transport; and warm standbys
(``ServingConfig.standby_replicas``) adopt a DOWN replica's prefix
families over the wire before taking its routing position. The
transport is MULTIPLEXED (``ServingConfig.concurrent_stepping``, on by
default): the drive loop fans every replica's step RPC out at once and
applies completions in replica-index order — a cluster step costs one
round-trip instead of N, and completion order provably never changes
health transitions, failover order or journal contents.

Telemetry: :class:`flexflow_tpu.metrics.ClusterStats` (router counters
+ failover/health/migration-queue counters + rpc/heartbeat/wire-byte/
standby counters + per-replica SchedulerStats aggregation) via
``ClusterManager.cluster_stats()``, logged at ``FF_LOG=serve=debug``;
per-request ``ProfileInfo.replica_id`` / ``router_queue_delay_s`` /
``retries`` / ``failover_replica_id`` / ``transport_retries``.
"""
from .faults import (
    KINDS,
    PROCESS_KINDS,
    REPLICA_KINDS,
    TRANSPORT_KINDS,
    Fault,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    InjectedManagerCrash,
    InjectedTransportFault,
)
from .health import HealthConfig, HealthMonitor, HealthState, ReplicaHealth
from .journal import (
    JournalEntry,
    JournalState,
    RequestJournal,
    replay_journal,
)
from .manager import ClusterManager, ClusterRequest
from .migration import migrate_request
from .remote import HeartbeatGap, RemoteReplica
from .replica import Replica
from .router import POLICIES, Router
from .server import ReplicaServerCore
from .transport import (
    ConnectionLost,
    DeadlineExceeded,
    FrameError,
    LoopbackTransport,
    RemoteError,
    RpcFuture,
    SocketTransport,
    TransportError,
)

__all__ = [
    "ClusterManager",
    "ClusterRequest",
    "Replica",
    "RemoteReplica",
    "ReplicaServerCore",
    "Router",
    "POLICIES",
    "migrate_request",
    "HealthConfig",
    "HealthMonitor",
    "HealthState",
    "HeartbeatGap",
    "ReplicaHealth",
    "Fault",
    "FaultPlan",
    "FaultInjector",
    "InjectedFault",
    "InjectedManagerCrash",
    "InjectedTransportFault",
    "KINDS",
    "REPLICA_KINDS",
    "TRANSPORT_KINDS",
    "PROCESS_KINDS",
    "RequestJournal",
    "JournalEntry",
    "JournalState",
    "replay_journal",
    "TransportError",
    "FrameError",
    "ConnectionLost",
    "DeadlineExceeded",
    "RemoteError",
    "RpcFuture",
    "LoopbackTransport",
    "SocketTransport",
]
