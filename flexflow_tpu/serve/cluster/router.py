"""Router — prefix-aware placement, session affinity, SLO admission.

The cluster front-end's placement brain. Given the candidate replicas
(the whole cluster, or the prefill pool under disaggregation), each
:meth:`route` call answers "which replica takes this prompt — or do we
shed it":

* **prefix** (default): score every candidate by how many leading
  prompt tokens its radix tree already holds
  (``Replica.prefix_score`` → ``PrefixCache.match_len``, a read-only
  probe) and place on the longest match — the request then prefills
  only its uncached suffix, and same-prefix traffic naturally
  PARTITIONS across replicas instead of duplicating every prefix
  family into every replica's limited tree. A universal miss falls
  back to least-loaded (which is also what seeds the partition: the
  first request of a new prefix family lands on the coldest replica,
  and every later relative follows it by match).
* **round_robin**: cycle the candidates — the ablation baseline.
* **least_loaded**: smallest queue-delay estimate (ties: fewest live
  requests, then lowest index for determinism).

**Session affinity** overrides the policy: a ``session_id`` seen before
routes to the replica that served it last (multi-turn chat keeps
hitting the replica whose tree holds the transcript). Affinity is
recorded on every placement, hit or miss.

**SLO admission** (``ServingConfig.slo_queue_delay_s``): when every
candidate's queue-delay estimate exceeds the bound, the request is
SHED — :meth:`route` returns ``(None, "shed")`` and the ClusterManager
surfaces it as ``RequestStatus.ERROR`` / ``GenerationResult.error``,
the PR-2 unservable-request contract (terminal, never a hang). With
room anywhere, the delay bound also REDIRECTS: an over-SLO preferred
replica loses the request to the best under-SLO one.

Counters land in :class:`flexflow_tpu.metrics.ClusterStats` through the
callable-stats pattern (a zero-arg callable, so a bench swapping the
stats object mid-run keeps counting).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

from ...logging_utils import get_logger
from ...metrics import ClusterStats

POLICIES = ("prefix", "round_robin", "least_loaded")


class Router:
    """Placement over ``replicas`` (Replica-shaped: ``prefix_score`` /
    ``queue_delay_s`` / ``load`` / ``index``). ``stats`` is a
    ClusterStats or a zero-arg callable returning one.

    The entries may be in-process :class:`~.replica.Replica` or
    :class:`~.remote.RemoteReplica` — for a remote one,
    ``prefix_score`` is a read-only RPC, broadcast CONCURRENTLY across
    the candidates (one round-trip per placement, not N serial peeks;
    an unreachable replica scores 0 and the health machinery owns the
    outage) while
    ``queue_delay_s``/``load`` read the heartbeat-fed client mirror,
    so a scoring pass never blocks on a slow link. The list is LIVE:
    the manager swaps a warm standby into a dead replica's position
    (``ClusterManager._adopt_standby``), and the router scores
    whatever currently occupies it."""

    def __init__(
        self,
        replicas: Sequence,
        policy: str = "prefix",
        *,
        slo_queue_delay_s: Optional[float] = None,
        stats=None,
        health=None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown router_policy {policy!r} (expected one of "
                f"{POLICIES})"
            )
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.policy = policy
        self.slo_queue_delay_s = slo_queue_delay_s
        self._stats_src = stats
        # Health filter (serve/cluster/health.py): a zero-arg-per-pos
        # callable ``health(pos) -> bool`` — False (circuit-broken DOWN
        # replica) excludes the position from every scoring pass. None =
        # every replica is always routable (the PR-8 behavior).
        self.health = health
        self._rr_next = 0
        self.sessions: Dict[object, int] = {}  # session_id -> replica pos
        self._log = get_logger("serve")

    @property
    def stats(self) -> Optional[ClusterStats]:
        return (
            self._stats_src() if callable(self._stats_src)
            else self._stats_src
        )

    # ------------------------------------------------------------------

    def _routable(self, pos: int) -> bool:
        return self.health is None or bool(self.health(pos))

    def _under_slo(self, pos: int) -> bool:
        if self.slo_queue_delay_s is None:
            return True
        return self.replicas[pos].queue_delay_s() <= self.slo_queue_delay_s

    def drop_replica_sessions(self, pos: int) -> int:
        """Forget every session pinned to ``pos`` (the replica went
        DOWN): each session re-pins on its next turn — which is also
        what re-seeds a dead replica's prefix families on survivors
        (the next relative misses everywhere and lands least-loaded,
        exactly like a brand-new family). Returns sessions dropped."""
        stale = [k for k, v in self.sessions.items() if v == pos]
        for k in stale:
            del self.sessions[k]
        return len(stale)

    def _least_loaded(self, positions: Sequence[int]) -> int:
        return min(
            positions,
            key=lambda p: (
                self.replicas[p].queue_delay_s(),
                self.replicas[p].load(),
                p,
            ),
        )

    def _prefix_scores(self, tokens: Sequence[int],
                       positions: Sequence[int]) -> list:
        """Score every candidate's cached-prefix match, CONCURRENTLY
        where the replica speaks the async RPC surface: all peek RPCs
        are issued first, then harvested in position order — a
        placement over N remote replicas costs one round-trip, not N
        serial peeks, and the scored list is identical to the serial
        broadcast's (issue/harvest order is position order, and each
        score is position-local)."""
        issued = []
        for p in positions:
            rep = self.replicas[p]
            if hasattr(rep, "prefix_score_async"):
                issued.append((p, rep, rep.prefix_score_async(tokens)))
            else:  # in-process replica: the probe is a local tree read
                issued.append((p, rep, None))
        scored = []
        for p, rep, call in issued:
            if hasattr(rep, "finish_prefix_score"):
                scored.append((rep.finish_prefix_score(call), p))
            else:
                scored.append((rep.prefix_score(tokens), p))
        return scored

    def route(
        self,
        tokens: Sequence[int],
        session_id: Optional[object] = None,
        *,
        ignore_slo: bool = False,
    ) -> Tuple[Optional[int], str]:
        """Place one prompt. Returns ``(position, how)`` — a position
        into ``self.replicas`` and the decision kind ("affinity",
        "prefix", "round_robin", "least_loaded") — or ``(None, "shed")``
        when SLO admission rejects it, or ``(None, "down")`` when every
        replica is circuit-broken (the caller surfaces a terminal
        error, never a hang). ``ignore_slo`` bypasses SLO admission —
        failover re-admissions were already admitted once and must not
        be shed on their second landing. Records the placement (and the
        session) in the stats."""
        st = self.stats
        alive = [
            p for p in range(len(self.replicas)) if self._routable(p)
        ]
        if not alive:
            self._log.debug("router: every replica is DOWN")
            return None, "down"
        eligible = [
            p for p in alive if ignore_slo or self._under_slo(p)
        ]
        if not eligible:
            if st is not None:
                st.sheds += 1
            self._log.debug(
                "router shed: every healthy replica over "
                "slo_queue_delay_s=%s (delays: %s)",
                self.slo_queue_delay_s,
                [round(r.queue_delay_s(), 3) for r in self.replicas],
            )
            return None, "shed"

        pos, how = None, self.policy
        if session_id is not None and session_id in self.sessions:
            cand = self.sessions[session_id]
            if cand in eligible:
                pos, how = cand, "affinity"
        if pos is None:
            if self.policy == "prefix":
                scored = self._prefix_scores(tokens, eligible)
                best_score = max(s for s, _ in scored)
                if best_score > 0:
                    ties = [p for s, p in scored if s == best_score]
                    pos = (
                        ties[0] if len(ties) == 1
                        else self._least_loaded(ties)
                    )
                else:
                    pos, how = self._least_loaded(eligible), "least_loaded"
            elif self.policy == "round_robin":
                # next eligible at or after the cursor, cursor advances
                # past the chosen one — a full cycle over a healthy
                # cluster touches every replica exactly once
                n = len(self.replicas)
                for off in range(n):
                    cand = (self._rr_next + off) % n
                    if cand in eligible:
                        pos = cand
                        self._rr_next = (cand + 1) % n
                        break
            else:  # least_loaded
                pos = self._least_loaded(eligible)
        if session_id is not None:
            self.sessions[session_id] = pos
        if st is not None:
            st.record_placement(how)
        self._log.debug(
            "router place: replica %d via %s (prompt %d tokens)",
            self.replicas[pos].index, how, len(tokens),
        )
        return pos, how
