"""Replica RPC transport — length-prefixed binary frames over localhost
TCP (or an in-process loopback), no dependencies beyond the stdlib.

ROADMAP item 1: the :class:`~.replica.Replica` surface was deliberately
shaped for per-host processes — this module is the wire under it. The
protocol is a deliberately small, deterministic binary codec rather
than pickle (never unpickle from a socket) or JSON (which cannot carry
KV page bytes without base64 inflation):

* **Frame** = ``MAGIC(2) | VERSION(1) | LENGTH(4, big-endian) |
  PAYLOAD(LENGTH bytes)``. Every read is bounded: a malformed magic,
  an unknown version, an oversized length or a short body raise a
  typed :class:`TransportError` — a corrupt peer can never hang a
  ``recv`` loop (socket reads additionally carry the RPC deadline).
* **Payload** = one self-describing value: None/bool/int/float/str/
  bytes/list/dict plus **numpy ndarrays** encoded as
  ``dtype | shape | raw C-order bytes``. Arrays are the load-bearing
  case: a migrated KV page's int8/int4 codes, its f32 quant scale
  rows and the generic decoder's position lines ride the codec
  BYTE-EXACT, so the PR-7/PR-8 bitwise page-migration contract holds
  across process boundaries (the int8/int4 coded pages already are a
  compact wire format — 4-8x fewer bytes than bf16, the same
  bandwidth argument EQuARX makes for quantized collectives).
* **RPC** = request ``{"seq": n, "method": str, "args": {...}}`` →
  response ``{"seq": n, "ok": bool, "result": ...}`` or
  ``{"seq": n, "ok": False, "error": {"type": ..., "msg": ...}}``.
  The client assigns ONE ``seq`` per logical call and reuses it across
  retries; the server caches recent responses by ``seq`` and replays
  a duplicate instead of re-executing — which is what makes retrying
  a ``step``/``submit`` whose RESPONSE was lost safe (at-most-once
  execution, at-least-once delivery). The ``seq`` is ALSO the frame's
  **call-tag**: every response names the call it answers, so one
  connection can carry many in-flight RPCs and complete them out of
  order — the client demultiplexes responses by tag into per-call
  :class:`RpcFuture` slots (``call_async``), which is what lets the
  cluster drive loop step N replicas in ONE round-trip instead of N.

Two transports implement the same ``call``/``call_async`` surface:

* :class:`LoopbackTransport` — in-process: every call is encoded to
  real frame bytes, decoded, dispatched against a local
  :class:`~.server.ReplicaServerCore`, and the response round-trips
  the codec the same way. Tier-1 tests run the WHOLE cluster through
  it to prove a loopback-transported cluster is BITWISE the in-process
  PR-8/9 cluster — the serialization layer is exercised end to end
  without sockets or subprocesses. ``call_async`` completes INLINE at
  issue time by default (deterministic — the concurrent drive loop on
  loopback is provably the serial loop), or on a per-transport worker
  thread with an optional real link delay (``threaded``/``delay_s``)
  so chaos tests and the bench can overlap real wall-clock latency
  across replicas.
* :class:`SocketTransport` — localhost TCP to a subprocess replica
  server (``python -m flexflow_tpu.serve.cluster.server``). A
  per-connection WRITER LOCK serializes frame sends (and re-dials — a
  racing pair of callers can neither interleave frame bytes nor
  double-count ``reconnects``), while a READER THREAD demultiplexes
  responses by call-tag into the pending futures, so many RPCs ride
  one connection concurrently. Deadline expiry and connection loss
  fail the affected futures with typed errors; a dead connection is
  remembered and re-dialed by the next call.

Deadlines, bounded retries and exponential backoff live one level up
in :class:`~.remote.RemoteReplica` — the transports only move frames.
Transport-level fault injection (FaultPlan kinds drop/delay/
disconnect/partition, serve/cluster/faults.py) is consulted there too,
so both transports see identical scripted failures.
"""
from __future__ import annotations

import queue
import select
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Union

import numpy as np

from ...analysis.locks import make_lock
from ...obs.tracer import NULL_TRACER

MAGIC = b"FT"
VERSION = 1
_HEADER = struct.Struct("!2sBI")
#: Hard cap on one frame's payload (a corrupted length prefix must not
#: make a reader try to allocate gigabytes). Generous: the largest real
#: frames are standby tree adoptions (many pages in one response).
MAX_FRAME_BYTES = 1 << 30


class TransportError(RuntimeError):
    """Base of every transport failure: framing/codec corruption,
    connection loss, deadline expiry, injected transport faults. The
    RemoteReplica retry loop treats exactly this hierarchy as
    retryable; remote APPLICATION exceptions (:class:`RemoteError`)
    are not transport errors and never retried (the server already
    executed)."""


class FrameError(TransportError):
    """Malformed or truncated frame / codec payload."""


class ConnectionLost(TransportError):
    """The peer closed or reset the connection mid-exchange."""


class DeadlineExceeded(TransportError):
    """No response within the RPC deadline."""


class RemoteError(RuntimeError):
    """The server executed the call and raised. Carries the remote
    exception's type name so callers can branch on semantics (e.g. an
    ``AssertionError`` from a remote ``check_no_leaks`` audit)."""

    def __init__(self, type_name: str, message: str):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.message = message


# ---------------------------------------------------------------------------
# value codec

_T_NONE = b"N"
_T_TRUE = b"T"
_T_FALSE = b"F"
_T_INT = b"i"       # 8-byte signed
_T_BIGINT = b"I"    # length-prefixed decimal string (hash chains etc.)
_T_FLOAT = b"f"     # 8-byte IEEE double
_T_STR = b"s"
_T_BYTES = b"b"
_T_LIST = b"l"
_T_DICT = b"d"
_T_NDARRAY = b"a"

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1


def encode_value(value: Any, out: bytearray) -> None:
    """Append one value's encoding to ``out``. Raises
    :class:`FrameError` on an unencodable type — the codec is closed
    over exactly the types the Replica surface speaks."""
    if value is None:
        out += _T_NONE
    elif value is True:
        out += _T_TRUE
    elif value is False:
        out += _T_FALSE
    elif isinstance(value, (int, np.integer)) and not isinstance(value, bool):
        v = int(value)
        if _I64_MIN <= v <= _I64_MAX:
            out += _T_INT
            out += struct.pack("!q", v)
        else:
            raw = str(v).encode("ascii")
            out += _T_BIGINT
            out += struct.pack("!I", len(raw))
            out += raw
    elif isinstance(value, (float, np.floating)):
        out += _T_FLOAT
        out += struct.pack("!d", float(value))
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out += _T_STR
        out += struct.pack("!I", len(raw))
        out += raw
    elif isinstance(value, (bytes, bytearray, memoryview)):
        raw = bytes(value)
        out += _T_BYTES
        out += struct.pack("!I", len(raw))
        out += raw
    elif isinstance(value, np.ndarray):
        dt = np.dtype(value.dtype).str.encode("ascii")
        body = np.ascontiguousarray(value).tobytes()
        out += _T_NDARRAY
        out += struct.pack("!I", len(dt))
        out += dt
        out += struct.pack("!I", len(value.shape))
        for dim in value.shape:
            out += struct.pack("!q", int(dim))
        out += struct.pack("!I", len(body))
        out += body
    elif isinstance(value, (list, tuple)):
        out += _T_LIST
        out += struct.pack("!I", len(value))
        for item in value:
            encode_value(item, out)
    elif isinstance(value, dict):
        out += _T_DICT
        out += struct.pack("!I", len(value))
        for k, v in value.items():
            encode_value(k, out)
            encode_value(v, out)
    else:
        raise FrameError(
            f"unencodable type {type(value).__name__!r} — the wire codec "
            "carries None/bool/int/float/str/bytes/list/dict/ndarray only"
        )


class _Reader:
    """Bounds-checked cursor over one payload — every read validates
    its length against the remaining bytes, so a truncated or corrupt
    payload raises :class:`FrameError` instead of over-reading."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise FrameError(
                f"truncated payload: need {n} bytes at offset {self.pos}, "
                f"have {len(self.buf) - self.pos}"
            )
        chunk = self.buf[self.pos:self.pos + n]
        self.pos += n
        return chunk

    def u32(self) -> int:
        return struct.unpack("!I", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack("!q", self.take(8))[0]


def _decode(r: _Reader) -> Any:
    tag = r.take(1)
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return r.i64()
    if tag == _T_BIGINT:
        return int(r.take(r.u32()).decode("ascii"))
    if tag == _T_FLOAT:
        return struct.unpack("!d", r.take(8))[0]
    if tag == _T_STR:
        return r.take(r.u32()).decode("utf-8")
    if tag == _T_BYTES:
        return r.take(r.u32())
    if tag == _T_NDARRAY:
        dt = np.dtype(r.take(r.u32()).decode("ascii"))
        ndim = r.u32()
        if ndim > 64:
            raise FrameError(f"ndarray with {ndim} dims — corrupt frame")
        shape = tuple(r.i64() for _ in range(ndim))
        body = r.take(r.u32())
        expect = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if len(body) != expect:
            raise FrameError(
                f"ndarray body {len(body)} bytes != shape {shape} × "
                f"{dt} ({expect} bytes)"
            )
        return np.frombuffer(body, dtype=dt).reshape(shape).copy()
    if tag == _T_LIST:
        return [_decode(r) for _ in range(r.u32())]
    if tag == _T_DICT:
        return {_decode(r): _decode(r) for _ in range(r.u32())}
    raise FrameError(f"unknown codec tag {tag!r}")


def decode_value(payload: bytes) -> Any:
    """Decode one payload; raises :class:`FrameError` on corruption or
    trailing garbage."""
    r = _Reader(payload)
    value = _decode(r)
    if r.pos != len(payload):
        raise FrameError(
            f"{len(payload) - r.pos} trailing bytes after payload"
        )
    return value


# ---------------------------------------------------------------------------
# framing

def encode_frame(value: Any) -> bytes:
    """One value → one wire frame (header + payload)."""
    body = bytearray()
    encode_value(value, body)
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(
            f"frame payload {len(body)} bytes exceeds MAX_FRAME_BYTES"
        )
    return _HEADER.pack(MAGIC, VERSION, len(body)) + bytes(body)


def decode_frame(frame: bytes) -> Any:
    """One complete wire frame → its value (header validated)."""
    if len(frame) < _HEADER.size:
        raise FrameError(
            f"short frame: {len(frame)} bytes < {_HEADER.size}-byte header"
        )
    magic, version, length = _HEADER.unpack_from(frame)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME_BYTES")
    body = frame[_HEADER.size:]
    if len(body) != length:
        raise FrameError(
            f"truncated frame: header says {length} bytes, got {len(body)}"
        )
    return decode_value(body)


def read_frame_from_socket(sock: socket.socket,
                           size_out: Optional[list] = None) -> Any:
    """Read exactly one frame off a socket whose timeout the caller has
    already set to the RPC deadline. EVERY failure mode is a typed
    raise — timeout (:class:`DeadlineExceeded`), peer close
    (:class:`ConnectionLost`), corrupt header (:class:`FrameError`) —
    a reader can never hang past its deadline or spin on garbage.
    ``size_out``, when given, receives the frame's total byte count
    (wire accounting without a re-encode)."""
    header = _recv_exact(sock, _HEADER.size)
    magic, version, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME_BYTES")
    if size_out is not None:
        size_out.append(_HEADER.size + length)
    return decode_value(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = bytearray()
    while len(chunks) < n:
        try:
            chunk = sock.recv(n - len(chunks))
        except socket.timeout as exc:
            raise DeadlineExceeded(
                f"no response within the socket deadline ({exc})"
            ) from exc
        except OSError as exc:
            raise ConnectionLost(f"socket read failed: {exc}") from exc
        if not chunk:
            raise ConnectionLost("peer closed the connection mid-frame")
        chunks += chunk
    return bytes(chunks)


# ---------------------------------------------------------------------------
# transports

#: ClusterStats is a plain dataclass shared by EVERY transport in the
#: cluster; once responses can complete on reader/worker threads, the
#: ``+=`` on its wire counters must be serialized or concurrent
#: completions lose increments. A :class:`~...analysis.locks
#: .SanitizableLock` so ``ServingConfig(sanitizers=("locks",))`` can
#: watch its acquisition order against the transport/server locks.
_STATS_LOCK = make_lock("_STATS_LOCK")


class RpcFuture:
    """One in-flight RPC's completion slot. ``call_async`` returns one
    immediately; the transport resolves it (result or typed transport/
    remote error) when the tagged response arrives. Each future carries
    its OWN deadline, anchored at issue time: :meth:`result` waits at
    most the remaining budget and raises :class:`DeadlineExceeded` —
    many futures with different deadlines can ride one connection.

    The "wire" tracer event for the exchange is emitted from
    :meth:`result` on the HARVESTING thread, never from the transport's
    reader/worker thread — tracer timelines stay single-threaded per
    lane (the FF108 contract) even though completions are concurrent.
    """

    __slots__ = ("seq", "method", "deadline_s", "sent_bytes",
                 "received_bytes", "completed_at", "_t0", "_event",
                 "_result", "_exc", "_on_deadline", "_tracer", "_traced")

    def __init__(self, seq: int, method: str, deadline_s: float):
        self.seq = seq
        self.method = method
        self.deadline_s = deadline_s
        self.sent_bytes = 0
        self.received_bytes = 0
        #: ``time.perf_counter()`` stamp of the completing resolve/fail
        #: — the manager derives per-replica RTT from it without a
        #: clock read of its own racing the completion.
        self.completed_at: Optional[float] = None
        self._t0 = time.perf_counter()
        self._event = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._on_deadline: Optional[Callable[[], None]] = None
        self._tracer = None
        self._traced = False

    def _resolve(self, result: Any) -> None:
        self._result = result
        self.completed_at = time.perf_counter()
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self.completed_at = time.perf_counter()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self) -> Any:
        """Wait out the remaining deadline budget and return the
        response (or raise the typed failure). Idempotent after
        completion."""
        remaining = self.deadline_s - (time.perf_counter() - self._t0)
        if not self._event.wait(max(0.0, remaining)):
            on_deadline, self._on_deadline = self._on_deadline, None
            if on_deadline is not None:
                on_deadline()
            raise DeadlineExceeded(
                f"rpc {self.method!r} exceeded {self.deadline_s:g}s"
            )
        if self._exc is not None:
            raise self._exc
        tr = self._tracer
        if tr is not None and not self._traced:
            self._traced = True
            tr.event("wire", method=self.method, sent=self.sent_bytes,
                     received=self.received_bytes)
        return self._result


class Transport:
    """One replica's RPC channel. ``stats`` is a ClusterStats or a
    zero-arg callable returning one (the callable-stats pattern) —
    wire byte counters land there on every exchange."""

    #: wall-clock retry backoff only makes sense when a real link can
    #: recover with time; the loopback fails or succeeds instantly.
    needs_backoff = False

    def __init__(self, stats=None):
        self._stats_src = stats
        self.bytes_sent = 0  # ffcheck: guarded-by=_STATS_LOCK
        self.bytes_received = 0  # ffcheck: guarded-by=_STATS_LOCK
        self.reconnects = 0  # ffcheck: guarded-by=_STATS_LOCK
        # Observability (flexflow_tpu/obs): with a live tracer attached
        # (obs.attach_observability sets the owning RemoteReplica's
        # wire tracer here too) every frame exchange becomes a "wire"
        # event carrying its byte counts — the per-RPC half of the
        # ClusterStats wire_bytes_* counters.
        self.tracer = NULL_TRACER

    @property
    def stats(self):
        return (
            self._stats_src() if callable(self._stats_src)
            else self._stats_src
        )

    def _count(self, sent: int = 0, received: int = 0) -> None:
        with _STATS_LOCK:
            self.bytes_sent += sent
            self.bytes_received += received
            st = self.stats
            if st is not None:
                st.wire_bytes_sent += sent
                st.wire_bytes_received += received

    def _count_reconnect(self) -> None:
        with _STATS_LOCK:
            self.reconnects += 1
            st = self.stats
            if st is not None:
                st.reconnects += 1

    def call(self, seq: int, method: str, args: Dict[str, Any],
             deadline_s: float) -> Any:
        raise NotImplementedError

    def call_async(self, seq: int, method: str, args: Dict[str, Any],
                   deadline_s: float) -> RpcFuture:
        """Issue the RPC and return its :class:`RpcFuture` without
        waiting for the response. NEVER raises a transport error —
        issue-time failures come back as an already-failed future, so
        a fan-out caller collects every outcome at harvest time.

        The base implementation executes :meth:`call` inline and
        returns an already-completed future — correct (and
        deterministic) for any transport whose ``call`` is cheap."""
        fut = RpcFuture(seq, method, deadline_s)
        try:
            fut._resolve(self.call(seq, method, args, deadline_s))
        except (TransportError, RemoteError) as exc:
            fut._fail(exc)
        return fut

    def drop_connection(self) -> None:
        """Tear the link down (injected ``disconnect`` fault or a real
        error observed by the caller); the next :meth:`call`
        reconnects."""

    def close(self) -> None:
        pass


#: Exactly one loopback dispatch at a time, cluster-wide: dispatch runs
#: the replica's REAL scheduler/engine step, and JAX host-side state is
#: not thread-safe. Worker threads overlap their injected link DELAYS
#: freely (that is the concurrency the bench measures); the computes
#: behind them serialize here, same as N processes sharing one chip.
_LOOPBACK_DISPATCH_LOCK = make_lock("_LOOPBACK_DISPATCH_LOCK")


class LoopbackTransport(Transport):
    """In-process transport: requests and responses round-trip the REAL
    codec (encode → frame → decode on both legs) before/after hitting
    a local dispatch callable — ``dispatch(request_dict) ->
    response_dict`` (a :class:`~.server.ReplicaServerCore`). What the
    caller receives is exactly what a socket peer would have received,
    byte for byte, which is what lets tier-1 prove the transported
    cluster bitwise against the in-process one without sockets.

    ``call_async`` completes INLINE at issue time by default, so the
    concurrent drive loop over loopback replicas is deterministic —
    issue order IS completion order. Setting ``threaded = True``
    (optionally with a ``delay_s`` link latency: a float, or a
    ``callable(method) -> float``) moves async completions onto a
    per-transport worker thread that sleeps the delay BEFORE
    dispatching — real wall-clock latency that overlaps across
    replicas, for the chaos tests and the ``serve_cluster_async``
    bench. The sync :meth:`call` path always stays inline — but it
    dispatches under the same global lock as the worker, so a sync
    retry racing an in-flight threaded call serializes into the
    core's seq cache instead of double-executing the RPC."""

    def __init__(self, dispatch: Callable[[Dict[str, Any]], Dict[str, Any]],
                 stats=None):
        super().__init__(stats)
        self.dispatch = dispatch
        self._connected = True
        #: flip post-build to move async completions onto the worker
        self.threaded = False
        #: injected one-way link delay, paid once per RPC (threaded
        #: mode only): seconds, or ``callable(method) -> seconds``
        self.delay_s: Union[float, Callable[[str], float]] = 0.0
        self._queue: Optional["queue.Queue"] = None
        self._worker: Optional[threading.Thread] = None

    def call(self, seq: int, method: str, args: Dict[str, Any],
             deadline_s: float) -> Any:
        if not self._connected:
            # mirror the socket behavior: a dropped link reconnects on
            # the next call (and the reconnect is counted)
            self._connected = True
            self._count_reconnect()
        request = encode_frame({"seq": seq, "method": method, "args": args})
        self._count(sent=len(request))
        # Same serialization as the worker loop: a sync call (e.g. a
        # deadline-expiry retry) must not dispatch concurrently with a
        # threaded async call still in flight — the core's seq cache
        # dedupes re-execution only when dispatches serialize.
        with _LOOPBACK_DISPATCH_LOCK:
            response_frame = encode_frame(
                # ffcheck: disable=FF111 -- the hold IS the protocol: dispatch runs the real engine step and JAX host state is single-threaded; serializing computes is what this lock exists for
                self.dispatch(decode_frame(request))
            )
        self._count(received=len(response_frame))
        tr = self.tracer
        if tr.enabled:
            tr.event("wire", method=method, sent=len(request),
                     received=len(response_frame))
        response = decode_frame(response_frame)
        return _unwrap_response(response, seq)

    def call_async(self, seq: int, method: str, args: Dict[str, Any],
                   deadline_s: float) -> RpcFuture:
        if not self.threaded:
            return super().call_async(seq, method, args, deadline_s)
        # Issue-time bookkeeping stays on the CALLER thread in issue
        # order — reconnect counting and sent-byte accounting are
        # deterministic regardless of completion interleaving.
        if not self._connected:
            self._connected = True
            self._count_reconnect()
        request = encode_frame({"seq": seq, "method": method, "args": args})
        self._count(sent=len(request))
        fut = RpcFuture(seq, method, deadline_s)
        fut.sent_bytes = len(request)
        fut._tracer = self.tracer if self.tracer.enabled else None
        self._ensure_worker().put((fut, request))
        return fut

    def _ensure_worker(self) -> "queue.Queue":
        if self._queue is None:
            self._queue = queue.Queue()
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True,
                name="ff-loopback-rpc",
            )
            self._worker.start()
        return self._queue

    def _worker_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            fut, request = item
            delay = (
                self.delay_s(fut.method) if callable(self.delay_s)
                else self.delay_s
            )
            if delay > 0:
                # ffcheck: disable=FF109 -- injected LINK latency on the worker thread is the quantity under test in the threaded bench; step logic never sees this clock
                time.sleep(delay)
            try:
                with _LOOPBACK_DISPATCH_LOCK:
                    response_frame = encode_frame(
                        # ffcheck: disable=FF111 -- same as the sync path: the hold serializes real engine steps (JAX host state is single-threaded); link delays already overlapped above, outside the lock
                        self.dispatch(decode_frame(request))
                    )
                self._count(received=len(response_frame))
                fut.received_bytes = len(response_frame)
                result = _unwrap_response(decode_frame(response_frame),
                                          fut.seq)
            except (TransportError, RemoteError) as exc:
                fut._fail(exc)
            except Exception as exc:  # dispatch cores never raise; belt
                fut._fail(FrameError(f"loopback dispatch failed: {exc}"))
            else:
                fut._resolve(result)

    def drop_connection(self) -> None:
        self._connected = False

    def close(self) -> None:
        if self._queue is not None:
            self._queue.put(None)
            self._queue = None
            self._worker = None


class SocketTransport(Transport):
    """Localhost TCP transport to a subprocess replica server —
    MULTIPLEXED: one connection carries many in-flight RPCs, completed
    out of order and demultiplexed by the response's ``seq`` call-tag.

    Concurrency model:

    * a per-connection LOCK serializes dialing and frame sends, so two
      racing callers can neither interleave frame bytes on the wire
      nor double-dial (and double-count ``reconnects``) after a drop;
    * a READER THREAD per connection (on a ``dup()`` of the socket, so
      writer-side ``settimeout`` never races it) reads response frames
      and resolves the matching pending :class:`RpcFuture`; a response
      whose tag matches nothing (a late reply to a call that already
      timed out and was retried under the same seq — the server's seq
      cache replays for the retry) is dropped on the floor;
    * per-call deadlines are enforced by :meth:`RpcFuture.result`
      wall-clock waits, not socket timeouts — slow calls can't stall
      fast ones sharing the connection. A deadline expiry harvested
      through the sync :meth:`call` drops the connection, preserving
      the pre-multiplexing contract (the response may still be in
      flight; the retry re-dials and the seq cache de-duplicates).

    Connection loss fails EVERY pending future with
    :class:`ConnectionLost`; the dead link is remembered and re-dialed
    by the next call.
    """

    needs_backoff = True

    def __init__(self, host: str, port: int, stats=None,
                 connect_timeout_s: float = 10.0):
        super().__init__(stats)
        self.host = host
        self.port = int(port)
        self.connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None  # ffcheck: guarded-by=_lock
        self._ever_connected = False  # ffcheck: guarded-by=_lock
        #: serializes dial / send / pending-table mutation; reconnect
        #: accounting happens inside, so a racing pair of callers
        #: observing a dead link produce exactly ONE re-dial
        self._lock = make_lock("SocketTransport._lock")
        self._pending: Dict[int, RpcFuture] = {}  # ffcheck: guarded-by=_lock
        #: connection generation — a reader thread only tears down the
        #: pending table of the connection it was spawned for
        self._gen = 0  # ffcheck: guarded-by=_lock

    def _dial_locked(self) -> socket.socket:
        """Dial and start this connection's reader. Caller holds
        ``_lock``."""
        self._lock.assert_held("SocketTransport re-dial")
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except OSError as exc:
            raise ConnectionLost(
                f"connect to {self.host}:{self.port} failed: {exc}"
            ) from exc
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ever_connected:
            self._count_reconnect()
        self._ever_connected = True
        self._sock = sock
        self._gen += 1
        # The reader owns a dup'd socket object onto the same
        # connection (dup shares the open file description, so the
        # writer's per-send settimeout also flips the shared
        # O_NONBLOCK — the reader therefore select()s for readability
        # and only then reads, instead of blocking in recv). shutdown()
        # on either handle wakes both sides.
        rsock = sock.dup()
        threading.Thread(
            target=self._reader_loop, args=(rsock, self._gen), daemon=True,
            name=f"ff-rpc-reader-{self.host}:{self.port}",
        ).start()
        return sock

    def _reader_loop(self, rsock: socket.socket, gen: int) -> None:
        try:
            while True:
                # idle tick: wait for a frame to START, and notice a
                # torn-down connection (drop_connection's shutdown
                # makes the socket readable-with-EOF immediately)
                try:
                    ready = select.select([rsock], [], [], 0.5)[0]
                except (OSError, ValueError):
                    self._fail_pending(
                        gen, ConnectionLost("reader socket closed")
                    )
                    return
                if not ready:
                    with self._lock:
                        if gen != self._gen or self._sock is None:
                            return  # superseded or dropped — retire
                    continue
                size_out: list = []
                try:
                    # a frame's bytes follow its first byte promptly
                    # (the server writes each response with one
                    # sendall) — the generous timeout only bounds a
                    # mid-frame peer stall
                    rsock.settimeout(self.connect_timeout_s)
                    response = read_frame_from_socket(rsock, size_out)
                except TransportError as exc:
                    self._fail_pending(gen, exc)
                    return
                seq = (
                    response.get("seq") if isinstance(response, dict)
                    else None
                )
                if not isinstance(seq, int):
                    self._fail_pending(
                        gen, FrameError(f"untagged rpc response: "
                                        f"{type(response).__name__}")
                    )
                    return
                with self._lock:
                    fut = self._pending.pop(seq, None)
                if fut is None:
                    continue  # late reply to an abandoned/retried call
                self._count(received=size_out[0])
                fut.received_bytes = size_out[0]
                try:
                    result = _unwrap_response(response, fut.seq)
                except (TransportError, RemoteError) as exc:
                    fut._fail(exc)
                else:
                    fut._resolve(result)
        finally:
            try:
                rsock.close()
            except OSError:
                pass

    def _fail_pending(self, gen: int, exc: TransportError) -> None:
        """The ``gen`` connection died: fail its pending futures and
        mark the transport dead (unless a newer connection already took
        over — then its reader owns the pending table)."""
        with self._lock:
            if gen != self._gen:
                return
            pending = list(self._pending.values())
            self._pending.clear()
            self._close_sock_locked()
        for fut in pending:
            fut._fail(exc)

    def _close_sock_locked(self) -> None:
        self._lock.assert_held("SocketTransport teardown")
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call_async(self, seq: int, method: str, args: Dict[str, Any],
                   deadline_s: float) -> RpcFuture:
        fut = RpcFuture(seq, method, deadline_s)
        fut._tracer = self.tracer if self.tracer.enabled else None
        frame = encode_frame({"seq": seq, "method": method, "args": args})
        fut.sent_bytes = len(frame)
        with self._lock:
            try:
                sock = self._sock if self._sock is not None \
                    else self._dial_locked()  # ffcheck: disable=FF111 -- re-dial must be atomic with the liveness check: two callers racing a dead link would otherwise double-dial and orphan a reader generation
                self._pending[seq] = fut
                sock.settimeout(deadline_s)
                # ffcheck: disable=FF111 -- frame writes must serialize per connection (interleaved sendall corrupts the stream); per-call deadline bounds the stall via settimeout above
                sock.sendall(frame)
            except TransportError as exc:
                self._pending.pop(seq, None)
                fut._fail(exc)
                return fut
            except (socket.timeout, OSError) as exc:
                self._pending.pop(seq, None)
                self._close_sock_locked()
                fut._fail(ConnectionLost(f"rpc {method!r} send failed: "
                                         f"{exc}"))
                return fut
        self._count(sent=len(frame))
        return fut

    def call(self, seq: int, method: str, args: Dict[str, Any],
             deadline_s: float) -> Any:
        fut = self.call_async(seq, method, args, deadline_s)
        # pre-multiplexing semantics: a sync caller that gives up on
        # its deadline abandons the connection (the in-flight response
        # would otherwise desynchronize a serial request/response view)
        fut._on_deadline = self.drop_connection
        return fut.result()

    def drop_connection(self) -> None:
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
            self._close_sock_locked()
        for fut in pending:
            fut._fail(ConnectionLost("connection dropped"))

    def close(self) -> None:
        self.drop_connection()


def _unwrap_response(response: Any, seq: int) -> Any:
    if not isinstance(response, dict) or "ok" not in response:
        raise FrameError(f"malformed rpc response: {response!r}")
    if response.get("seq") != seq:
        raise FrameError(
            f"rpc response seq {response.get('seq')} != request seq {seq}"
        )
    if response["ok"]:
        return response.get("result")
    err = response.get("error") or {}
    raise RemoteError(
        str(err.get("type", "RuntimeError")), str(err.get("msg", ""))
    )
