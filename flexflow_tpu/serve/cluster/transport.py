"""Replica RPC transport — length-prefixed binary frames over localhost
TCP (or an in-process loopback), no dependencies beyond the stdlib.

ROADMAP item 1: the :class:`~.replica.Replica` surface was deliberately
shaped for per-host processes — this module is the wire under it. The
protocol is a deliberately small, deterministic binary codec rather
than pickle (never unpickle from a socket) or JSON (which cannot carry
KV page bytes without base64 inflation):

* **Frame** = ``MAGIC(2) | VERSION(1) | LENGTH(4, big-endian) |
  PAYLOAD(LENGTH bytes)``. Every read is bounded: a malformed magic,
  an unknown version, an oversized length or a short body raise a
  typed :class:`TransportError` — a corrupt peer can never hang a
  ``recv`` loop (socket reads additionally carry the RPC deadline).
* **Payload** = one self-describing value: None/bool/int/float/str/
  bytes/list/dict plus **numpy ndarrays** encoded as
  ``dtype | shape | raw C-order bytes``. Arrays are the load-bearing
  case: a migrated KV page's int8/int4 codes, its f32 quant scale
  rows and the generic decoder's position lines ride the codec
  BYTE-EXACT, so the PR-7/PR-8 bitwise page-migration contract holds
  across process boundaries (the int8/int4 coded pages already are a
  compact wire format — 4-8x fewer bytes than bf16, the same
  bandwidth argument EQuARX makes for quantized collectives).
* **RPC** = request ``{"seq": n, "method": str, "args": {...}}`` →
  response ``{"seq": n, "ok": bool, "result": ...}`` or
  ``{"seq": n, "ok": False, "error": {"type": ..., "msg": ...}}``.
  The client assigns ONE ``seq`` per logical call and reuses it across
  retries; the server caches recent responses by ``seq`` and replays
  a duplicate instead of re-executing — which is what makes retrying
  a ``step``/``submit`` whose RESPONSE was lost safe (at-most-once
  execution, at-least-once delivery).

Two transports implement the same ``call`` surface:

* :class:`LoopbackTransport` — in-process: every call is encoded to
  real frame bytes, decoded, dispatched against a local
  :class:`~.server.ReplicaServerCore`, and the response round-trips
  the codec the same way. Tier-1 tests run the WHOLE cluster through
  it to prove a loopback-transported cluster is BITWISE the in-process
  PR-8/9 cluster — the serialization layer is exercised end to end
  without sockets or subprocesses.
* :class:`SocketTransport` — localhost TCP to a subprocess replica
  server (``python -m flexflow_tpu.serve.cluster.server``). Blocking
  reads carry the per-RPC deadline as the socket timeout; connection
  loss marks the transport dead and the next call reconnects
  (``reconnects`` counted into ClusterStats).

Deadlines, bounded retries and exponential backoff live one level up
in :class:`~.remote.RemoteReplica` — the transports only move frames.
Transport-level fault injection (FaultPlan kinds drop/delay/
disconnect/partition, serve/cluster/faults.py) is consulted there too,
so both transports see identical scripted failures.
"""
from __future__ import annotations

import socket
import struct
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ...obs.tracer import NULL_TRACER

MAGIC = b"FT"
VERSION = 1
_HEADER = struct.Struct("!2sBI")
#: Hard cap on one frame's payload (a corrupted length prefix must not
#: make a reader try to allocate gigabytes). Generous: the largest real
#: frames are standby tree adoptions (many pages in one response).
MAX_FRAME_BYTES = 1 << 30


class TransportError(RuntimeError):
    """Base of every transport failure: framing/codec corruption,
    connection loss, deadline expiry, injected transport faults. The
    RemoteReplica retry loop treats exactly this hierarchy as
    retryable; remote APPLICATION exceptions (:class:`RemoteError`)
    are not transport errors and never retried (the server already
    executed)."""


class FrameError(TransportError):
    """Malformed or truncated frame / codec payload."""


class ConnectionLost(TransportError):
    """The peer closed or reset the connection mid-exchange."""


class DeadlineExceeded(TransportError):
    """No response within the RPC deadline."""


class RemoteError(RuntimeError):
    """The server executed the call and raised. Carries the remote
    exception's type name so callers can branch on semantics (e.g. an
    ``AssertionError`` from a remote ``check_no_leaks`` audit)."""

    def __init__(self, type_name: str, message: str):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.message = message


# ---------------------------------------------------------------------------
# value codec

_T_NONE = b"N"
_T_TRUE = b"T"
_T_FALSE = b"F"
_T_INT = b"i"       # 8-byte signed
_T_BIGINT = b"I"    # length-prefixed decimal string (hash chains etc.)
_T_FLOAT = b"f"     # 8-byte IEEE double
_T_STR = b"s"
_T_BYTES = b"b"
_T_LIST = b"l"
_T_DICT = b"d"
_T_NDARRAY = b"a"

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1


def encode_value(value: Any, out: bytearray) -> None:
    """Append one value's encoding to ``out``. Raises
    :class:`FrameError` on an unencodable type — the codec is closed
    over exactly the types the Replica surface speaks."""
    if value is None:
        out += _T_NONE
    elif value is True:
        out += _T_TRUE
    elif value is False:
        out += _T_FALSE
    elif isinstance(value, (int, np.integer)) and not isinstance(value, bool):
        v = int(value)
        if _I64_MIN <= v <= _I64_MAX:
            out += _T_INT
            out += struct.pack("!q", v)
        else:
            raw = str(v).encode("ascii")
            out += _T_BIGINT
            out += struct.pack("!I", len(raw))
            out += raw
    elif isinstance(value, (float, np.floating)):
        out += _T_FLOAT
        out += struct.pack("!d", float(value))
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out += _T_STR
        out += struct.pack("!I", len(raw))
        out += raw
    elif isinstance(value, (bytes, bytearray, memoryview)):
        raw = bytes(value)
        out += _T_BYTES
        out += struct.pack("!I", len(raw))
        out += raw
    elif isinstance(value, np.ndarray):
        dt = np.dtype(value.dtype).str.encode("ascii")
        body = np.ascontiguousarray(value).tobytes()
        out += _T_NDARRAY
        out += struct.pack("!I", len(dt))
        out += dt
        out += struct.pack("!I", len(value.shape))
        for dim in value.shape:
            out += struct.pack("!q", int(dim))
        out += struct.pack("!I", len(body))
        out += body
    elif isinstance(value, (list, tuple)):
        out += _T_LIST
        out += struct.pack("!I", len(value))
        for item in value:
            encode_value(item, out)
    elif isinstance(value, dict):
        out += _T_DICT
        out += struct.pack("!I", len(value))
        for k, v in value.items():
            encode_value(k, out)
            encode_value(v, out)
    else:
        raise FrameError(
            f"unencodable type {type(value).__name__!r} — the wire codec "
            "carries None/bool/int/float/str/bytes/list/dict/ndarray only"
        )


class _Reader:
    """Bounds-checked cursor over one payload — every read validates
    its length against the remaining bytes, so a truncated or corrupt
    payload raises :class:`FrameError` instead of over-reading."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise FrameError(
                f"truncated payload: need {n} bytes at offset {self.pos}, "
                f"have {len(self.buf) - self.pos}"
            )
        chunk = self.buf[self.pos:self.pos + n]
        self.pos += n
        return chunk

    def u32(self) -> int:
        return struct.unpack("!I", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack("!q", self.take(8))[0]


def _decode(r: _Reader) -> Any:
    tag = r.take(1)
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return r.i64()
    if tag == _T_BIGINT:
        return int(r.take(r.u32()).decode("ascii"))
    if tag == _T_FLOAT:
        return struct.unpack("!d", r.take(8))[0]
    if tag == _T_STR:
        return r.take(r.u32()).decode("utf-8")
    if tag == _T_BYTES:
        return r.take(r.u32())
    if tag == _T_NDARRAY:
        dt = np.dtype(r.take(r.u32()).decode("ascii"))
        ndim = r.u32()
        if ndim > 64:
            raise FrameError(f"ndarray with {ndim} dims — corrupt frame")
        shape = tuple(r.i64() for _ in range(ndim))
        body = r.take(r.u32())
        expect = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if len(body) != expect:
            raise FrameError(
                f"ndarray body {len(body)} bytes != shape {shape} × "
                f"{dt} ({expect} bytes)"
            )
        return np.frombuffer(body, dtype=dt).reshape(shape).copy()
    if tag == _T_LIST:
        return [_decode(r) for _ in range(r.u32())]
    if tag == _T_DICT:
        return {_decode(r): _decode(r) for _ in range(r.u32())}
    raise FrameError(f"unknown codec tag {tag!r}")


def decode_value(payload: bytes) -> Any:
    """Decode one payload; raises :class:`FrameError` on corruption or
    trailing garbage."""
    r = _Reader(payload)
    value = _decode(r)
    if r.pos != len(payload):
        raise FrameError(
            f"{len(payload) - r.pos} trailing bytes after payload"
        )
    return value


# ---------------------------------------------------------------------------
# framing

def encode_frame(value: Any) -> bytes:
    """One value → one wire frame (header + payload)."""
    body = bytearray()
    encode_value(value, body)
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(
            f"frame payload {len(body)} bytes exceeds MAX_FRAME_BYTES"
        )
    return _HEADER.pack(MAGIC, VERSION, len(body)) + bytes(body)


def decode_frame(frame: bytes) -> Any:
    """One complete wire frame → its value (header validated)."""
    if len(frame) < _HEADER.size:
        raise FrameError(
            f"short frame: {len(frame)} bytes < {_HEADER.size}-byte header"
        )
    magic, version, length = _HEADER.unpack_from(frame)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME_BYTES")
    body = frame[_HEADER.size:]
    if len(body) != length:
        raise FrameError(
            f"truncated frame: header says {length} bytes, got {len(body)}"
        )
    return decode_value(body)


def read_frame_from_socket(sock: socket.socket,
                           size_out: Optional[list] = None) -> Any:
    """Read exactly one frame off a socket whose timeout the caller has
    already set to the RPC deadline. EVERY failure mode is a typed
    raise — timeout (:class:`DeadlineExceeded`), peer close
    (:class:`ConnectionLost`), corrupt header (:class:`FrameError`) —
    a reader can never hang past its deadline or spin on garbage.
    ``size_out``, when given, receives the frame's total byte count
    (wire accounting without a re-encode)."""
    header = _recv_exact(sock, _HEADER.size)
    magic, version, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME_BYTES")
    if size_out is not None:
        size_out.append(_HEADER.size + length)
    return decode_value(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = bytearray()
    while len(chunks) < n:
        try:
            chunk = sock.recv(n - len(chunks))
        except socket.timeout as exc:
            raise DeadlineExceeded(
                f"no response within the socket deadline ({exc})"
            ) from exc
        except OSError as exc:
            raise ConnectionLost(f"socket read failed: {exc}") from exc
        if not chunk:
            raise ConnectionLost("peer closed the connection mid-frame")
        chunks += chunk
    return bytes(chunks)


# ---------------------------------------------------------------------------
# transports

class Transport:
    """One replica's RPC channel. ``stats`` is a ClusterStats or a
    zero-arg callable returning one (the callable-stats pattern) —
    wire byte counters land there on every exchange."""

    #: wall-clock retry backoff only makes sense when a real link can
    #: recover with time; the loopback fails or succeeds instantly.
    needs_backoff = False

    def __init__(self, stats=None):
        self._stats_src = stats
        self.bytes_sent = 0
        self.bytes_received = 0
        self.reconnects = 0
        # Observability (flexflow_tpu/obs): with a live tracer attached
        # (obs.attach_observability sets the owning RemoteReplica's
        # wire tracer here too) every frame exchange becomes a "wire"
        # event carrying its byte counts — the per-RPC half of the
        # ClusterStats wire_bytes_* counters.
        self.tracer = NULL_TRACER

    @property
    def stats(self):
        return (
            self._stats_src() if callable(self._stats_src)
            else self._stats_src
        )

    def _count(self, sent: int = 0, received: int = 0) -> None:
        self.bytes_sent += sent
        self.bytes_received += received
        st = self.stats
        if st is not None:
            st.wire_bytes_sent += sent
            st.wire_bytes_received += received

    def _count_reconnect(self) -> None:
        self.reconnects += 1
        st = self.stats
        if st is not None:
            st.reconnects += 1

    def call(self, seq: int, method: str, args: Dict[str, Any],
             deadline_s: float) -> Any:
        raise NotImplementedError

    def drop_connection(self) -> None:
        """Tear the link down (injected ``disconnect`` fault or a real
        error observed by the caller); the next :meth:`call`
        reconnects."""

    def close(self) -> None:
        pass


class LoopbackTransport(Transport):
    """In-process transport: requests and responses round-trip the REAL
    codec (encode → frame → decode on both legs) before/after hitting
    a local dispatch callable — ``dispatch(request_dict) ->
    response_dict`` (a :class:`~.server.ReplicaServerCore`). What the
    caller receives is exactly what a socket peer would have received,
    byte for byte, which is what lets tier-1 prove the transported
    cluster bitwise against the in-process one without sockets."""

    def __init__(self, dispatch: Callable[[Dict[str, Any]], Dict[str, Any]],
                 stats=None):
        super().__init__(stats)
        self.dispatch = dispatch
        self._connected = True

    def call(self, seq: int, method: str, args: Dict[str, Any],
             deadline_s: float) -> Any:
        if not self._connected:
            # mirror the socket behavior: a dropped link reconnects on
            # the next call (and the reconnect is counted)
            self._connected = True
            self._count_reconnect()
        request = encode_frame({"seq": seq, "method": method, "args": args})
        self._count(sent=len(request))
        response_frame = encode_frame(self.dispatch(decode_frame(request)))
        self._count(received=len(response_frame))
        tr = self.tracer
        if tr.enabled:
            tr.event("wire", method=method, sent=len(request),
                     received=len(response_frame))
        response = decode_frame(response_frame)
        return _unwrap_response(response, seq)

    def drop_connection(self) -> None:
        self._connected = False


class SocketTransport(Transport):
    """Localhost TCP transport to a subprocess replica server. One
    connection, serial request/response exchanges (the cluster drive
    loop is single-threaded); the per-call ``deadline_s`` becomes the
    socket timeout for both the send and the response read. A dead
    connection is remembered and re-dialed on the next call."""

    needs_backoff = True

    def __init__(self, host: str, port: int, stats=None,
                 connect_timeout_s: float = 10.0):
        super().__init__(stats)
        self.host = host
        self.port = int(port)
        self.connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._ever_connected = False

    def _connect(self) -> socket.socket:
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except OSError as exc:
            raise ConnectionLost(
                f"connect to {self.host}:{self.port} failed: {exc}"
            ) from exc
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ever_connected:
            self._count_reconnect()
        self._ever_connected = True
        return sock

    def call(self, seq: int, method: str, args: Dict[str, Any],
             deadline_s: float) -> Any:
        if self._sock is None:
            self._sock = self._connect()
        sock = self._sock
        frame = encode_frame({"seq": seq, "method": method, "args": args})
        size_out: list = []
        try:
            sock.settimeout(deadline_s)
            sock.sendall(frame)
            self._count(sent=len(frame))
            response = read_frame_from_socket(sock, size_out)
        except TransportError:
            self.drop_connection()
            raise
        except socket.timeout as exc:
            self.drop_connection()
            raise DeadlineExceeded(
                f"rpc {method!r} exceeded {deadline_s}s"
            ) from exc
        except OSError as exc:
            self.drop_connection()
            raise ConnectionLost(f"rpc {method!r} failed: {exc}") from exc
        self._count(received=size_out[0])
        tr = self.tracer
        if tr.enabled:
            tr.event("wire", method=method, sent=len(frame),
                     received=size_out[0])
        return _unwrap_response(response, seq)

    def drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self.drop_connection()


def _unwrap_response(response: Any, seq: int) -> Any:
    if not isinstance(response, dict) or "ok" not in response:
        raise FrameError(f"malformed rpc response: {response!r}")
    if response.get("seq") != seq:
        raise FrameError(
            f"rpc response seq {response.get('seq')} != request seq {seq}"
        )
    if response["ok"]:
        return response.get("result")
    err = response.get("error") or {}
    raise RemoteError(
        str(err.get("type", "RuntimeError")), str(err.get("msg", ""))
    )
