"""Replica RPC server — the remote end of the Replica surface.

Two layers:

* :class:`ReplicaServerCore` — a transport-agnostic dispatch table over
  ONE local :class:`~.replica.Replica`. Every RPC the cluster front-end
  speaks (step/heartbeat/submit/migration/tree adoption/audits) is one
  method here; both the in-process :class:`~.transport.LoopbackTransport`
  and the TCP accept loop below dispatch into the same table, so the
  loopback tier-1 tests exercise EXACTLY the code a subprocess replica
  runs. Responses are cached by request ``seq`` (bounded LRU): a client
  retrying a call whose RESPONSE was lost gets the cached answer
  replayed instead of a re-execution — ``step``/``submit`` stay
  at-most-once under at-least-once delivery.

* ``python -m flexflow_tpu.serve.cluster.server`` — a subprocess
  replica: builds a model + engine from a JSON spec (family, config
  preset + overrides, init seed, ServingConfig), binds a localhost TCP
  port (``--port 0`` picks one and prints it), and serves frames until
  a ``shutdown`` RPC or SIGTERM. Each server is its own single-process
  JAX runtime — which is exactly what sidesteps the CPU backend's
  missing multiprocess collectives: the cluster is N cooperating
  single-process engines, not one multi-process mesh. Determinism
  across processes comes from seeded param init on a pinned-threefry
  CPU backend (flexflow_tpu/__init__.py), so a subprocess replica's
  generation is bitwise the in-process build's.

**Envelope**: every state-bearing response carries ``telemetry`` (the
heartbeat payload — ``SchedulerStats`` snapshot + the queue-delay
inputs the router reads) and ``updates`` (per-request flushed state:
status/tokens/error/profile). The client-side mirror in
:mod:`.remote` is built ONLY from envelopes, so the front-end always
holds the flushed truth it needs for failover re-admission even after
the transport to this server dies.
"""
from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import socket
import sys
import threading
from typing import Any, Dict

from ...analysis.locks import make_lock
from ...logging_utils import get_logger
from ..batch_config import GenerationConfig
from ..request_manager import RequestStatus
from .replica import Replica
from .transport import (
    ConnectionLost,
    FrameError,
    TransportError,
    encode_frame,
    read_frame_from_socket,
)

_log = get_logger("serve")

#: responses replayed for duplicate seqs (idempotent client retries)
_SEQ_CACHE_SIZE = 32


def gen_to_wire(gen: GenerationConfig) -> Dict[str, Any]:
    d = dataclasses.asdict(gen)
    d["stop_token_ids"] = list(d["stop_token_ids"])
    return d


def gen_from_wire(d: Dict[str, Any]) -> GenerationConfig:
    d = dict(d)
    d["stop_token_ids"] = tuple(d.get("stop_token_ids", ()))
    return GenerationConfig(**d)


def profile_to_wire(profile) -> Dict[str, Any]:
    return dataclasses.asdict(profile)


class ReplicaServerCore:
    """Dispatch table over one local replica (see module docstring)."""

    def __init__(self, replica: Replica):
        self.replica = replica
        self._responses: "collections.OrderedDict[int, Dict]" = (
            collections.OrderedDict()
        )
        # At-most-once is only as strong as the atomicity of
        # cache-check → execute → cache-write. The subprocess socket
        # server is a serial loop, but an embedded (loopback) core can
        # be reached from two threads at once — the manager thread's
        # sync retry racing the transport worker still holding the
        # original attempt. Both would miss the seq cache and
        # double-execute (donated engine buffers make that a
        # deleted-array crash, not just a logic bug), so dispatch
        # serializes behind this lock and the loser replays the cache.
        self._dispatch_lock = make_lock("ReplicaServerCore._dispatch_lock")
        self.shutdown_requested = False

    # ------------------------------------------------------------------
    # envelope

    def _telemetry(self) -> Dict[str, Any]:
        rep = self.replica
        out = {
            "steps_taken": rep.steps_taken,
            "has_work": rep.has_work(),
            "load": rep.load(),
            "active": rep.active_requests(),
            "backlog_tokens": rep.backlog_tokens(),
            "token_rate": rep.token_rate(),
            "rate_samples": rep._rate_samples,
            "queue_delay_s": rep.queue_delay_s(),
            "hold_finished": sorted(rep.rm.hold_finished),
            "stats": rep.rm.stats.snapshot(),
        }
        tracer = rep.rm.tracer
        if tracer.enabled:
            # tracing on (obs/): this server's spans ship home inside
            # every state-bearing envelope — drained, so each event
            # crosses the wire once. Events are codec-safe flat dicts.
            events = tracer.buffer.drain()
            if events:
                out["trace_events"] = events
        return out

    def _request_state(self, req) -> Dict[str, Any]:
        return {
            "status": req.status.value,
            "tokens": [int(t) for t in req.tokens],
            "prompt_len": int(req.prompt_len),
            "n_sched": int(req.n_sched),
            "slot": int(req.slot),
            "pipeline_refs": int(req.pipeline_refs),
            "error": req.error,
            "profile": profile_to_wire(req.profile),
        }

    def _envelope(self, **extra) -> Dict[str, Any]:
        out = {
            "telemetry": self._telemetry(),
            "updates": {
                int(rid): self._request_state(req)
                for rid, req in self.replica.rm.requests.items()
            },
        }
        out.update(extra)
        return out

    # ------------------------------------------------------------------
    # methods

    def _m_hello(self, args):
        rep = self.replica
        pager = getattr(rep.engine, "pager", None)
        return self._envelope(
            index=rep.index,
            role=rep.role,
            paged=pager is not None,
            page_size=pager.page_size if pager is not None else 0,
        )

    def _m_heartbeat(self, args):
        return self._envelope()

    def _m_prefix_score(self, args):
        return {"score": self.replica.prefix_score(args["tokens"])}

    def _m_step(self, args):
        return self._envelope(progressed=self.replica.step())

    def _m_drain(self, args):
        self.replica.drain()
        return self._envelope()

    def _m_abandon(self, args):
        return self._envelope(dropped=self.replica.abandon())

    def _m_reset_rate(self, args):
        self.replica.reset_rate()
        return {}

    def _m_check_no_leaks(self, args):
        self.replica.check_no_leaks()
        return {"ok": True}

    def _m_submit(self, args):
        rid = self.replica.rm.submit(
            [int(t) for t in args["tokens"]], gen_from_wire(args["gen"]),
            trace_id=args.get("trace_id"),
        )
        req = self.replica.rm.requests[rid]
        return self._envelope(rid=rid, prompt_len=int(req.prompt_len))

    def _m_hold_on_finish(self, args):
        self.replica.rm.hold_on_finish(int(args["rid"]))
        return {}

    def _m_release_held(self, args):
        self.replica.rm.release_held(int(args["rid"]))
        return self._envelope()

    def _m_migrate_out(self, args):
        """Gather a held, completed prefill's KV pages for the wire:
        every page's async device→host gather starts first, then ONE
        blocking harvest — the prefill→decode hand-off boundary, the
        same reviewed flush point as the in-process migration (the
        request completed, so the source pipeline is drained and no
        decode step waits on this). Codes, quant scale rows and
        generic-decoder pos lines ride back byte-exact."""
        import jax

        rep = self.replica
        rid = int(args["rid"])
        req = rep.rm.requests[rid]
        assert req.status is RequestStatus.COMPLETED, (
            f"migrate_out of request {rid} in state {req.status}"
        )
        assert req.pipeline_refs == 0, "migrate_out with dispatches in flight"
        assert req.slot >= 0, "migrate_out after the slot was released"
        eng = rep.engine
        n_pages = eng.pager.pages_for(req.prompt_len)
        row = eng.pager.table[req.slot]
        handles = [eng.fetch_page(int(row[j])) for j in range(n_pages)]
        # ffcheck: disable=FF107 -- transport migration flush point: the prefill→decode hand-off harvests its page gathers in ONE blocking sync before serialization — the request is COMPLETED (source pipeline drained) and the destination has not seen it, so no decode step anywhere waits on this transfer
        values = jax.device_get(handles)
        return {
            "tokens": [int(t) for t in req.tokens],
            "prompt_len": int(req.prompt_len),
            "prompt": req.prompt,
            "page_size": eng.pager.page_size,
            "pages": [dict(v) for v in values],
        }

    def _m_migrate_in(self, args):
        """Adopt an externally prefilled request + upload its migrated
        pages — transactional: any upload failure rolls the adoption
        back (``RequestManager.rollback_adopt``) before the error goes
        back over the wire, so nothing leaks on this side and the
        source keeps holding."""
        rep = self.replica
        eng = rep.engine
        if int(args["page_size"]) != eng.pager.page_size:
            raise ValueError(
                "prefill and decode pools disagree on page_size "
                f"({args['page_size']} vs {eng.pager.page_size})"
            )
        rid = rep.rm.adopt_prefilled(
            [int(t) for t in args["tokens"]],
            int(args["prompt_len"]),
            gen_from_wire(args["gen"]),
            prompt_text=args.get("prompt", ""),
            trace_id=args.get("trace_id"),
        )
        if rid is None:
            return self._envelope(rid=None)
        try:
            row = eng.pager.table[rep.rm.requests[rid].slot]
            for j, payload in enumerate(args["pages"]):
                eng.upload_page(int(row[j]), payload)
        except Exception:
            rep.rm.rollback_adopt(rid)
            raise
        return self._envelope(rid=rid)

    def _m_export_tree(self, args):
        return {"entries": self.replica.export_prefix_tree()}

    def _m_import_tree(self, args):
        return self._envelope(
            adopted=self.replica.import_prefix_tree(args["entries"])
        )

    def _m_shutdown(self, args):
        self.shutdown_requested = True
        return {"ok": True}

    # ------------------------------------------------------------------
    # dispatch

    def dispatch(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """One decoded request frame → one response dict. Never raises:
        application exceptions become ``ok=False`` error responses
        (and are cached like successes — a retried failing call must
        not re-execute either). Thread-safe: concurrent callers
        serialize behind the per-core dispatch lock, so a duplicate
        seq racing the original executes exactly once and replays the
        cached response."""
        if not isinstance(request, dict) or "method" not in request:
            return {
                "seq": None, "ok": False,
                "error": {"type": "FrameError",
                          "msg": f"malformed rpc request: {request!r}"},
            }
        seq = request.get("seq")
        with self._dispatch_lock:
            if seq is not None and seq in self._responses:
                self._responses.move_to_end(seq)
                return self._responses[seq]
            method = str(request["method"])
            handler = getattr(self, f"_m_{method}", None)
            if handler is None:
                response: Dict[str, Any] = {
                    "seq": seq, "ok": False,
                    "error": {"type": "FrameError",
                              "msg": f"unknown rpc method {method!r}"},
                }
            else:
                try:
                    response = {
                        "seq": seq, "ok": True,
                        "result": handler(request.get("args") or {}),
                    }
                except Exception as exc:
                    response = {
                        "seq": seq, "ok": False,
                        "error": {"type": type(exc).__name__,
                                  "msg": str(exc)},
                    }
            if seq is not None:
                self._responses[seq] = response
                while len(self._responses) > _SEQ_CACHE_SIZE:
                    self._responses.popitem(last=False)
            return response


# ---------------------------------------------------------------------------
# subprocess entry point

_DTYPES = {"float32": "float32", "bfloat16": "bfloat16", "float16": "float16"}


def serving_config_from_dict(spec: Dict[str, Any]):
    """ServingConfig from a JSON-able dict (``cache_dtype`` by name,
    tuple fields from lists)."""
    import jax.numpy as jnp

    from ..engine import ServingConfig

    kw = dict(spec)
    if "cache_dtype" in kw:
        name = str(kw["cache_dtype"])
        if name not in _DTYPES:
            raise ValueError(
                f"unknown cache_dtype {name!r} (expected one of "
                f"{sorted(_DTYPES)})"
            )
        kw["cache_dtype"] = jnp.dtype(_DTYPES[name])
    for field in ("fused_decode", "sanitizers", "replica_endpoints"):
        if field in kw:
            kw[field] = tuple(kw[field])
    return ServingConfig(**kw)


def build_replica_from_spec(spec: Dict[str, Any]) -> Replica:
    """Build the served replica from a JSON spec::

        {"family": "llama",
         "config": {"preset": "tiny", "dtype": "float32", ...overrides},
         "seed": 0, "gen_seed": 0, "index": 0, "role": "mixed",
         "serving": {...ServingConfig kwargs...}}

    Param init is seeded (``jax.random.PRNGKey(seed)``), so every
    process that builds the same spec holds byte-identical weights —
    the cross-process analog of PR-8's params-shared-by-reference."""
    import jax
    import jax.numpy as jnp

    family = spec.get("family", "llama")
    if family != "llama":
        raise ValueError(
            f"replica server spec supports family='llama' for now "
            f"(got {family!r}) — other families ride once checkpoint "
            "loading lands in the spec"
        )
    from ...models import llama

    conf = dict(spec.get("config") or {})
    preset = conf.pop("preset", "tiny")
    dtype = jnp.dtype(_DTYPES.get(str(conf.pop("dtype", "float32")),
                                  "float32"))
    maker = getattr(llama.LLaMAConfig, preset, None)
    if maker is None:
        raise ValueError(f"unknown llama config preset {preset!r}")
    cfg = maker(dtype=dtype)
    if conf:
        cfg = dataclasses.replace(cfg, **conf)
    params = llama.init_params(jax.random.PRNGKey(int(spec.get("seed", 0))),
                               cfg)
    serving = serving_config_from_dict(dict(spec.get("serving") or {}))
    replica = Replica.build(
        int(spec.get("index", 0)), llama, cfg, params, serving,
        role=str(spec.get("role", "mixed")),
        eos_token_id=spec.get("eos_token_id"),
        seed=int(spec.get("gen_seed", 0)),
    )
    if spec.get("trace"):
        # observability: trace into a local buffer that _telemetry
        # drains into every envelope — the client stitches this
        # subprocess's spans into the cluster-wide timeline
        from ...obs import attach_observability

        attach_observability(replica)
    return replica


def serve_forever(core: ReplicaServerCore, port: int = 0,
                  host: str = "127.0.0.1",
                  announce=None) -> None:
    """Accept loop: one client at a time, frames in / frames out in
    ARRIVAL order. The multiplexing client may PIPELINE many tagged
    requests onto the connection before reading anything — the
    serial read→dispatch→respond loop composes with that unchanged,
    because every response carries its request's ``seq`` call-tag and
    the client demultiplexes (the replica executes one RPC at a time
    either way; it owns a single JAX runtime). A malformed frame — or
    a client that vanished mid-exchange (e.g. its deadline expired and
    it dropped the connection) — closes that CONNECTION with a logged
    warning and the server keeps accepting — a corrupt, hostile or
    impatient client cannot take the replica down. Returns after a
    ``shutdown`` RPC."""
    listener = socket.create_server((host, port))
    actual_port = listener.getsockname()[1]
    if announce is not None:
        announce(actual_port)
    _log.warning("replica server %d listening on %s:%d",
                 core.replica.index, host, actual_port)
    try:
        while not core.shutdown_requested:
            conn, addr = listener.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                while not core.shutdown_requested:
                    try:
                        request = read_frame_from_socket(conn)
                    except ConnectionLost:
                        break  # client went away — accept the next one
                    except (FrameError, TransportError) as exc:
                        _log.warning(
                            "replica server %d: dropping connection on "
                            "malformed frame (%s)",
                            core.replica.index, exc,
                        )
                        break
                    try:
                        conn.sendall(encode_frame(core.dispatch(request)))
                    except OSError as exc:
                        # the client dropped the connection between our
                        # read and this write (deadline expiry on its
                        # side) — the response is already in the seq
                        # cache for the retry; keep serving
                        _log.warning(
                            "replica server %d: client went away "
                            "mid-response (%s)", core.replica.index, exc,
                        )
                        break
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
    finally:
        listener.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.serve.cluster.server",
        description="Serve one cluster replica over localhost TCP "
                    "(the multi-host end of ServingConfig."
                    "replica_transport='socket').",
    )
    parser.add_argument("--port", type=int, default=0,
                        help="TCP port to bind (0 = pick one and print it)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--spec", default=None,
                        help="replica spec JSON (see "
                             "build_replica_from_spec)")
    parser.add_argument("--spec-file", default=None,
                        help="path to a replica spec JSON file")
    args = parser.parse_args(argv)
    if bool(args.spec) == bool(args.spec_file):
        parser.error("exactly one of --spec / --spec-file is required")
    if args.spec_file:
        with open(args.spec_file) as f:
            spec = json.load(f)
    else:
        spec = json.loads(args.spec)
    core = ReplicaServerCore(build_replica_from_spec(spec))

    def announce(port):
        # the line the spawning test/driver parses to find the port
        print(f"FLEXFLOW_REPLICA_SERVER PORT={port}", flush=True)

    serve_forever(core, port=args.port, host=args.host, announce=announce)
    return 0


if __name__ == "__main__":
    sys.exit(main())
