"""Drain-based live reconfiguration — grow, shrink and re-pool a
serving cluster under traffic.

ROADMAP item 2b's autoscaler needs a MECHANISM before it can have a
policy; this module is that mechanism. Three first-class, journaled
operations over a live :class:`~.manager.ClusterManager`:

* :func:`scale_out` — build (or dial) a new replica, WARM it by
  shipping a donor's hot prefix subtrees through the PR-12
  export/import path (best-effort, like standby adoption: an
  unreachable donor means a cold join), then enter it into routing.
  The first request it sees can already be a prefix hit.
* :func:`begin_scale_in` / :func:`maybe_retire` — mark a replica
  DRAINING: the router immediately stops placing on it (the same
  health-callback exclusion a DOWN replica gets, without the failover
  — its requests are fine), its session pins drop through the SAME
  ``Router.drop_replica_sessions`` flow the DOWN path uses (they
  re-pin on survivors), and in-flight work finishes where it is (held
  prefills on a draining prefill replica still hand off through the
  existing page-migration queue). Once idle, the replica retires: its
  prefix tree ships to a survivor (so re-pinned sessions land WARM,
  not cold), ``check_no_leaks`` audits the pool, and it leaves the
  membership. :func:`scale_in` is the blocking convenience wrapper.
* :func:`set_pools` — flip replicas between the prefill/decode pools
  (or from all-mixed into a disaggregated split) under traffic.
  Placement-only: live requests keep decoding where they are; only
  future placements see the new pools. Flips that would strand held
  prefills (dropping disaggregation with migrations still queued) are
  rejected loudly — drain first.

Every operation journals a ``reconfig`` begin marker, applies its
mutations in memory, and journals a commit + the resulting membership
snapshot (``members`` record) — so a manager crash mid-operation
recovers as "the op never happened" and a crash after the commit
recovers the NEW membership (:meth:`ClusterManager.recover`).

Nothing here touches a device: reconfiguration is host-side membership
surgery plus the (already reviewed, FF107-suppressed) tree-export
harvest — the drive loop's dispatch pipeline never waits on it.
"""
from __future__ import annotations

from typing import Dict, Optional

from ...logging_utils import get_logger
from ..request_manager import TERMINAL_STATUSES
from .health import HealthState
from .replica import ROLES

_log = get_logger("serve")


# ---------------------------------------------------------------------------
# routing-table surgery shared by every operation


def rebuild_routing(cm) -> None:
    """Recompute the pools + routing table after a membership or role
    change, preserving session pins whose replica is still in the
    routing set (pins to removed/re-pooled replicas drop and re-pin on
    their next turn, exactly like the DOWN path)."""
    cm.prefill_pool = [r for r in cm.replicas if r.role == "prefill"]
    cm.decode_pool = [r for r in cm.replicas if r.role == "decode"]
    cm.disaggregated = bool(cm.prefill_pool)
    routing = cm.prefill_pool if cm.disaggregated else cm.replicas
    old = list(cm.router.replicas)
    old_sessions = dict(cm.router.sessions)
    cm.router.replicas[:] = routing
    cm._routing_pos = [cm.replicas.index(r) for r in routing]
    new_pos = {id(r): i for i, r in enumerate(routing)}
    cm.router.sessions = {
        k: new_pos[id(old[v])]
        for k, v in old_sessions.items()
        if 0 <= v < len(old) and id(old[v]) in new_pos
    }
    if routing:
        cm.router._rr_next %= len(routing)


def _journal_begin(cm, op: str, **detail) -> None:
    if cm.journal is not None:
        cm.journal.append_now(
            {"type": "reconfig", "op": op, "phase": "begin", **detail}
        )


def _journal_commit(cm, op: str, **detail) -> None:
    if cm.journal is not None:
        cm.journal.append(
            {"type": "reconfig", "op": op, "phase": "commit", **detail}
        )
        cm.journal.append_now(
            {"type": "members", "members": cm.members_snapshot()}
        )


# ---------------------------------------------------------------------------
# scale_out


def scale_out(
    cm,
    *,
    role: str = "mixed",
    endpoint: Optional[str] = None,
    warm: bool = True,
    replica=None,
) -> int:
    """Add one replica to the live cluster and return its position.

    The replica is built through the same factory :meth:`build` /
    :meth:`recover` used (in-process / loopback / socket — ``endpoint``
    names the server for socket transport), or taken prebuilt via
    ``replica``. With ``warm=True`` the first routable survivor with a
    non-empty prefix tree donates: its exported subtrees import into
    the newcomer BEFORE it enters routing, so it joins warm (the
    warm-standby path, reused). ``role`` must be consistent with the
    current pool structure (a disaggregated cluster takes
    prefill/decode, an all-mixed one takes mixed)."""
    if role not in ROLES:
        raise ValueError(f"unknown replica role {role!r} "
                         f"(expected one of {ROLES})")
    if cm.disaggregated and role == "mixed":
        raise ValueError(
            "scale_out(role='mixed') on a disaggregated cluster — pick "
            "'prefill' or 'decode' (mixed replicas cannot join split "
            "pools)"
        )
    if not cm.disaggregated and role != "mixed":
        raise ValueError(
            f"scale_out(role={role!r}) on a non-disaggregated cluster "
            "— use set_pools to split the pools first"
        )
    _journal_begin(cm, "scale_out", role=role, endpoint=endpoint or "")
    index = cm._next_replica_index
    if replica is None:
        rep = cm._make_member(index, role, endpoint)
    else:
        rep = replica
        rep.role = role
        index = rep.index
    cm._next_replica_index = max(cm._next_replica_index, index) + 1
    if getattr(rep, "is_remote", False):
        rep.bind_stats(lambda: cm.stats)
    rep.fault_injector = cm.fault_injector
    blocks = 0
    if warm:
        blocks = _warm_join(cm, rep)
    pos = len(cm.replicas)
    cm.replicas.append(rep)
    cm.health.add()
    if endpoint:
        cm._endpoints[index] = endpoint
    rebuild_routing(cm)
    cm.serving.replicas = len(cm.replicas)
    if cm.disaggregated:
        cm.serving.prefill_replicas = len(cm.prefill_pool)
        cm.serving.decode_replicas = len(cm.decode_pool)
    cm.stats.scale_outs += 1
    _journal_commit(cm, "scale_out", index=index, role=role)
    tr = cm.tracer
    if tr.enabled:
        tr.event("scale_out", replica=index, role=role, warm_blocks=blocks)
    _log.warning(
        "scale_out: replica %d joined at position %d (%s, %d prefix "
        "blocks warm, %d replicas now)",
        index, pos, role, blocks, len(cm.replicas),
    )
    return pos


def _warm_join(cm, rep) -> int:
    """Ship a donor's prefix tree into the joining replica (best
    effort: any failure means a cold join, capacity still grows)."""
    for pos, donor in enumerate(cm.replicas):
        if not cm._routable_pos(pos):
            continue
        try:
            entries = donor.export_prefix_tree()
            if not entries:
                continue
            return rep.import_prefix_tree(entries)
        except Exception as exc:
            _log.warning(
                "scale_out warm join: export from replica %d failed "
                "(%s) — trying the next donor", donor.index, exc,
            )
    return 0


# ---------------------------------------------------------------------------
# scale_in (drain → retire)


def begin_scale_in(cm, pos: int) -> None:
    """Mark the replica at ``pos`` DRAINING (non-blocking): the router
    places nothing new on it, its sessions re-pin on survivors, and
    the drive loop retires it (:func:`maybe_retire`) once its in-flight
    work finished or migrated."""
    if not 0 <= pos < len(cm.replicas):
        raise ValueError(f"scale_in position {pos} out of range "
                         f"(cluster has {len(cm.replicas)} replicas)")
    rep = cm.replicas[pos]
    if rep.index in cm._draining:
        raise ValueError(f"replica {rep.index} is already draining")
    survivors = [
        p for p in range(len(cm.replicas))
        if p != pos and cm._routable_pos(p)
    ]
    if not survivors:
        raise ValueError(
            "scale_in would leave no routable replica — grow the "
            "cluster (or recover the others) first"
        )
    if cm.disaggregated:
        pool = cm.prefill_pool if rep.role == "prefill" else cm.decode_pool
        rest = [
            r for r in pool
            if r is not rep and cm._routable_pos(cm.replicas.index(r))
        ]
        if not rest:
            raise ValueError(
                f"scale_in of replica {rep.index} would empty the "
                f"{rep.role} pool — set_pools (or scale_out) first"
            )
    _journal_begin(cm, "scale_in", index=rep.index)
    cm._draining.add(rep.index)
    # drain and DOWN re-home sessions through the SAME flow — the
    # draining replica's multi-turn sessions re-pin on survivors (and
    # land WARM once the retiree's tree ships at retire time)
    dropped = cm._drop_sessions(pos)
    tr = cm.tracer
    if tr.enabled:
        tr.event("drain_begin", replica=rep.index, sessions_dropped=dropped)
    _log.warning(
        "scale_in: replica %d draining (%d sessions re-pin; router "
        "places nothing new on it)", rep.index, dropped,
    )


def _drain_blockers(cm, pos: int) -> int:
    """Work still pinning the draining replica at ``pos``: live
    requests homed there plus queued migrations sourcing from it."""
    n = 0
    for cr in cm.requests.values():
        if (
            cr.rid is not None and cr.replica == pos
            and cr.status not in TERMINAL_STATUSES
        ):
            n += 1
    n += sum(1 for cid in cm._migration_queue
             if cm.requests[cid].replica == pos)
    return n


def maybe_retire(cm) -> bool:
    """Retire every draining replica whose work has drained (called
    from the manager's drive loop each cluster step). Returns True when
    a replica retired this call."""
    if not cm._draining:
        return False
    retired_any = False
    for pos in range(len(cm.replicas) - 1, -1, -1):
        rep = cm.replicas[pos]
        if rep.index not in cm._draining:
            continue
        if cm.health[pos].state is HealthState.DOWN:
            # died mid-drain: the failover/standby path owns it now and
            # the scale_in never commits (recovery replays the old
            # membership; the begin marker dangles harmlessly)
            cm._draining.discard(rep.index)
            _log.warning(
                "scale_in: draining replica %d went DOWN — the "
                "failover path owns it, the drain is void", rep.index,
            )
            continue
        if _drain_blockers(cm, pos) or rep.has_work():
            continue
        _retire(cm, pos)
        retired_any = True
    return retired_any


def _retire(cm, pos: int) -> None:
    rep = cm.replicas[pos]
    rep.drain()  # defensive: flush any tail the idle check raced with
    # re-home the retiree's prefix families on the least-loaded
    # survivor BEFORE it leaves: the sessions begin_scale_in re-pinned
    # land warm instead of re-seeding cold (best-effort, like standby
    # adoption)
    blocks = 0
    heirs = [
        r for p, r in enumerate(cm.replicas)
        if p != pos and cm._routable_pos(p)
    ]
    if heirs:
        heir = min(heirs, key=lambda r: (r.load(), r.index))
        try:
            entries = rep.export_prefix_tree()
            if entries:
                blocks = heir.import_prefix_tree(entries)
        except Exception as exc:
            _log.warning(
                "scale_in: prefix-tree hand-off from retiring replica "
                "%d failed (%s) — survivors re-seed cold",
                rep.index, exc,
            )
    # the retiring pool must audit clean — a drained replica with a
    # leaked page is a bug, not a tolerable degrade
    rep.check_no_leaks()
    assert not rep.rm.hold_finished, (
        f"retiring replica {rep.index} still holds slots "
        f"{rep.rm.hold_finished}"
    )
    # terminal requests that lived here re-home their RESULTS to the
    # cluster record (the retired object leaves the manager's reach)
    for cr in cm.requests.values():
        if cr.rid is None or cr.replica != pos:
            continue
        req = rep.rm.requests[cr.rid]
        cr._known = list(req.tokens)
        if cr.error is None:
            cr.error = req.error
        cr.finished = cr.error is None
        cr.rid = None
        cr.replica = None
    cm.replicas.pop(pos)
    cm.health.remove(pos)
    cm._draining.discard(rep.index)
    cm._failed_obs.discard(pos)
    cm._failed_obs = {p - 1 if p > pos else p for p in cm._failed_obs}
    for cr in cm.requests.values():
        if cr.replica is not None and cr.replica > pos:
            cr.replica -= 1
    rebuild_routing(cm)
    cm.serving.replicas = len(cm.replicas)
    if cm.disaggregated:
        cm.serving.prefill_replicas = len(cm.prefill_pool)
        cm.serving.decode_replicas = len(cm.decode_pool)
    cm._endpoints.pop(rep.index, None)
    cm._retired.append(rep)
    cm.stats.scale_ins += 1
    _journal_commit(cm, "scale_in", index=rep.index)
    tr = cm.tracer
    if tr.enabled:
        tr.event("retire", replica=rep.index, warm_blocks=blocks)
    _log.warning(
        "scale_in: replica %d retired leak-free (%d prefix blocks "
        "re-homed; %d replicas remain)",
        rep.index, blocks, len(cm.replicas),
    )


def scale_in(cm, pos: int, *, max_steps: int = 5000) -> None:
    """Blocking convenience: :func:`begin_scale_in` then drive the
    cluster until the replica retires. Bounded — a drain that makes no
    progress within ``max_steps`` raises instead of hanging (the PR-2
    never-hang contract extends to operations)."""
    rep = cm.replicas[pos]
    begin_scale_in(cm, pos)
    for _ in range(max_steps):
        if all(r.index != rep.index for r in cm.replicas):
            return
        cm.step()
    raise RuntimeError(
        f"scale_in of replica {rep.index} did not drain within "
        f"{max_steps} cluster steps "
        f"({_drain_blockers(cm, cm.replicas.index(rep))} blockers left)"
    )


# ---------------------------------------------------------------------------
# set_pools


def set_pools(cm, roles: Dict[int, str]) -> None:
    """Flip replica pool roles under traffic: ``roles`` maps cluster
    POSITIONS to their new role. The resulting assignment must be a
    valid pool structure (all mixed, or a non-empty prefill pool with a
    non-empty decode pool — the same invariant ``validate_cluster``
    enforces at construction). Placement-only: live requests finish
    where they run; only future placements see the new pools."""
    new_roles = [r.role for r in cm.replicas]
    for pos, role in roles.items():
        if not 0 <= int(pos) < len(cm.replicas):
            raise ValueError(f"set_pools position {pos} out of range")
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r} "
                             f"(expected one of {ROLES})")
        if cm.replicas[int(pos)].index in cm._draining:
            raise ValueError(
                f"set_pools on draining replica at position {pos} — "
                "let the drain finish (or is the drain the point?)"
            )
        new_roles[int(pos)] = role
    n_prefill = sum(1 for r in new_roles if r == "prefill")
    n_decode = sum(1 for r in new_roles if r == "decode")
    n_mixed = sum(1 for r in new_roles if r == "mixed")
    if n_prefill or n_decode:
        if n_mixed:
            raise ValueError(
                "set_pools would mix 'mixed' replicas with split "
                f"pools ({new_roles}) — assign every replica a pool"
            )
        if not (n_prefill and n_decode):
            raise ValueError(
                f"set_pools needs BOTH pools non-empty (got "
                f"{n_prefill} prefill / {n_decode} decode)"
            )
        if cm.serving.kv_layout != "paged":
            raise ValueError(
                "disaggregated pools need kv_layout='paged' (pages are "
                "the migration unit)"
            )
    else:
        # dropping disaggregation entirely: held prefills waiting on
        # the migration queue (or still prefilling toward it) would
        # strand — the queue only drains while the cluster is
        # disaggregated
        pending = cm._migration_queue or any(
            cr.phase == "prefill" and cr.rid is not None
            and cr.status not in TERMINAL_STATUSES
            for cr in cm.requests.values()
        )
        if pending:
            raise ValueError(
                "set_pools to all-mixed with prefill-phase requests "
                "still in flight would strand their page hand-offs — "
                "drain first"
            )
    _journal_begin(cm, "set_pools",
                   roles={int(p): r for p, r in roles.items()})
    for pos, role in roles.items():
        cm.replicas[int(pos)].role = role
    rebuild_routing(cm)
    cm.serving.prefill_replicas = len(cm.prefill_pool)
    cm.serving.decode_replicas = len(cm.decode_pool)
    cm.stats.pool_flips += 1
    _journal_commit(cm, "set_pools")
    tr = cm.tracer
    if tr.enabled:
        tr.event(
            "set_pools",
            prefill=len(cm.prefill_pool), decode=len(cm.decode_pool),
            mixed=sum(1 for r in cm.replicas if r.role == "mixed"),
        )
    _log.warning(
        "set_pools: %d prefill / %d decode / %d mixed",
        len(cm.prefill_pool), len(cm.decode_pool),
        sum(1 for r in cm.replicas if r.role == "mixed"),
    )
