"""Replica — one engine + scheduler pair inside a cluster.

A :class:`Replica` wraps one :class:`InferenceEngine` (its own mesh, its
own KV pool, its own prefix-cache radix tree) plus the
:class:`RequestManager` that drives it, and exposes exactly the surface
the front-end router needs to place work:

* ``prefix_score(tokens)`` — how many leading prompt tokens this
  replica's radix tree already holds (a READ-ONLY probe,
  ``PrefixCache.match_len``: scoring N replicas must not touch the
  N-1 losers' LRU state);
* ``queue_delay_s()`` — an admission-delay estimate: backlog tokens
  (undispatched prompt tokens of queued + prefilling requests, plus
  one token per decode row) over the replica's OBSERVED token rate
  (an EMA over ``SchedulerStats`` deltas, updated by :meth:`step`).
  Optimistically 0 before any rate is observed — SLO shedding
  (``ServingConfig.slo_queue_delay_s``) only ever acts on measured
  load, never on a cold start;
* ``load()`` — queued + active requests, the least-loaded tiebreak.

Replicas here are IN-PROCESS: on this CPU box every replica's mesh maps
onto the same device, which is what makes N-replica runs testable and
bit-exact-checkable anywhere. The API is deliberately shaped so a later
multi-host deployment can swap the in-process engine for a per-host
process behind the same five methods (score/delay/load/step/drain) —
the router never reaches past them.
"""
from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

from ...logging_utils import get_logger
from ..engine import InferenceEngine, ServingConfig
from ..request_manager import TERMINAL_STATUSES, RequestManager, RequestStatus

#: Pool roles under disaggregated serving (ServingConfig.prefill_replicas
#: / decode_replicas). "mixed" replicas serve both phases.
ROLES = ("mixed", "prefill", "decode")


class Replica:
    """One cluster member: engine + request manager + routing telemetry."""

    def __init__(self, index: int, rm: RequestManager, role: str = "mixed"):
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r} "
                             f"(expected one of {ROLES})")
        self.index = int(index)
        self.rm = rm
        self.role = role
        # token-rate EMA (tokens/sec the scheduler actually retired) —
        # the denominator of the queue-delay estimate
        self._rate = 0.0
        self._last_tokens = 0
        self._last_t: Optional[float] = None
        self._log = get_logger("serve")

    @classmethod
    def build(
        cls,
        index: int,
        model: Any,
        cfg: Any,
        params: Any,
        serving: ServingConfig,
        *,
        role: str = "mixed",
        mesh=None,
        devices: Optional[Sequence[Any]] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
    ) -> "Replica":
        """Construct a replica with its OWN mesh (and so its own TP
        group) over ``devices``. Params are shared by reference across
        replicas — on one host that is free; per-host processes would
        each load their own copy behind the same constructor."""
        if mesh is None:
            import jax

            from ...core.mesh import MachineSpec

            devices = list(devices or jax.devices()[:1])
            mesh = MachineSpec().make_mesh(devices)
        engine = InferenceEngine(model, cfg, params, serving, mesh)
        rm = RequestManager(
            engine, tokenizer=tokenizer, eos_token_id=eos_token_id,
            seed=seed,
        )
        return cls(index, rm, role=role)

    # ------------------------------------------------------------------
    # router-facing telemetry

    @property
    def engine(self) -> InferenceEngine:
        return self.rm.engine

    @property
    def stats(self):
        return self.rm.stats

    def prefix_score(self, tokens: Sequence[int]) -> int:
        """Leading prompt tokens this replica's radix tree would serve
        from cache (0 without prefix caching) — read-only."""
        pc = self.rm.prefix_cache
        if pc is None or len(tokens) < 2:
            return 0
        return pc.match_len(tokens)

    def active_requests(self) -> int:
        return sum(
            1 for r in self.rm.requests.values()
            if r.status not in TERMINAL_STATUSES
        )

    def load(self) -> float:
        """Least-loaded tiebreak: live requests (queued + in slots)."""
        return float(self.active_requests())

    def backlog_tokens(self) -> int:
        """Tokens of work already accepted but not yet dispatched:
        undispatched prompt tokens (queued requests count their whole
        prompt) plus one pending token per decode row."""
        n = 0
        for req in self.rm.requests.values():
            if req.status in TERMINAL_STATUSES:
                continue
            if req.status is RequestStatus.DECODING:
                n += 1
            else:  # PENDING / PREFILLING
                n += max(0, req.prompt_len - req.n_sched)
        return n

    def token_rate(self) -> float:
        """EMA tokens/sec this replica's scheduler has been retiring
        (prefill + decode tokens dispatched, from SchedulerStats)."""
        return self._rate

    def queue_delay_s(self) -> float:
        """Estimated seconds before NEW work would start executing:
        backlog over the observed token rate. 0 while no rate has been
        observed (cold replicas are never shed on a guess)."""
        if self._rate <= 0.0:
            return 0.0
        return self.backlog_tokens() / self._rate

    # ------------------------------------------------------------------
    # scheduling passthrough

    def has_work(self) -> bool:
        return bool(self.rm.pending) or self.active_requests() > 0 or bool(
            self.rm._inflight
        )

    def step(self) -> bool:
        """One scheduler step + a rate-EMA update from the stats delta."""
        progressed = self.rm.step()
        now = time.perf_counter()
        done = self.rm.stats.prefill_tokens + self.rm.stats.decode_tokens
        if self._last_t is not None:
            dt = now - self._last_t
            delta = done - self._last_tokens
            if dt > 0 and delta > 0:
                inst = delta / dt
                self._rate = (
                    inst if self._rate == 0.0
                    else 0.8 * self._rate + 0.2 * inst
                )
        self._last_t = now
        self._last_tokens = done
        return progressed

    def drain(self) -> None:
        self.rm.drain()

    # ------------------------------------------------------------------
    # audits

    def check_no_leaks(self) -> None:
        """Page-pool refcount audit for THIS replica (paged layout):
        slot tables + this replica's own radix tree must account for
        every reference — run by tests after migrations to prove no
        page leaked on either side of a hand-off."""
        pager = getattr(self.engine, "pager", None)
        if pager is None:
            return
        external = None
        if self.rm.prefix_cache is not None:
            external = self.rm.prefix_cache.page_refs()
        pager.check_no_leaks(external=external)
