"""Replica — one engine + scheduler pair inside a cluster.

A :class:`Replica` wraps one :class:`InferenceEngine` (its own mesh, its
own KV pool, its own prefix-cache radix tree) plus the
:class:`RequestManager` that drives it, and exposes exactly the surface
the front-end router needs to place work:

* ``prefix_score(tokens)`` — how many leading prompt tokens this
  replica's radix tree already holds (a READ-ONLY probe,
  ``PrefixCache.match_len``: scoring N replicas must not touch the
  N-1 losers' LRU state);
* ``queue_delay_s()`` — an admission-delay estimate: backlog tokens
  (undispatched prompt tokens of queued + prefilling requests, plus
  one token per decode row) over the replica's OBSERVED token rate
  (an EMA over ``SchedulerStats`` deltas, updated by :meth:`step`).
  Optimistically 0 before any rate is observed — SLO shedding
  (``ServingConfig.slo_queue_delay_s``) only ever acts on measured
  load, never on a cold start;
* ``load()`` — queued + active requests, the least-loaded tiebreak.

Replicas here are IN-PROCESS: on this CPU box every replica's mesh maps
onto the same device, which is what makes N-replica runs testable and
bit-exact-checkable anywhere. The API is deliberately shaped so a later
multi-host deployment can swap the in-process engine for a per-host
process behind the same five methods (score/delay/load/step/drain) —
the router never reaches past them.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from ...logging_utils import get_logger
from ..engine import InferenceEngine, ServingConfig
from ..request_manager import TERMINAL_STATUSES, RequestManager, RequestStatus

#: Pool roles under disaggregated serving (ServingConfig.prefill_replicas
#: / decode_replicas). "mixed" replicas serve both phases.
ROLES = ("mixed", "prefill", "decode")


class Replica:
    """One cluster member: engine + request manager + routing telemetry."""

    def __init__(self, index: int, rm: RequestManager, role: str = "mixed"):
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r} "
                             f"(expected one of {ROLES})")
        self.index = int(index)
        self.rm = rm
        self.role = role
        # token-rate EMA (tokens/sec the scheduler actually retired) —
        # the denominator of the queue-delay estimate. ``_rate_samples``
        # gates the estimate: a single (or stale) observation is not a
        # denominator — SLO shedding must never act on a cold rate.
        self._rate = 0.0
        self._rate_samples = 0
        self._last_tokens = 0
        self._last_t: Optional[float] = None
        # fault-injection harness (serve/cluster/faults.py): consulted
        # at the top of step(); injected latency accumulates here per
        # step and is read by the manager's health monitor.
        self.fault_injector = None
        self.steps_taken = 0
        self.injected_latency_s = 0.0
        self._log = get_logger("serve")

    @classmethod
    def build(
        cls,
        index: int,
        model: Any,
        cfg: Any,
        params: Any,
        serving: ServingConfig,
        *,
        role: str = "mixed",
        mesh=None,
        devices: Optional[Sequence[Any]] = None,
        tokenizer: Any = None,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        ssms: Sequence[Any] = (),
        spec: Any = None,
    ) -> "Replica":
        """Construct a replica with its OWN mesh (and so its own TP
        group) over ``devices``. Params are shared by reference across
        replicas — on one host that is free; per-host processes would
        each load their own copy behind the same constructor.

        ``ssms`` — (model, cfg, params) triples — are this replica's
        OWN SpecInfer draft mirrors: each builds a fresh SSM engine on
        the replica's mesh (draft params shared by reference like the
        target's) and the replica runs a SpecInferManager instead of a
        plain RequestManager. ``spec`` alone with
        ``SpecConfig.draft="early_exit"`` self-speculates with no
        mirror engines at all."""
        if mesh is None:
            import jax

            from ...core.mesh import MachineSpec

            devices = list(devices or jax.devices()[:1])
            mesh = MachineSpec().make_mesh(devices)
        engine = InferenceEngine(model, cfg, params, serving, mesh)
        early_exit = getattr(spec, "draft", "ssm") == "early_exit"
        if ssms or early_exit:
            from ..specinfer import SpecInferManager

            ssm_engines = [
                InferenceEngine(m, c, p, serving, mesh)
                for (m, c, p) in ssms
            ]
            rm: RequestManager = SpecInferManager(
                engine, ssm_engines, spec, tokenizer=tokenizer,
                eos_token_id=eos_token_id, seed=seed,
            )
        else:
            rm = RequestManager(
                engine, tokenizer=tokenizer, eos_token_id=eos_token_id,
                seed=seed,
            )
        return cls(index, rm, role=role)

    # ------------------------------------------------------------------
    # router-facing telemetry

    @property
    def engine(self) -> InferenceEngine:
        return self.rm.engine

    @property
    def stats(self):
        return self.rm.stats

    def prefix_score(self, tokens: Sequence[int]) -> int:
        """Leading prompt tokens this replica's radix tree would serve
        from cache (0 without prefix caching) — read-only."""
        pc = self.rm.prefix_cache
        if pc is None or len(tokens) < 2:
            return 0
        return pc.match_len(tokens)

    def active_requests(self) -> int:
        return sum(
            1 for r in self.rm.requests.values()
            if r.status not in TERMINAL_STATUSES
        )

    def load(self) -> float:
        """Least-loaded tiebreak: live requests (queued + in slots)."""
        return float(self.active_requests())

    def backlog_tokens(self) -> int:
        """Tokens of work already accepted but not yet dispatched:
        undispatched prompt tokens (queued requests count their whole
        prompt) plus one pending token per decode row."""
        n = 0
        for req in self.rm.requests.values():
            if req.status in TERMINAL_STATUSES:
                continue
            if req.status is RequestStatus.DECODING:
                n += 1
            else:  # PENDING / PREFILLING
                n += max(0, req.prompt_len - req.n_sched)
        return n

    def token_rate(self) -> float:
        """EMA tokens/sec this replica's scheduler has been retiring
        (prefill + decode tokens dispatched, from SchedulerStats)."""
        return self._rate

    def queue_delay_s(self) -> float:
        """Estimated seconds before NEW work would start executing:
        backlog over the observed token rate. 0 until at least two rate
        samples exist (cold replicas — first steps after start, after
        ``abandon``, or after probe re-admission — are never shed on a
        guess or a stale denominator, and the division cannot see a
        zero/near-zero rate)."""
        if self._rate_samples < 2 or self._rate <= 0.0:
            return 0.0
        return self.backlog_tokens() / self._rate

    def rate_snapshot(self) -> Dict[str, float]:
        """The DOCUMENTED read path over the rate-EMA internals, for
        telemetry consumers (the autotune TrafficEstimator, tests,
        dashboards) — everything the router's shed decision sees, as
        plain floats:

        * ``token_rate`` — the ``_rate`` EMA (tokens/sec; 0.8·prev +
          0.2·instantaneous per :meth:`step`, 0.0 while cold).
        * ``rate_samples`` — EMA updates folded in so far; the
          queue-delay gate opens at 2 (see :meth:`queue_delay_s`).
        * ``backlog_tokens`` — accepted-but-undispatched work.
        * ``queue_delay_s`` — backlog/rate, 0.0 while the gate is
          closed (cold replica, post-``reset_rate``, pre-envelope
          remote mirror) — consumers must treat 0.0 as "no estimate",
          NOT "idle".
        """
        return {
            "token_rate": float(self._rate),
            "rate_samples": float(self._rate_samples),
            "backlog_tokens": float(self.backlog_tokens()),
            "queue_delay_s": float(self.queue_delay_s()),
        }

    # ------------------------------------------------------------------
    # scheduling passthrough

    def has_work(self) -> bool:
        return bool(self.rm.pending) or self.active_requests() > 0 or bool(
            self.rm._inflight
        )

    def step(self) -> bool:
        """One scheduler step + a rate-EMA update from the stats delta.
        The fault injector (when attached) runs FIRST — an injected
        crash/transient raises here, at the replica surface, exactly
        where a remote replica's RPC failure would surface."""
        self.steps_taken += 1
        self.injected_latency_s = 0.0
        if self.fault_injector is not None:
            self.fault_injector.on_step(self)  # may raise InjectedFault
        progressed = self.rm.step()
        now = time.perf_counter()
        done = self.rm.stats.prefill_tokens + self.rm.stats.decode_tokens
        if self._last_t is not None:
            dt = now - self._last_t
            delta = done - self._last_tokens
            if dt > 0 and delta > 0:
                inst = delta / dt
                self._rate = (
                    inst if self._rate == 0.0
                    else 0.8 * self._rate + 0.2 * inst
                )
                self._rate_samples += 1
        self._last_t = now
        self._last_tokens = done
        return progressed

    def drain(self) -> None:
        self.rm.drain()

    # ------------------------------------------------------------------
    # fault tolerance (serve/cluster/health.py drives these)

    def reset_rate(self) -> None:
        """Forget the token-rate EMA (and its wall-clock anchor). Called
        when the replica goes DOWN so probe re-admission starts with a
        cold, optimistic estimate instead of a stale denominator — the
        dt across the outage would otherwise read as a near-zero rate
        and SLO-shed everything routed at the recovered replica."""
        self._rate = 0.0
        self._rate_samples = 0
        self._last_t = None
        self._last_tokens = (
            self.rm.stats.prefill_tokens + self.rm.stats.decode_tokens
        )

    def abandon(self) -> int:
        """Tear the scheduler state down after the replica was declared
        DOWN: drop every in-flight dispatch WITHOUT flushing (the device
        results are suspect and nothing may block on them), mark every
        live request ERROR (the manager has already captured their
        flushed tokens for recompute re-admission elsewhere), and
        release every slot's pages so a later probe re-admission starts
        from a clean pool. The prefix-cache radix tree is KEPT — its
        pages were written by completed, flushed dispatches and survive
        the fault, so a recovered replica rejoins with its prefix
        families warm. Returns the number of live requests dropped."""
        rm = self.rm
        rm._inflight.clear()
        rm._prev_dispatch_slots = set()
        rm.pending.clear()
        rm.hold_finished.clear()
        dropped = 0
        for req in rm.requests.values():
            req.pipeline_refs = 0
            req.inflight = 0
            if req.status not in TERMINAL_STATUSES:
                req.status = RequestStatus.ERROR
                req.error = "replica down — failed over"
                dropped += 1
        for slot, rid in enumerate(rm.slots):
            if rid is None:
                continue
            if rm._paged:
                rm._release_pages(slot)
            rm.slots[slot] = None
            rm.requests[rid].slot = -1
        self.reset_rate()
        tr = rm.tracer
        if tr.enabled:
            tr.event("abandon", dropped=dropped)
        return dropped

    # ------------------------------------------------------------------
    # warm-standby adoption (serve/cluster/manager.py _adopt_standby)

    def export_prefix_tree(self):
        """Serialize this replica's prefix radix tree — block keys plus
        page content bytes (``PrefixCache.export_tree``) — for a warm
        standby to adopt. Empty without prefix caching. Works on a
        circuit-broken replica: ``abandon`` keeps the tree (its pages
        hold only flushed completed writes)."""
        pc = self.rm.prefix_cache
        if pc is None:
            return []
        return pc.export_tree(fetch_page=self.engine.fetch_page)

    def import_prefix_tree(self, entries) -> int:
        """Adopt an exported tree into this replica's prefix cache
        (``PrefixCache.import_tree``); returns blocks adopted. 0
        without prefix caching — the standby still replaces the dead
        replica's capacity, just cold."""
        pc = self.rm.prefix_cache
        if pc is None:
            return 0
        return pc.import_tree(entries, upload_page=self.engine.upload_page)

    # ------------------------------------------------------------------
    # audits

    def check_no_leaks(self) -> None:
        """Page-pool refcount audit for THIS replica (paged layout):
        slot tables + this replica's own radix tree must account for
        every reference — run by tests after migrations to prove no
        page leaked on either side of a hand-off."""
        pager = getattr(self.engine, "pager", None)
        if pager is None:
            return
        external = None
        if self.rm.prefix_cache is not None:
            external = self.rm.prefix_cache.page_refs()
        pager.check_no_leaks(external=external)
