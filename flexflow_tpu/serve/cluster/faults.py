"""Deterministic fault injection for cluster serving.

MPK's argument (PAPERS.md) that runtime behavior must be testable
deterministically applies doubly to FAILURE paths: a failover that only
reproduces under a real outage is a failover that was never tested. So
the harness ships with the feature — a :class:`FaultPlan` scripts
exactly which replica fails, how, and at which replica-local step, and
the same plan replays the same scenario bit-for-bit (tests/
test_cluster_faults.py, bench ``serve_faults``).

Faults are wired at the :class:`~.replica.Replica` surface — the same
five-method boundary a multi-host deployment would put RPC behind, so
every injected failure looks to the manager exactly like a remote
replica failing:

=============  ==========================================================
kind           effect (at replica-local step ``step``, 1-based)
=============  ==========================================================
``crash``      every step from ``step`` on raises :class:`InjectedFault`
               — a permanently dead replica (probes keep failing)
``transient``  steps ``[step, step+count)`` raise, later steps succeed —
               a blip the health machine should absorb (or, past the
               failure threshold, a trip that PROBING later recovers)
``latency``    steps ``[step, step+count)`` report ``seconds`` of extra
               latency to the health monitor (no real sleep — the spike
               detector compares reported latencies, so the scenario is
               both deterministic and fast)
``migration``  the next ``count`` prefill→decode migrations OFF this
               replica raise :class:`InjectedMigrationFault` before any
               page moves (the manager retries with backoff, then falls
               back to recompute re-admission)
``oom``        at ``step``, up to ``pages`` free pages are taken out of
               the replica's pool for ``count`` steps — realistic page
               pressure that must surface as preemptions/held-admission,
               never as a leak or a hang. Call :meth:`FaultInjector.
               release_all` before auditing pools.
=============  ==========================================================

**Transport kinds** (PR 12) are injected one level lower, AT the RPC
transport (:meth:`FaultInjector.on_rpc`, consulted per RPC *attempt*
by :class:`~.remote.RemoteReplica`) — they only exist for remote
replicas (``ServingConfig.replica_transport`` "loopback"/"socket");
``ClusterManager.attach_faults`` rejects a plan aiming them at
in-process replicas with a loud error. ``step`` windows count the
replica's client-side step counter, same as the replica kinds:

=============  ==========================================================
kind           effect (during steps ``[step, step+count)``)
=============  ==========================================================
``drop``       the FIRST attempt of each RPC is lost (raises
               :class:`InjectedTransportFault`); retries succeed — a
               lossy link the deadline/retry/backoff machinery must
               absorb without a health observation (``rpc_retries``
               counts the cost)
``delay``      every RPC attempt carries ``seconds`` of reported extra
               latency (no real sleep); under the deadline it feeds the
               health monitor's latency-spike detector, at/over the
               deadline each attempt fails as DeadlineExceeded — a slow
               link degrades exactly like a stalled replica
``disconnect`` the first attempt of each RPC fails AND tears the
               connection down; the retry reconnects (``reconnects``
               counted) and succeeds
``partition``  EVERY attempt of every RPC fails — retries exhaust, the
               manager's health machine sees consecutive failures /
               heartbeat gaps and circuit-breaks the replica exactly
               like a crash (failover re-admission, probes after
               backoff)
=============  ==========================================================

**Process kinds** (PR 14) exercise REAL process death rather than
surface-level raises: ``sigkill`` sends SIGKILL to the registered
subprocess replica server pid at the replica's client-side step
(socket clusters only; ``FaultInjector.register_process`` wires the
pid) — the transport then fails against a genuinely dead peer — and
``manager_crash`` raises :class:`InjectedManagerCrash` out of
``ClusterManager.step`` at a scripted CLUSTER step, exactly once, so
tests/bench drop the manager there and recover it from the durable
journal (``ClusterManager.recover``) the way an operator would restart
a SIGKILL'd control plane.

``FaultPlan.random(seed, n_replicas)`` draws a reproducible plan for
chaos tests (replica kinds by default; ``include_transport=True`` /
``include_process=True`` widen the pool, or pass ``kinds`` explicitly);
``from_json``/``to_json`` round-trip plans for the CLI's
``--fault-plan`` flag and for bench scripts.
"""
from __future__ import annotations

import dataclasses
import json
import random
from typing import Dict, List, Optional, Sequence, Tuple

from ...logging_utils import get_logger
from .transport import TransportError

#: faults injected at the Replica surface (PR 9)
REPLICA_KINDS = ("crash", "transient", "latency", "migration", "oom")
#: faults injected at the RPC transport (PR 12, remote replicas only)
TRANSPORT_KINDS = ("drop", "delay", "disconnect", "partition")
#: PROCESS-level faults (PR 14): real process death, not surface-level
#: raises — "sigkill" SIGKILLs a registered subprocess replica server
#: at the replica's client-side step (socket clusters only; the RPC
#: layer then sees a REAL dead peer), "manager_crash" raises
#: :class:`InjectedManagerCrash` at a scripted CLUSTER step so the
#: caller can drop the manager and exercise journal recovery
#: (``ClusterManager.recover``) where a real SIGKILL would restart
#: the process.
PROCESS_KINDS = ("sigkill", "manager_crash")
KINDS = REPLICA_KINDS + TRANSPORT_KINDS + PROCESS_KINDS


class InjectedFault(RuntimeError):
    """An injected replica failure (crash/transient step exception)."""


class InjectedManagerCrash(InjectedFault):
    """The scripted manager death ("manager_crash"): raised out of
    ``ClusterManager.step`` at the scripted cluster step, exactly once
    — the harness's stand-in for kill -9 on the control plane."""


class InjectedMigrationFault(InjectedFault):
    """An injected prefill→decode migration failure."""


class InjectedTransportFault(InjectedFault, TransportError):
    """An injected TRANSPORT failure (drop/disconnect/partition) — a
    :class:`TransportError`, so the RemoteReplica retry loop treats it
    exactly like a real lost frame. ``kind`` lets the retry loop run
    the disconnect's reconnect semantics."""

    def __init__(self, message: str, kind: str):
        super().__init__(message)
        self.kind = kind


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scripted failure. ``step`` is REPLICA-LOCAL (that replica's
    Nth ``step()`` call), which keeps plans deterministic no matter how
    the cluster interleaves its replicas."""

    kind: str
    replica: int
    step: int
    count: int = 1        # transient/latency/oom: steps; migration: fails
    seconds: float = 1.0  # latency: injected extra seconds per step
    pages: int = 4        # oom: free pages taken out of the pool

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (expected one of "
                f"{KINDS})"
            )
        if self.replica < 0 or self.step < 1 or self.count < 1:
            raise ValueError(
                f"fault needs replica >= 0, step >= 1, count >= 1 "
                f"(got {self})"
            )


class FaultPlan:
    """An ordered, immutable set of :class:`Fault` — the whole scenario."""

    def __init__(self, faults: Sequence[Fault] = ()):
        self.faults: Tuple[Fault, ...] = tuple(faults)

    def __iter__(self):
        return iter(self.faults)

    def __len__(self):
        return len(self.faults)

    def __repr__(self):
        return f"FaultPlan({list(self.faults)!r})"

    def to_json(self) -> str:
        return json.dumps([dataclasses.asdict(f) for f in self.faults])

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        """Parse a plan from a JSON list of fault dicts, e.g.
        ``[{"kind": "crash", "replica": 1, "step": 20}]``."""
        spec = json.loads(text)
        if isinstance(spec, dict):
            spec = [spec]
        return cls([Fault(**f) for f in spec])

    @classmethod
    def random(
        cls,
        seed: int,
        n_replicas: int,
        *,
        horizon: int = 120,
        n_faults: Optional[int] = None,
        kinds: Sequence[str] = REPLICA_KINDS,
        include_transport: bool = False,
        include_process: bool = False,
    ) -> "FaultPlan":
        """A reproducible random plan: same seed → same plan, always
        (stdlib ``random.Random`` — no global RNG state touched).
        Defaults to the replica kinds — the PR-9 contract;
        ``include_transport=True`` adds the wire kinds (remote replicas
        only) and ``include_process=True`` adds the process kinds
        (sigkill needs a socket cluster + registered pids;
        manager_crash needs a recovery-capable driver) — or pass
        ``kinds`` explicitly for full control."""
        kinds = tuple(kinds)
        if include_transport:
            kinds += tuple(k for k in TRANSPORT_KINDS if k not in kinds)
        if include_process:
            kinds += tuple(k for k in PROCESS_KINDS if k not in kinds)
        rng = random.Random(seed)
        n = n_faults if n_faults is not None else rng.randint(1, 3)
        faults = []
        for _ in range(n):
            faults.append(Fault(
                kind=rng.choice(list(kinds)),
                replica=rng.randrange(n_replicas),
                step=rng.randint(2, max(2, horizon)),
                count=rng.randint(1, 4),
                seconds=round(rng.uniform(0.5, 3.0), 3),
                pages=rng.randint(1, 6),
            ))
        return cls(faults)


class FaultInjector:
    """Executes a :class:`FaultPlan` against live replicas.

    One injector serves the whole cluster: ``Replica.step`` calls
    :meth:`on_step` (which may raise, report latency, or squeeze the
    page pool) and ``migration.migrate_request`` calls
    :meth:`migration_fault`. ``fired`` records every injection
    ``(kind, replica, step)`` for tests and the bench timeline.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.fired: List[Dict[str, object]] = []
        self._logged_crash: set = set()
        # per-fault consumed migration failures (Fault is frozen)
        self._mig_left: Dict[int, int] = {
            i: f.count for i, f in enumerate(plan) if f.kind == "migration"
        }
        # replica index -> (release_at_step, [held pages], pager)
        self._held: Dict[int, Tuple[int, List[int], object]] = {}
        # PROCESS kinds: registered subprocess pids ("sigkill" targets)
        # + once-only firing state (a killed process stays killed; a
        # recovered manager must not immediately re-crash)
        self._pids: Dict[int, int] = {}
        self._sigkilled: set = set()
        self._mgr_fired: set = set()
        self._log = get_logger("serve")

    def register_process(self, replica_index: int, pid: int) -> None:
        """Register the OS pid serving ``replica_index`` so a scripted
        "sigkill" fault can kill the REAL process (socket clusters;
        the harness that spawned the server knows the pid)."""
        self._pids[int(replica_index)] = int(pid)

    # ------------------------------------------------------------------

    def _fire(self, fault: Fault, step_no: int, **extra) -> None:
        rec = {"kind": fault.kind, "replica": fault.replica,
               "step": int(step_no), **extra}
        self.fired.append(rec)
        self._log.debug("fault injected: %s", rec)

    def on_step(self, replica) -> None:
        """Consulted at the top of ``Replica.step``. May raise
        :class:`InjectedFault`; otherwise accumulates any scripted
        latency into ``replica.injected_latency_s`` and applies/releases
        page-pool pressure."""
        idx, sn = replica.index, replica.steps_taken
        self._tick_oom(replica)
        for fault in self.plan:
            if fault.replica != idx:
                continue
            if fault.kind == "crash" and sn >= fault.step:
                if idx not in self._logged_crash:
                    self._logged_crash.add(idx)
                    self._fire(fault, sn)
                raise InjectedFault(
                    f"injected crash (replica {idx}, step {sn})"
                )
            if (
                fault.kind == "transient"
                and fault.step <= sn < fault.step + fault.count
            ):
                self._fire(fault, sn)
                raise InjectedFault(
                    f"injected transient step exception (replica {idx}, "
                    f"step {sn})"
                )
            if (
                fault.kind == "latency"
                and fault.step <= sn < fault.step + fault.count
            ):
                replica.injected_latency_s += fault.seconds
                self._fire(fault, sn, seconds=fault.seconds)
            if fault.kind == "oom" and sn == fault.step:
                self._grab_pages(replica, fault)
            if (
                fault.kind == "sigkill"
                and sn >= fault.step
                and idx not in self._sigkilled
            ):
                import os as _os
                import signal as _signal

                pid = self._pids.get(idx)
                if pid is None:
                    raise RuntimeError(
                        f"sigkill fault for replica {idx} but no pid "
                        "was registered — call FaultInjector."
                        "register_process(index, pid) with the spawned "
                        "server's pid"
                    )
                self._sigkilled.add(idx)
                self._fire(fault, sn, pid=pid)
                self._log.warning(
                    "fault harness: SIGKILL pid %d (replica %d server)",
                    pid, idx,
                )
                _os.kill(pid, _signal.SIGKILL)
                # the step proceeds into its RPC against a genuinely
                # dead peer — deadlines/retries/health see REAL process
                # death, not a surface-level raise

    def on_cluster_step(self, manager) -> None:
        """Consulted at the top of ``ClusterManager.step``: a scripted
        "manager_crash" raises :class:`InjectedManagerCrash` exactly
        once at (or after) its cluster step — the caller abandons the
        manager and recovers from the journal."""
        sn = manager._step_counter
        for i, fault in enumerate(self.plan):
            if (
                fault.kind != "manager_crash"
                or sn < fault.step
                or i in self._mgr_fired
            ):
                continue
            self._mgr_fired.add(i)
            self._fire(fault, sn)
            raise InjectedManagerCrash(
                f"injected manager crash (cluster step {sn})"
            )

    def on_rpc(self, replica_index: int, step_no: int, method: str,
               attempt: int) -> float:
        """Consulted by :meth:`RemoteReplica._rpc` before every RPC
        *attempt* (``attempt`` 0 = the first try). May raise
        :class:`InjectedTransportFault`; returns the injected extra
        seconds of link delay (0.0 when none). ``step_no`` is the
        replica's CLIENT-side step counter — the same replica-local
        clock the replica kinds use, so mixed plans script one
        deterministic timeline."""
        delay = 0.0
        for fault in self.plan:
            if (
                fault.kind not in TRANSPORT_KINDS
                or fault.replica != replica_index
                or not (fault.step <= step_no < fault.step + fault.count)
            ):
                continue
            if fault.kind == "partition":
                if attempt == 0:
                    self._fire(fault, step_no, method=method)
                raise InjectedTransportFault(
                    f"injected partition (replica {replica_index}, step "
                    f"{step_no}, rpc {method})", "partition",
                )
            if fault.kind == "drop" and attempt == 0:
                self._fire(fault, step_no, method=method)
                raise InjectedTransportFault(
                    f"injected dropped frame (replica {replica_index}, "
                    f"step {step_no}, rpc {method})", "drop",
                )
            if fault.kind == "disconnect" and attempt == 0:
                self._fire(fault, step_no, method=method)
                raise InjectedTransportFault(
                    f"injected disconnect (replica {replica_index}, step "
                    f"{step_no}, rpc {method})", "disconnect",
                )
            if fault.kind == "delay":
                delay += fault.seconds
                if attempt == 0:
                    self._fire(fault, step_no, seconds=fault.seconds,
                               method=method)
        return delay

    def migration_fault(self, src) -> None:
        """Consulted at the top of ``migrate_request`` (before any
        adoption or page movement, so a failure leaves nothing to roll
        back on THIS side — exceptions later in the hand-off exercise
        the destination rollback path instead)."""
        for i, fault in enumerate(self.plan):
            if fault.kind != "migration" or fault.replica != src.index:
                continue
            if src.steps_taken >= fault.step and self._mig_left.get(i, 0) > 0:
                self._mig_left[i] -= 1
                self._fire(fault, src.steps_taken)
                raise InjectedMigrationFault(
                    f"injected migration failure (source replica "
                    f"{src.index})"
                )

    # ------------------------------------------------------------------
    # oom: hold free pages as an external owner for a step window

    def _grab_pages(self, replica, fault: Fault) -> None:
        pager = getattr(replica.engine, "pager", None)
        if pager is None:
            return  # dense layout: nothing to squeeze
        held: List[int] = []
        for _ in range(fault.pages):
            page = pager.take_free_page()
            if page is None:
                break
            pager.acquire(page)
            held.append(page)
        if held:
            self._held[replica.index] = (
                replica.steps_taken + fault.count, held, pager
            )
            self._fire(fault, replica.steps_taken, pages=len(held))

    def _tick_oom(self, replica) -> None:
        entry = self._held.get(replica.index)
        if entry is not None and replica.steps_taken >= entry[0]:
            self._release(replica.index)

    def _release(self, idx: int) -> None:
        release_at, held, pager = self._held.pop(idx)
        for page in held:
            pager.release_ref(page)

    def release_all(self) -> None:
        """Return every page the oom faults still hold — call before a
        pool leak audit (``check_no_leaks``) or at the end of a run
        whose window outlived the workload."""
        for idx in list(self._held):
            self._release(idx)

    def held_pages(self, idx: int) -> int:
        entry = self._held.get(idx)
        return len(entry[1]) if entry else 0
