"""Durable request journal — the crash-recovery substrate of the
cluster control plane.

PRs 9/12/13 made every *replica* expendable; this module makes the
ClusterManager itself expendable. It is an append-only, CRC-framed
record log (the PR-12 binary codec carries the payloads — no pickle,
no JSON) of everything the manager has PROMISED and everything it has
already DELIVERED:

* ``submit``   — one record per accepted request: prompt tokens,
  GenerationConfig, session id, cluster/trace id. Written (and
  flushed) before the request is placed, so a submission that returned
  a cluster id is never lost to a manager crash.
* ``tokens``   — flushed-token DELTAS, batched at the drive loop's
  existing flush sync point (one buffered write + one file flush per
  cluster step — never a per-token write, never a hot-path fsync).
  The journal only ever holds FLUSHED host truth, which is exactly
  what the recompute re-admission path replays.
* ``terminal`` — the request reached COMPLETED/ERROR (``error`` set
  for sheds/failures); recovery rehydrates these so ``result`` still
  answers for them after a restart.
* ``members``  — the CURRENT cluster membership snapshot (index /
  role / endpoint per replica), rewritten by every committed
  reconfiguration (scale_out / scale_in / set_pools), so a recovered
  manager rebuilds the membership the crash interrupted, not the one
  the config started with.
* ``reconfig`` — begin/commit markers around each reconfiguration (a
  begin without a commit recovers as "the op never happened": every
  mutation is applied in memory only between the two records and the
  commit carries the resulting members snapshot).

**Frame format**: ``MAGIC(2="FJ") | LENGTH(4, big-endian) |
CRC32(4, of the payload) | PAYLOAD`` where PAYLOAD is one codec value
(:func:`~.transport.encode_value`). A torn tail — a partial header, a
short payload, or a CRC mismatch from a crash mid-write — recovers by
TRUNCATION at the last whole record, never by corruption propagating
into replay (:func:`replay_journal` rewrites the file to the good
prefix before returning).

**Compaction**: terminal records retire their entries; once
``compact_threshold`` finished requests accumulate, :meth:`compact`
rewrites the log to the live set (members snapshot + one submit +
tokens record per unfinished request) through a temp file and an
atomic ``os.replace`` — the journal's size tracks in-flight work, not
run length.

Durability scope: ``flush`` pushes buffered frames into the OS page
cache (``file.flush``) — what survives a killed PROCESS, which is the
failure this PR recovers from (the tested contract: SIGKILL the
manager, restart from the journal, bitwise outputs). ``fsync=True``
additionally survives a host power loss at the price of a disk sync
per flush point; off by default and NOT part of the hot-path budget.
"""
from __future__ import annotations

import dataclasses
import os
import struct
import zlib
from typing import Any, Dict, List, Optional

from ...logging_utils import get_logger
from ..batch_config import GenerationConfig
from .transport import FrameError, decode_value, encode_value

MAGIC = b"FJ"
_HEADER = struct.Struct("!2sII")  # magic, payload length, payload crc32
#: one journal record's payload cap — a corrupt length prefix must not
#: make replay try to allocate gigabytes (prompts + flushed deltas are
#: small; the members snapshot is a few hundred bytes).
MAX_RECORD_BYTES = 1 << 26

_log = get_logger("serve")


def encode_record(record: Dict[str, Any]) -> bytes:
    """One record dict → one CRC-framed journal frame."""
    body = bytearray()
    encode_value(record, body)
    if len(body) > MAX_RECORD_BYTES:
        raise FrameError(
            f"journal record {len(body)} bytes exceeds MAX_RECORD_BYTES"
        )
    return _HEADER.pack(MAGIC, len(body), zlib.crc32(body)) + bytes(body)


class RequestJournal:
    """Append side of the log (see module docstring). ``stats`` is a
    ClusterStats or a zero-arg callable returning one (the
    callable-stats pattern) — record/byte/compaction counters land
    there so the bench can price journal overhead per request."""

    def __init__(
        self,
        path: str,
        *,
        compact_threshold: int = 256,
        fsync: bool = False,
        stats=None,
    ):
        self.path = path
        self.compact_threshold = int(compact_threshold)
        self.fsync = bool(fsync)
        self._stats_src = stats
        self._buf = bytearray()
        self._finished_since_compact = 0
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "ab")

    @property
    def stats(self):
        return (
            self._stats_src() if callable(self._stats_src)
            else self._stats_src
        )

    # ------------------------------------------------------------------
    # append side

    def append(self, record: Dict[str, Any]) -> None:
        """Buffer one record (framed + CRC'd). Nothing touches the file
        until :meth:`flush` — token deltas batch at the drive loop's
        flush sync point."""
        frame = encode_record(record)
        self._buf += frame
        st = self.stats
        if st is not None:
            st.journal_records += 1
            st.journal_bytes += len(frame)

    def flush(self) -> None:
        """Write buffered frames and push them to the OS (one
        ``file.flush`` per call — the per-cluster-step durability
        boundary; ``fsync=True`` additionally syncs the disk)."""
        if not self._buf:
            return
        self._f.write(self._buf)
        self._buf = bytearray()
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def append_now(self, record: Dict[str, Any]) -> None:
        """Append + flush in one call — submissions, terminals and
        reconfiguration records are durable the moment they return."""
        self.append(record)
        self.flush()

    def note_finished(self) -> None:
        self._finished_since_compact += 1

    def should_compact(self) -> bool:
        return self._finished_since_compact >= self.compact_threshold

    def compact(self, live_records: List[Dict[str, Any]]) -> None:
        """Rewrite the log to ``live_records`` (a members snapshot plus
        one submit + tokens record per unfinished request, built by the
        manager) through a temp file + atomic replace. Finished
        entries retire here — the log's size tracks in-flight work."""
        self.flush()
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for rec in live_records:
                f.write(encode_record(rec))
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._finished_since_compact = 0
        st = self.stats
        if st is not None:
            st.journal_compactions += 1
        _log.debug("journal compacted to %d live records",
                   len(live_records))

    def close(self) -> None:
        self.flush()
        self._f.close()


# ---------------------------------------------------------------------------
# replay side


@dataclasses.dataclass
class JournalEntry:
    """One request's journaled lifecycle: what was promised (prompt +
    GenerationConfig) and what was already delivered (flushed output
    tokens), plus its terminal state if it reached one."""

    cid: int
    tokens: List[int]               # the ORIGINAL prompt
    prompt_len: int
    gen: GenerationConfig
    session: Optional[object] = None
    prompt_text: str = ""
    flushed: List[int] = dataclasses.field(default_factory=list)
    terminal: bool = False
    error: Optional[str] = None


@dataclasses.dataclass
class JournalState:
    """What :func:`replay_journal` reconstructs: every journaled
    request, the last committed membership snapshot (None = the
    config's static membership), and what the scan observed."""

    entries: Dict[int, JournalEntry] = dataclasses.field(
        default_factory=dict
    )
    members: Optional[List[Dict[str, Any]]] = None
    records: int = 0
    truncated_bytes: int = 0

    @property
    def next_cid(self) -> int:
        return max(self.entries, default=0) + 1

    def unfinished(self) -> List[JournalEntry]:
        return [e for e in self.entries.values() if not e.terminal]


def _gen_from_record(d: Dict[str, Any]) -> GenerationConfig:
    d = dict(d)
    d["stop_token_ids"] = tuple(d.get("stop_token_ids", ()))
    return GenerationConfig(**d)


def _apply(state: JournalState, rec: Dict[str, Any]) -> None:
    kind = rec.get("type")
    if kind == "submit":
        cid = int(rec["cid"])
        state.entries[cid] = JournalEntry(
            cid=cid,
            tokens=[int(t) for t in rec["tokens"]],
            prompt_len=int(rec["prompt_len"]),
            gen=_gen_from_record(rec["gen"]),
            session=rec.get("session"),
            prompt_text=rec.get("prompt", ""),
        )
    elif kind == "tokens":
        entry = state.entries.get(int(rec["cid"]))
        if entry is not None:
            entry.flushed.extend(int(t) for t in rec["toks"])
    elif kind == "terminal":
        entry = state.entries.get(int(rec["cid"]))
        if entry is not None:
            entry.terminal = True
            entry.error = rec.get("error")
    elif kind == "members":
        state.members = list(rec["members"])
    # "reconfig" begin/commit markers carry no replayable state of their
    # own: a commit always writes the members snapshot alongside, and a
    # begin without a commit means the op never happened — replay
    # ignores both and keeps the last committed membership.
    # "autoscale" records (serve/autotune/policy.py) are likewise
    # replay-inert: each is the AUDIT record of one policy decision
    # (kind/reason/applied), while the applied op's own reconfig
    # begin→commit + members snapshot carry the recoverable state — so
    # a SIGKILL between a decision and its commit recovers exactly like
    # any torn reconfig: as if the decision never fired.


def replay_journal(path: str) -> JournalState:
    """Scan the journal, apply every whole record, and TRUNCATE the
    file at the first torn/corrupt frame (a crash mid-write leaves a
    partial tail; replay recovers the good prefix and the restarted
    manager appends from there). A missing file replays empty."""
    state = JournalState()
    if not os.path.exists(path):
        return state
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    good = 0
    why = None
    while pos < len(data):
        if pos + _HEADER.size > len(data):
            why = "partial header"
            break
        magic, length, crc = _HEADER.unpack_from(data, pos)
        if magic != MAGIC:
            why = f"bad magic {magic!r}"
            break
        if length > MAX_RECORD_BYTES:
            why = f"record length {length} exceeds MAX_RECORD_BYTES"
            break
        body = data[pos + _HEADER.size:pos + _HEADER.size + length]
        if len(body) != length:
            why = "torn payload"
            break
        if zlib.crc32(body) != crc:
            why = "crc mismatch"
            break
        try:
            rec = decode_value(body)
        except FrameError as exc:
            why = f"undecodable payload ({exc})"
            break
        _apply(state, rec)
        state.records += 1
        pos += _HEADER.size + length
        good = pos
    if good < len(data):
        state.truncated_bytes = len(data) - good
        _log.warning(
            "journal %s: torn tail (%s) — truncating %d bytes after "
            "%d whole records",
            path, why, state.truncated_bytes, state.records,
        )
        with open(path, "r+b") as f:
            f.truncate(good)
    return state


def live_records(
    members: Optional[List[Dict[str, Any]]],
    entries: List[JournalEntry],
) -> List[Dict[str, Any]]:
    """The compacted representation of the live state: the membership
    snapshot (when dynamic) plus one submit + one tokens record per
    unfinished request — replaying a compacted log is indistinguishable
    from replaying the full history."""
    from .server import gen_to_wire  # local import: server pulls heavy deps

    out: List[Dict[str, Any]] = []
    if members is not None:
        out.append({"type": "members", "members": list(members)})
    for e in entries:
        out.append({
            "type": "submit",
            "cid": e.cid,
            "tokens": list(e.tokens),
            "prompt_len": e.prompt_len,
            "gen": gen_to_wire(e.gen),
            "session": e.session,
            "prompt": e.prompt_text,
        })
        if e.flushed:
            out.append({
                "type": "tokens", "cid": e.cid, "toks": list(e.flushed),
            })
    return out
